"""Benchmarks against BASELINE.md's measurable configs.

Default run executes EVERY config — one JSON line each, the headline
LAST (so a driver that keeps the final line gets the headline) — and
also writes the full set to ``BENCH_CONFIGS.json``. ``--headline``
runs only the headline.

Headline: ImageNet ResNet-50 train-step throughput per chip, amp O2
semantics (bf16 compute / fp32 master params, BN stats fp32 with
compute-dtype apply — see docs/PERF.md), FusedSGD momentum inside a
``FlatOptimizer`` (the ``multi_tensor_apply`` tier —
``reference:apex/multi_tensor_apply/multi_tensor_apply.py:28-34``;
round-5 A/B in docs/PERF.md shows this wrap beats both per-leaf and
persistent-flat *inside the donated step*), synthetic data (the
reference's ``--prof`` style synthetic path).

``vs_baseline`` compares against NVIDIA's published DGX-A100
DeepLearningExamples ResNet-50 AMP number (~2470 imgs/sec per A100), the
"8xA100 amp-O2+DDP" north-star divided per chip; the reference repo itself
publishes no numbers (BASELINE.md). The line also carries ``mfu``
(model-flops-utilization from XLA's compiled cost analysis over the chip's
peak bf16 throughput), ``std_ms``, and ``step_ms``. Every headline/GPT
line additionally carries ``modeled_step_ms`` (the pyprof per-region
roofline lower bound of the exact program measured — the denominator
"how fast could this step possibly run") and ``comm_exposed_ms``
(modeled collective traffic the measured step failed to hide under
compute; 0.0 on single-chip programs) — see docs/OBSERVABILITY.md
"Step-time attribution" and ``scripts/attribute_step.py`` for the full
per-region breakdown.

Other configs:
  config 2 — FusedLayerNorm fwd+bwd, the library's auto-selected path
             (measured: XLA at every hidden size) vs forced-Pallas at a
             transformer shape and a large-hidden (32k) point
             (``reference:apex/normalization/fused_layer_norm.py:168-201``,
             ``reference:apex/contrib/csrc/layer_norm/ln_api.cpp:246``);
  config 3 — FusedAdam step time, per-leaf vs FlatOptimizer flat-buffer
             (``reference:apex/optimizers/fused_adam.py:90``);
  config 5 — GPT-small train step (Mosaic-compiled flash attention,
             vocab-parallel-shape loss) tokens/sec
             (``reference:apex/transformer/testing/standalone_gpt.py:1440``);
             anchored to 40% MFU — the published llm.c/nanoGPT-class
             utilization for GPT-2-124M-scale A100 training — over this
             chip's peak, using the compiled step's exact FLOP count;
  remat    — GPT-small train step swept over the activation-remat
             policies (none|selective|full|offload, apex_tpu/remat.py):
             ``gpt_remat_<policy>_step_ms`` + ``_temp_bytes`` trace the
             memory/compute frontier (docs/PERF.md "Remat & HBM");
  flash    — flash-attention seq-4096 fwd+bwd vs XLA attention;
  dp_ovl   — gradient-accumulation window + FusedAdam on the full DP
             mesh, bucketed end-of-window sync vs monolithic per-leaf
             psums (``dp_window_overlap_step_ms``; needs >= 2 devices,
             CPU ratio ~1.0 expected — docs/PERF.md "DP overlap + ZeRO");
  sp_ovl   — GPT-small TP=2 sequence-parallel fwd+bwd, ring-decomposed
             collective matmuls vs the fused all_gather/psum_scatter
             baseline (``gpt_sp_overlap_tokens_per_sec``; needs >= 2
             devices, emits a skip line otherwise — docs/PERF.md
             "Dependent-collective overlap");
  decode   — serving fast path: KV-cached autoregressive decode through
             the AOT ``ServingEngine`` (Pallas decode kernel, donated
             cache, fixed-shape sampling). Two legs:
             ``gpt_decode_tok_per_sec_b1`` (one active slot in a
             max_seqs=1 program — per-token latency) and
             ``gpt_decode_tok_per_sec_sat`` (every slot of the
             saturating grid active — per-chip throughput), each with
             HBM accounting and a prefill-vs-decode pyprof split;
             ``vs_baseline`` is measured over the HBM roofline
             (docs/SERVING.md "Reading bench_gpt_decode");
  paged    — the paged twin: ``gpt_decode_tok_per_sec_paged`` (the
             saturating grid through ``PagedServingEngine`` — block-pool
             cache, bounded-grid kernel; carries ``modeled_hbm_ratio``,
             the pyprof-modeled paged/dense attention-HBM gap) and
             ``gpt_decode_ttft_prefix_ms`` (shared-prefix admission vs
             the cold prefill it skips); engine config is the
             declarative ``BENCH_DECODE_CONFIGS`` table
             (docs/SERVING.md "Paged serving");
  spec     — speculative decoding: ``gpt_decode_tok_per_sec_spec``, a
             same-session A/B of the scheduler loop with and without
             ``speculate_k`` drafting on a repetitive-text workload
             (acceptance rate on the line; docs/SERVING.md
             "Speculative decoding");
  fast     — the compound ``fastpath`` preset (tp_comm_overlap +
             bucketed DP + ZeRO-1 backward-interleaved apply +
             selective remat + donation) through the hybrid trainer vs
             the same-mesh baseline config
             (``gpt_fast_tokens_per_sec``; needs >= 2 devices; CPU
             ratio ~1.0 documented — docs/PERF.md "Flagship tuning").
             The trainer-leg configs are the declarative
             ``BENCH_TRAIN_CONFIGS`` table, statically validated by
             ``scripts/check_bench_configs.py``.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# persistent compile cache: the bench programs are identical across runs,
# so a warm cache turns the ~10 min cold-compile wall into seconds and
# keeps the headline (printed last) inside any driver timeout. An
# operator-set JAX_COMPILATION_CACHE_DIR wins over the default (the
# dryrun wrapper in __graft_entry__ already respects it; ADVICE.md r5).
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 "/tmp/jaxcache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)

A100_AMP_RN50_IMGS_PER_SEC = 2470.0  # per-chip baseline (see docstring)

# peak-flops table + cost_analysis extraction + MFU math live in
# observability.costs (shared with StepReporter's perf/mfu gauge) — one
# source of truth for peak-flops numbers. Imported after the compile-cache
# config above (import triggers no backend use, but keep the config first).
from apex_tpu.observability.costs import (  # noqa: E402
    flops_budget, memory_budget as _memory_budget,
    peak_flops as _peak_flops)


def _mem_extra(compiled) -> dict:
    """``temp_bytes``/``peak_hbm_bytes`` extras for a bench line, from the
    compiled step's memory analysis — {} when the backend reports none, so
    emitted lines never carry fabricated zeros. Every ``bench_gpt_*``
    entry records these so the perf trajectory tracks memory next to
    step_ms."""
    budget = _memory_budget(compiled)
    if budget is None:
        return {}
    return {"temp_bytes": int(budget["temp_bytes"]),
            "peak_hbm_bytes": int(budget["peak_hbm_bytes"])}


def _attrib_extra(traced, step_ms) -> dict:
    """``modeled_step_ms``/``comm_exposed_ms``/``overlap_efficiency``
    extras for a bench line: the pyprof roofline lower bound of the
    traced step, the modeled communication the measured step failed to
    hide (0.0 on comm-free single-chip programs), and the fraction of
    modeled ICI bytes that rode under compute (absent on comm-free
    programs) — so bench rounds track *exposure*, not just step_ms (see
    docs/OBSERVABILITY.md "Step-time attribution"). {} when the model
    cannot price the program, so lines never carry fabricated numbers."""
    try:
        from apex_tpu.pyprof import attribute
        rep = attribute(traced, step_ms / 1e3)
        out = {"modeled_step_ms": round(rep.modeled_step_ms, 3),
               "step_time_ms": round(float(step_ms), 3)}
        if rep.comm_exposed_ms is not None:
            out["comm_exposed_ms"] = round(rep.comm_exposed_ms, 3)
        if rep.overlap_efficiency is not None:
            out["overlap_efficiency"] = round(rep.overlap_efficiency, 4)
        # the per-region breakdown rides ONLY into BENCH_HISTORY.jsonl
        # (popped from the printed line by _emit): perfwatch's
        # AttributionDiff names the region whose ms moved when a later
        # round regresses (docs/OBSERVABILITY.md "Performance
        # observatory")
        out["attribution"] = [
            {"region": r.name, "modeled_ms": round(r.modeled_ms, 4),
             **({} if r.measured_ms is None
                else {"measured_ms": round(r.measured_ms, 4)})}
            for r in rep.regions]
        return out
    except Exception:
        return {}


def _trace_and_compile(jitted, *args):
    """AOT ``(traced, compiled)`` of a jitted step: the traced stage keeps
    the jaxpr the pyprof attribution walks, ``.lower().compile()`` is the
    identical executable the timing loop runs."""
    traced = jitted.trace(*args)
    return traced, traced.lower().compile()


def _sync(out) -> None:
    """Drain the device queue (``jax.block_until_ready`` can return before
    execution finishes across a tunneled dispatch path) — the shared fence
    lives in :func:`apex_tpu.utils.timers.device_fence`."""
    from apex_tpu.utils.timers import device_fence
    device_fence(out)


def _timeit(fn, args, iters, warmup, chunk=10):
    """Mean per-iteration wall times (seconds), measured in chunks of
    ``chunk`` iterations with one fetch-sync per chunk (minus the measured
    fetch round-trip). Args are threaded through so donated/carried state
    stays realistic. Per-chunk timing (not per-iteration) matters: the
    host->device dispatch path may cross a network tunnel, so a sync per
    step would time the tunnel, not the chip — steps inside a chunk queue
    asynchronously and the chunk wall time is device-bound."""
    out = args
    for _ in range(warmup):
        out = fn(*out)
    _sync(out)
    rtt = min(_timed(lambda: _sync(out)) for _ in range(5))
    per_iter = []
    for _ in range(max(1, iters // chunk)):
        t0 = time.perf_counter()
        for _ in range(chunk):
            out = fn(*out)
        _sync(out)
        per_iter.append(max(time.perf_counter() - t0 - rtt, 1e-9) / chunk)
    return np.asarray(per_iter)


def _timed(f) -> float:
    t0 = time.perf_counter()
    f()
    return time.perf_counter() - t0


_RESULTS = []
_HISTORY = None


def _history():
    """The append target for the performance observatory
    (``BENCH_HISTORY.jsonl`` next to this script; ``APEX_BENCH_HISTORY``
    overrides the path, ``=off`` disables). Lazy and failure-proof —
    longitudinal bookkeeping must never break a bench run."""
    global _HISTORY
    if _HISTORY is None:
        try:
            from apex_tpu.observability.perfwatch import BenchHistory
            dest = os.environ.get(
                "APEX_BENCH_HISTORY",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_HISTORY.jsonl"))
            _HISTORY = False if dest.lower() in ("", "0", "off", "none") \
                else BenchHistory(dest)
        except Exception:
            _HISTORY = False
    # explicit False check: an EMPTY BenchHistory is len()-falsy
    return None if _HISTORY is False else _HISTORY


def _emit(metric, value, unit, vs_baseline, **extra):
    # the per-region attribution block and the drift numerator are
    # history-only: printed lines (and BENCH_CONFIGS.json) keep their
    # pre-observatory shape, the cross-run differ is the only consumer
    attribution = extra.pop("attribution", None)
    step_time_ms = extra.pop("step_time_ms", None)
    line = {"metric": metric, "value": round(float(value), 2), "unit": unit,
            "vs_baseline": (None if vs_baseline is None
                            else round(float(vs_baseline), 4))}
    line.update(extra)
    _RESULTS.append(line)
    print(json.dumps(line), flush=True)
    hist = _history()
    if hist is not None:
        try:
            extras = dict(extra)
            if attribution is not None:
                extras["attribution"] = attribution
            if step_time_ms is not None:
                extras["step_time_ms"] = step_time_ms
            # raw_value carries full precision: the printed 2-decimal
            # value quantizes away sub-0.5% deltas (the class of bug
            # that forced gpt_decode_goodput into percent), and the
            # regression detector needs them
            hist.record(metric, value, unit, vs_baseline,
                        raw_value=float(value),
                        run=os.environ.get("BENCH_RUN"),
                        source="bench", extras=extras)
        except Exception:
            pass


def bench_headline(iters=50, warmup=5):
    from apex_tpu.amp.scaler import DynamicLossScale, all_finite
    from apex_tpu.models import ResNet50, ResNetConfig
    from apex_tpu.optimizers import FlatOptimizer, FusedSGD

    batch, img = 256, 224
    cfg = ResNetConfig(num_classes=1000, compute_dtype=jnp.bfloat16)
    model = ResNet50(cfg)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt = FlatOptimizer(FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0 ** 12)
    ls = scaler.init()

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, img, img, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, batch))

    def loss_fn(params, bn_state, scale):
        logits, new_bn = model(params, bn_state, x, training=True)
        onehot = jax.nn.one_hot(labels, 1000)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return loss * scale, (loss, new_bn)

    # params/bn/opt-state/scale are donated: the step updates them in place,
    # which avoids a full-parameter copy per iteration on HBM.
    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2, 3)))
    def step(params, bn_state, opt_state, ls):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
            params, bn_state, ls.loss_scale)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        # unscale fused into the optimizer update (the reference passes
        # 1/scale straight into the fused kernels the same way,
        # reference:apex/optimizers/fused_sgd.py:100-226) — one fewer full
        # pass over the gradients than a separate scaler.unscale
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite,
                                     scale=1.0 / ls.loss_scale)
        return params, new_bn, opt_state, new_ls

    # model flops per step from the compiled executable (includes fwd+bwd+
    # optimizer); falls back to the analytic RN50 figure (2*4.1 GMACs fwd,
    # x3 for train) if the backend has no cost analysis. The compiled
    # executable is reused for the timing loop so the program compiles once.
    traced, compiled = _trace_and_compile(step, params, bn_state,
                                          opt_state, ls)
    flops_per_step = flops_budget(compiled)
    if flops_per_step is None:
        flops_per_step = 3 * 2 * 4.1e9 * batch

    times = _timeit(compiled, (params, bn_state, opt_state, ls),
                    iters, warmup)
    step_ms = float(np.mean(times) * 1e3)
    imgs_per_sec = batch / float(np.mean(times))
    mfu = flops_per_step / float(np.mean(times)) / _peak_flops()
    _emit("resnet50_train_imgs_per_sec_per_chip", imgs_per_sec, "imgs/sec",
          imgs_per_sec / A100_AMP_RN50_IMGS_PER_SEC,
          step_ms=round(step_ms, 3),
          std_ms=round(float(np.std(times) * 1e3), 3),
          mfu=round(mfu, 4), iters=iters,
          **_attrib_extra(traced, step_ms))


def _device_loop_ms(step_fn, init_carry, k=50, reps=5):
    """Time ``step_fn`` (carry -> carry) by scanning it ``k`` times inside
    ONE jitted call — per-call host dispatch crosses a tunnel here and can
    exceed a sub-ms kernel by 10x, so micro-kernels must loop on device.
    Returns (mean_ms, std_ms) over ``reps`` calls."""
    @jax.jit
    def many(carry):
        return jax.lax.scan(lambda c, _: (step_fn(c), None), carry,
                            None, length=k)[0]

    out = many(init_carry)
    _sync(out)
    rtt = min(_timed(lambda: _sync(out)) for _ in range(3))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = many(out)
        _sync(out)
        times.append(max(time.perf_counter() - t0 - rtt, 1e-9) / k)
    return (float(np.mean(times) * 1e3), float(np.std(times) * 1e3))


def bench_layernorm():
    """BASELINE config 2: LN fwd+bwd. Reports the library's AUTO-selected
    path (measured policy: XLA at every hidden size — see
    ``normalization/_pallas.py:prefer_pallas``) against the forced-Pallas
    kernel, at a transformer-typical shape and at the large-hidden regime
    the reference's ``fast_layer_norm`` targets."""
    from apex_tpu.normalization import fused_layer_norm_affine

    def measure(rows, hidden, use_pallas):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(rows, hidden), jnp.bfloat16)
        w = jnp.asarray(rng.randn(hidden), jnp.float32)
        b = jnp.asarray(rng.randn(hidden), jnp.float32)

        def loss(x, w, b):
            y = fused_layer_norm_affine(x, w, b, (hidden,),
                                        use_pallas=use_pallas)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        def step(carry):
            x, w, b = carry
            dx, dw, db = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
            # thread all three grads so nothing is dead-code-eliminated
            return 0.1 * dx, w + 1e-30 * dw, b + 1e-30 * db

        return _device_loop_ms(step, (x, w, b), k=100)

    for rows, hidden in [(8192, 4096), (1024, 32768)]:
        auto_ms, auto_std = measure(rows, hidden, None)
        pallas_ms, _ = measure(rows, hidden, True)
        # metric renamed from layernorm_fwd_bwd_ms (r5): the old name's
        # vs_baseline flipped meaning mid-history (xla_ms/pallas_ms on the
        # Pallas time -> pallas_ms/auto_ms on the auto time); the new name
        # pins the auto-path semantics so cross-round consumers can't
        # silently compare inverted ratios (ADVICE.md r5, BASELINE.md)
        _emit("layernorm_auto_fwd_bwd_ms", auto_ms, "ms",
              pallas_ms / auto_ms,
              rows=rows, hidden=hidden, selected_path="xla",
              pallas_ms=round(pallas_ms, 3), std_ms=round(auto_std, 3))


def bench_optimizer():
    """BASELINE config 3: FusedAdam step time over an RN50-sized param tree —
    per-leaf tree_map vs the persistent-flat FlatOptimizer tier (state stays
    flat across steps; grads arrive flat, as the grad-w.r.t.-flat training
    pattern produces). A second point stresses leaf-count pathology (1024
    tiny leaves), the regime ``multi_tensor_apply`` exists for."""
    from apex_tpu.models import ResNet50, ResNetConfig
    from apex_tpu.optimizers import FlatOptimizer, FusedAdam

    def run_per_leaf(params, grads, k=20):
        opt = FusedAdam(lr=1e-3)
        state = opt.init(params)

        def step(carry):
            p, s = carry
            return opt.step(grads, s, p)

        return _device_loop_ms(step, (params, state), k=k)

    def run_flat(params, k=20):
        opt = FlatOptimizer(FusedAdam(lr=1e-3))
        fstate = opt.init_flat(params)
        flat_grads = jnp.full_like(fstate.flat_params, 1e-4)

        def step(fstate):
            return opt.flat_step(flat_grads, fstate)

        return _device_loop_ms(step, fstate, k=k)

    model = ResNet50(ResNetConfig(num_classes=1000))
    params, _ = model.init(jax.random.PRNGKey(0))
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(jnp.shape(p), 1e-4, jnp.float32), params)
    leaf_ms, _ = run_per_leaf(params, grads)
    flat_ms, flat_std = run_flat(params)
    n_leaves = len(jax.tree_util.tree_leaves(params))

    # leaf-count pathology point, the regime multi_tensor_apply exists for.
    # 512 leaves (not 1024): a >1000-op per-leaf program once hit a
    # transient remote-compile INTERNAL error at the 590s budget (r4
    # verdict); guarded so a compile blowup cannot sink the whole run.
    many = {f"p{i}": jnp.full((1024,), 0.1, jnp.float32)
            for i in range(512)}
    many_grads = jax.tree_util.tree_map(
        lambda p: jnp.full_like(p, 1e-4), many)
    try:
        many_leaf_ms, _ = run_per_leaf(many, many_grads)
        many_flat_ms, _ = run_flat(many)
        many_leaf_ms = round(many_leaf_ms, 3)
        many_flat_ms = round(many_flat_ms, 3)
    except Exception:
        many_leaf_ms = many_flat_ms = None

    _emit("fused_adam_step_ms_flat", flat_ms, "ms", leaf_ms / flat_ms,
          per_leaf_ms=round(leaf_ms, 3), n_leaves=n_leaves,
          std_ms=round(flat_std, 3),
          leaves512_flat_ms=many_flat_ms,
          leaves512_per_leaf_ms=many_leaf_ms)


def _gpt_train_step(batch=8, seq=1024, hidden=768, layers=12, heads=12,
                    vocab=32768, remat_policy=None, **cfg_overrides):
    """The canonical config-5 GPT-small train step (flash attention,
    FusedAdam, dynamic loss scaling, donated buffers), AOT-compiled.
    Shared by :func:`bench_gpt` (the baseline row), every
    :func:`bench_gpt_remat` leg, and ``scripts/attribute_step.py`` (which
    passes ``compute_dtype``/``use_flash``/``layer_scan_unroll`` through
    ``cfg_overrides`` to build its XLA-countable validation twin of the
    SAME program), so neither the remat sweep nor the attribution
    instrument can drift from the baseline step. ``cfg_overrides`` are
    extra :class:`GPTConfig` fields laid over the bench defaults.
    Returns ``(cfg, args, wrapped, compiled, traced)``: ``wrapped(*args)``
    runs one step and threads the donated buffers back as the next
    call's args (the `_timeit` convention); ``traced`` is the
    pre-lowering stage the pyprof attribution
    (``modeled_step_ms``/``comm_exposed_ms`` columns) walks."""
    from apex_tpu.amp.scaler import DynamicLossScale, all_finite
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg_kw = dict(vocab_size=vocab, hidden_size=hidden,
                  num_layers=layers, num_attention_heads=heads,
                  max_position_embeddings=seq,
                  compute_dtype=jnp.bfloat16, remat_policy=remat_policy)
    cfg_kw.update(cfg_overrides)
    cfg = GPTConfig(**cfg_kw)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4)
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0 ** 12)
    ls = scaler.init()
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, vocab, (batch, seq)))

    @(lambda f: jax.jit(f, donate_argnums=(0, 1, 2)))
    def step(params, opt_state, ls, tokens):
        def loss_fn(p):
            return model.loss(p, tokens, tokens) * ls.loss_scale
        grads = jax.grad(loss_fn)(params)
        grads = scaler.unscale(ls, grads)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite)
        return params, opt_state, new_ls

    traced, compiled = _trace_and_compile(step, params, opt_state, ls,
                                          tokens)

    def wrapped(params, opt_state, ls, tokens):
        params, opt_state, ls = compiled(params, opt_state, ls, tokens)
        return params, opt_state, ls, tokens

    return cfg, (params, opt_state, ls, tokens), wrapped, compiled, traced


def bench_gpt(iters=20, warmup=3):
    """BASELINE config 5: GPT-small train step on one chip — times the
    Mosaic-compiled flash-attention kernels end to end (fwd+bwd), FusedAdam,
    dynamic loss scaling."""
    batch, seq = 8, 1024
    cfg, args, wrapped, compiled, traced = _gpt_train_step(batch=batch,
                                                           seq=seq)
    params = args[0]
    times = _timeit(wrapped, args, iters, warmup)
    tok_per_sec = batch * seq / float(np.mean(times))

    # anchor: 40% MFU — the published llm.c/nanoGPT-class utilization for
    # GPT-2-124M-scale A100 training — over THIS chip's peak. Model flops
    # use the standard analytic count (llm.c / PaLM-appendix convention:
    # 6N per token for the parameter matmuls fwd+bwd, plus 12*L*d_model*seq
    # for attention) — XLA's cost_analysis cannot be used here because the
    # Mosaic flash-attention custom calls report zero flops, deflating MFU
    # ~4x. vs_baseline > 1 means the step beats the 40%-MFU standard; the
    # reference publishes no GPT numbers (BASELINE.md) so a utilization
    # anchor is the defensible comparison.
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    flops_per_tok = (6.0 * n_params
                     + 12.0 * cfg.num_layers * cfg.hidden_size * seq)
    vs_anchor = tok_per_sec / (0.40 * _peak_flops() / flops_per_tok)
    mfu = tok_per_sec * flops_per_tok / _peak_flops()
    step_ms = float(np.mean(times) * 1e3)
    _emit("gpt_small_train_tokens_per_sec", tok_per_sec, "tokens/sec",
          vs_anchor, anchor="40pct_mfu_this_chip",
          mfu=round(float(mfu), 4),
          step_ms=round(step_ms, 3),
          std_ms=round(float(np.std(times) * 1e3), 3),
          batch=batch, seq=seq, **_mem_extra(compiled),
          **_attrib_extra(traced, step_ms))


def bench_gpt_remat(iters=10, warmup=2, batch=8, seq=1024, hidden=768,
                    layers=12, heads=12, vocab=32768):
    """Activation-remat memory/compute frontier A/B: the BASELINE config-5
    GPT-small train step swept over the four
    :class:`~apex_tpu.remat.RematPolicy` modes in one session — same
    shapes, same data, fresh params per leg, so the deltas isolate the
    policy. Per policy two lines ride BENCH_*.json:

    - ``gpt_remat_<policy>_step_ms`` (vs_baseline = none_ms/policy_ms,
      < 1 means the policy pays recompute FLOPs);
    - ``gpt_remat_<policy>_temp_bytes`` (vs_baseline =
      policy_temp/none_temp, the fraction of the activation working set
      kept resident).

    Every leg is built by :func:`_gpt_train_step` — the same constructor
    as the ``gpt_small_train_tokens_per_sec`` baseline row — so the sweep
    cannot drift from the program the baseline measures.

    Expected/asserted-in-tests ordering: temp_bytes none > selective >
    full — selective keeps only the registry-tagged GEMM/flash outputs,
    full keeps only the scan carry. ``offload`` compiles everywhere but
    its byte movement only means something where pinned_host is a real
    second memory space (TPU); read its step_ms there
    (docs/PERF.md "Remat & HBM")."""
    def measure(policy):
        _cfg, args, wrapped, compiled, traced = _gpt_train_step(
            batch=batch, seq=seq, hidden=hidden, layers=layers,
            heads=heads, vocab=vocab, remat_policy=policy)
        mem = _mem_extra(compiled)
        times = _timeit(wrapped, args, iters, warmup)
        ms = float(np.mean(times) * 1e3)
        mem.update(_attrib_extra(traced, ms))
        return ms, float(np.std(times) * 1e3), mem

    results = {}
    for policy in ("none", "selective", "full", "offload"):
        try:
            results[policy] = measure(policy)
        except Exception as e:  # one leg must not sink the sweep
            results[policy] = e
    base = results.get("none")
    base_ms = base[0] if isinstance(base, tuple) else None
    base_temp = (base[2].get("temp_bytes")
                 if isinstance(base, tuple) else None)
    for policy, r in results.items():
        if isinstance(r, Exception):
            _emit(f"gpt_remat_{policy}_step_ms", -1.0, "error", None,
                  error=str(r))
            continue
        ms, std, mem = r
        _emit(f"gpt_remat_{policy}_step_ms", ms, "ms",
              None if (base_ms is None or policy == "none")
              else base_ms / ms,
              std_ms=round(std, 3), batch=batch, seq=seq, iters=iters,
              **mem)
        if "temp_bytes" in mem:
            _emit(f"gpt_remat_{policy}_temp_bytes", mem["temp_bytes"],
                  "bytes",
                  None if (not base_temp or policy == "none")
                  else mem["temp_bytes"] / base_temp,
                  peak_hbm_bytes=mem.get("peak_hbm_bytes"))


# Declarative trainer-driven bench configs (fmengine-style: the config
# surface a tuned compound run needs, as data). Keys are REAL
# TrainConfig/ModelConfig/OptimizerConfig field names — statically
# validated by scripts/check_bench_configs.py (wired into tier-1), so a
# renamed flag breaks the check instead of silently dropping a leg back
# to defaults. "gpt_base" is the headline config-5 shape through the
# hybrid trainer; "gpt_fast" is the compound overlap preset laid over it
# — the same knobs TrainConfig.fastpath() applies (asserted equal in
# tests/test_fastpath.py, so this record cannot drift from the preset;
# fastpath() additionally turns on sequence_parallel + tp_comm_overlap
# when the mesh/jax can carry them).
BENCH_TRAIN_CONFIGS = {
    "gpt_base": {
        "model": {"name": "gpt", "vocab_size": 32768, "hidden_size": 768,
                  "num_layers": 12, "num_attention_heads": 12,
                  "max_position_embeddings": 1024},
        "optimizer": {"name": "adam", "lr": 1e-4, "weight_decay": 0.01},
        "opt_level": "O2",
        "half_dtype": "bfloat16",
    },
    "gpt_fast": {
        "model": {"remat_policy": "selective"},
        "optimizer": {"zero": 1},
        "ddp_bucket_bytes": "auto",
    },
}


def _train_config_from_spec(*specs, parallel=None, batch=None):
    """Merge declarative spec dicts (later wins, nested sections update)
    into a TrainConfig; unknown keys fail in the dataclass constructors
    (and statically in scripts/check_bench_configs.py)."""
    from apex_tpu.config import TrainConfig

    merged = {}
    for spec in specs:
        for k, v in spec.items():
            if isinstance(v, dict):
                merged.setdefault(k, {}).update(v)
            else:
                merged[k] = v
    if parallel is not None:
        merged["parallel"] = dict(parallel)
    if batch is not None:
        merged["batch"] = dict(batch)
    return TrainConfig.from_dict(merged)


def bench_gpt_fast(iters=10, warmup=2, mb=8, seq=1024, max_devices=None):
    """Compound fastpath A/B: the headline GPT-small shape through the
    hybrid trainer on the full device set, baseline config vs
    ``TrainConfig.fastpath()`` — tp_comm_overlap (mesh/jax permitting) +
    bucketed DP + ZeRO-1 with backward-interleaved per-bucket RS→math→AG
    + selective remat + donated state, the first time every overlap
    feature is compounded on the flagship bench. Same session, same
    mesh, same data; ``vs_baseline`` is fast/base tokens-per-sec (> 1
    means the compound config pays). ``bucket_bytes`` in the line is the
    roofline-resolved ``"auto"`` grid. On a CPU host mesh there is no
    ICI latency to hide, so ~1.0 is the expected and documented reading
    (docs/PERF.md "Flagship tuning") — the win must be read off a
    multi-chip run, where ``overlap_efficiency``/``comm_exposed_ms`` on
    this line say how much of the modeled traffic actually hid. Skipped
    below 2 devices (the compound config is comm machinery; single-chip
    deltas are the remat bench's job)."""
    from apex_tpu.training import GPTHybridTrainer
    from apex_tpu.transformer import parallel_state
    from apex_tpu.utils.compat import HAS_VMA

    if jax.device_count() < 2:
        _emit("gpt_fast_tokens_per_sec", -1.0, "skipped", None,
              error=f"needs >= 2 devices, have {jax.device_count()}")
        return

    # tp=2 only where the trainer can carry SP overlap (VMA jax) and a
    # data axis remains; otherwise all devices go to dp. ``max_devices``
    # caps the mesh (the tier-1 smoke test runs this leg on 2 of the 8
    # virtual devices — compile cost scales with mesh width on CPU)
    n_dev = jax.device_count()
    if max_devices is not None:
        n_dev = min(n_dev, int(max_devices))
    tp = 2 if (HAS_VMA and n_dev % 2 == 0 and n_dev >= 4) else 1
    dp, M = n_dev // tp, 1
    parallel = {"tensor_model_parallel_size": tp,
                "pipeline_model_parallel_size": 1}
    batch = {"global_batch_size": M * mb * dp, "micro_batch_size": mb}
    base_cfg = _train_config_from_spec(BENCH_TRAIN_CONFIGS["gpt_base"],
                                       parallel=parallel, batch=batch)
    fast_cfg = base_cfg.fastpath()
    vocab = base_cfg.model.vocab_size
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, (M, dp * mb, seq)))
    targets = jnp.asarray(rng.randint(0, vocab, (M, dp * mb, seq)))

    def measure(cfg):
        mesh = cfg.initialize_mesh(devices=jax.devices()[: tp * dp])
        try:
            tr = GPTHybridTrainer(cfg, mesh)
            state = tr.init_state(jax.random.PRNGKey(0))
            jitted = jax.jit(tr.train_step, donate_argnums=(0, 1, 2))
            traced, compiled = _trace_and_compile(jitted, *state, tokens,
                                                  targets)

            def wrapped(s0, s1, s2, ls, tokens, targets):
                _loss, s0, s1, s2, ls = compiled(s0, s1, s2, ls, tokens,
                                                 targets)
                return s0, s1, s2, ls, tokens, targets

            times = _timeit(wrapped, (*state, tokens, targets), iters,
                            warmup)
            tps = M * dp * mb * seq / float(np.mean(times))
            return tps, times, _mem_extra(compiled), traced, tr
        finally:
            parallel_state.destroy_model_parallel()

    base_tps, _, _, _, _ = measure(base_cfg)
    fast_tps, times, mem, traced, tr = measure(fast_cfg)
    step_ms = float(np.mean(times) * 1e3)
    _emit("gpt_fast_tokens_per_sec", fast_tps, "tokens/sec",
          fast_tps / base_tps, base_tps=round(base_tps, 2),
          step_ms=round(step_ms, 3),
          std_ms=round(float(np.std(times) * 1e3), 3),
          tp=tp, dp=dp, batch=mb, seq=seq,
          # the resolved compound config, real field names only —
          # scripts/check_bench_configs.py validates these keys against
          # the dataclasses, so a renamed flag cannot ride along silently
          config={
              "model": {
                  "remat_policy": fast_cfg.model.remat_policy,
                  "sequence_parallel": fast_cfg.model.sequence_parallel,
                  "tp_comm_overlap": fast_cfg.model.tp_comm_overlap},
              "optimizer": {"zero": 1},
              "ddp_bucket_bytes": tr.bucket_bytes,
          },
          **mem, **_attrib_extra(traced, step_ms))


def bench_gpt_sp_overlap(iters=10, warmup=2, batch=8, seq=1024,
                         hidden=768, layers=12, heads=12, vocab=32768):
    """Dependent-collective overlap A/B: GPT-small fwd+bwd tokens/sec at
    TP=2 with Megatron sequence parallelism, ring-decomposed collective
    matmuls (``tensor_parallel/collective_matmul.py``) vs the fused
    all_gather/psum_scatter baseline — same session, same mesh, same
    params, so the ratio isolates the exposed-ICI-latency win.
    ``vs_baseline`` is overlap/fused (>1 means the decomposition pays).
    Skipped (emitted with an error note) below 2 devices."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.transformer import parallel_state
    from apex_tpu.utils.compat import shard_map_unchecked

    if jax.device_count() < 2:
        _emit("gpt_sp_overlap_tokens_per_sec", -1.0, "skipped", None,
              error=f"needs >= 2 devices, have {jax.device_count()}")
        return

    mesh = parallel_state.initialize_model_parallel(
        tensor_model_parallel_size=2, devices=jax.devices()[:2])
    try:
        kw = dict(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                  num_attention_heads=heads, max_position_embeddings=seq,
                  compute_dtype=jnp.bfloat16, tensor_model_parallel_size=2,
                  sequence_parallel=True)
        tokens = jnp.asarray(
            np.random.RandomState(0).randint(0, vocab, (batch, seq)))
        base = GPTModel(GPTConfig(**kw))
        params = base.init(jax.random.PRNGKey(0))
        specs = base.param_specs(params)

        def measure(overlap):
            model = GPTModel(GPTConfig(**kw, tp_comm_overlap=overlap))

            def step_inner(params, tokens):
                loss, grads = jax.value_and_grad(
                    lambda p: model.loss(p, tokens, tokens))(params)
                # thread a trivial update so bwd isn't dead-code-eliminated
                new_p = jax.tree_util.tree_map(
                    lambda p, g: p - (1e-12 * g).astype(p.dtype),
                    params, grads)
                return new_p, jax.lax.pmean(
                    jax.lax.pmean(loss, "tensor"), "data")

            # 0.4.x check_rep cannot see through jax.vjp inside the
            # body (compat.shard_map_unchecked docstring); full
            # checking stays on under VMA jax
            smapped = shard_map_unchecked(step_inner, mesh=mesh,
                                in_specs=(specs, P()),
                                out_specs=(specs, P()))

            @(lambda f: jax.jit(f, donate_argnums=(0,)))
            def step(params, tokens):
                new_p, loss = smapped(params, tokens)
                return new_p, loss, tokens

            # fresh param buffers per variant: the donated originals are
            # consumed by the first call. AOT-compiled so the memory plan
            # (temp_bytes) is recorded alongside the timing.
            p0 = jax.tree_util.tree_map(jnp.copy, params)
            traced, compiled = _trace_and_compile(step, p0, tokens)

            def wrapped(params, loss, tokens):
                return compiled(params, tokens)

            times = _timeit(wrapped, (p0, jnp.float32(0.0), tokens),
                            iters, warmup)
            return (batch * seq / float(np.mean(times)), times,
                    _mem_extra(compiled), traced)

        fused_tps, _, _, _ = measure(False)
        overlap_tps, times, mem, traced = measure(True)
        step_ms = float(np.mean(times) * 1e3)
        # the attribution here prices the ring ppermute chains hop by hop
        # — comm_exposed_ms is the number the overlap machinery exists to
        # drive to zero (CPU hosts have no ICI; read it on a TPU run)
        _emit("gpt_sp_overlap_tokens_per_sec", overlap_tps, "tokens/sec",
              overlap_tps / fused_tps,
              fused_tps=round(fused_tps, 2), tp=2, batch=batch, seq=seq,
              step_ms=round(step_ms, 3),
              std_ms=round(float(np.std(times) * 1e3), 3), **mem,
              **_attrib_extra(traced, step_ms))
    finally:
        parallel_state.destroy_model_parallel()


def bench_dp_accumulate_overlap(iters=10, warmup=2, K=4, layers=8,
                                hidden=512, batch_per_rank=8):
    """Bucketed-DP overlap A/B: a gradient-accumulation window (K
    microbatches, local sum, one end-of-window sync) + FusedAdam step on a
    pure-DP mesh, monolithic sync (one psum per grad leaf at the window
    end) vs the bucketed engine
    (``parallel/distributed.py::allreduce_grads(bucket_bytes=...)``) —
    same session, same mesh, same params, so the ratio isolates what
    XLA's latency-hiding scheduler buys from B independent bucket
    collectives it can overlap with the finite-check/scale epilogue and
    each other. ``vs_baseline`` is mono_ms/bucket_ms (>1 means bucketing
    pays). On a CPU host mesh there is no ICI latency to hide, so ~1.0 is
    the expected and documented reading (docs/PERF.md "DP overlap +
    ZeRO") — the win must be read off a multi-chip run. Skipped below 2
    devices."""
    from jax.sharding import Mesh, PartitionSpec as P

    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedDataParallel
    from apex_tpu.training import accumulate_gradients
    from apex_tpu.utils.compat import shard_map_unchecked

    if jax.device_count() < 2:
        _emit("dp_window_overlap_step_ms", -1.0, "skipped", None,
              error=f"needs >= 2 devices, have {jax.device_count()}")
        return

    dp = jax.device_count()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    rng = np.random.RandomState(0)
    widths = [hidden] * (layers + 1)
    params = {f"w{i}": jnp.asarray(
        rng.randn(widths[i], widths[i + 1]) * (widths[i] ** -0.5),
        jnp.float32) for i in range(layers)}
    xs = jnp.asarray(rng.randn(K, dp * batch_per_rank, hidden), jnp.float32)
    ys = jnp.asarray(rng.randn(K, dp * batch_per_rank, hidden), jnp.float32)

    def loss_fn(p, mb):
        x, y = mb
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    opt = FusedAdam(lr=1e-3)

    def measure(bucket_bytes):
        ddp = DistributedDataParallel("data", delay_allreduce=True,
                                      bucket_bytes=bucket_bytes)

        def window(p, s, xs, ys):
            def inner(p, s, xs, ys):
                loss, grads = accumulate_gradients(ddp, loss_fn, p,
                                                   (xs, ys))
                new_p, new_s = opt.step(grads, s, p)
                return jax.lax.pmean(loss, "data"), new_p, new_s
            pspec = jax.tree_util.tree_map(lambda _: P(), p)
            sspec = jax.tree_util.tree_map(lambda _: P(), s)
            return shard_map_unchecked(
                inner, mesh=mesh,
                in_specs=(pspec, sspec, P(None, "data"), P(None, "data")),
                out_specs=(P(), pspec, sspec))(p, s, xs, ys)

        @(lambda f: jax.jit(f, donate_argnums=(0, 1)))
        def step(p, s, xs, ys):
            _, new_p, new_s = window(p, s, xs, ys)
            return new_p, new_s, xs, ys

        p0 = jax.tree_util.tree_map(jnp.copy, params)
        s0 = opt.init(p0)
        times = _timeit(step, (p0, s0, xs, ys), iters, warmup)
        return float(np.mean(times) * 1e3), times

    mono_ms, _ = measure(None)
    from apex_tpu.parallel.distributed import DEFAULT_BUCKET_BYTES
    # params are ~layers*hidden^2*4 bytes; pick a bucket ~1/8 of that so
    # several buckets are in flight even at bench scale, capped at the
    # library default
    bb = min(DEFAULT_BUCKET_BYTES,
             max(1 << 16, (layers * hidden * hidden * 4) // 8))
    bucket_ms, times = measure(bb)
    _emit("dp_window_overlap_step_ms", bucket_ms, "ms",
          mono_ms / bucket_ms, mono_ms=round(mono_ms, 3),
          bucket_bytes=bb, dp=dp, num_micro=K,
          std_ms=round(float(np.std(times) * 1e3), 3))


# the stated serving SLO the decode goodput is scored under:
# (metric, quantile, threshold_ms). Production-shaped thresholds — on the
# CPU test host the absolute latencies are structural, so read goodput
# off a TPU round (BASELINE.md round 13).
DECODE_SLO = (("ttft_ms", 95.0, 2000.0), ("tpot_ms", 99.0, 500.0))

# Declarative paged-decode leg config: keys are REAL
# ``PagedServingEngine.__init__`` keyword parameters — statically
# validated by scripts/check_bench_configs.py (rule ast-bench-configs),
# so a renamed engine knob breaks the check instead of TypeError-ing
# only at bench runtime. num_blocks = max_seqs * (max_len/block_size)
# + 1 (the reserved null block): full dense-equivalent worst-case
# capacity, so the throughput delta isolates the bounded-grid kernel,
# not admission pressure. mean_context prices the kernel's CostEstimate
# at the fleet's expected live context (docs/SERVING.md "Paged
# serving").
BENCH_DECODE_CONFIGS = {
    "gpt_decode_paged": {
        "max_seqs": 8, "max_len": 1024, "prefill_len": 128,
        "block_size": 128, "num_blocks": 65, "mean_context": 160.0,
    },
    # the speculative A/B leg: a DENSE engine (no block keys — the
    # static check validates it against ServingEngine.__init__), small
    # batch where decode is deepest into the memory-bound regime and
    # speculation's k-tokens-per-step amortization reads clearest;
    # speculate_k >= 1 is enforced statically (k=0 would silently bench
    # the non-speculative path against itself)
    "gpt_decode_spec": {
        "max_seqs": 4, "max_len": 1024, "prefill_len": 128,
        "speculate_k": 4,
    },
}


def bench_gpt_decode(iters=40, warmup=5, prefill_iters=5, max_len=1024,
                     prefill_len=128, sat_slots=8, hidden=768, layers=12,
                     heads=12, vocab=32768):
    """Serving decode family (docs/SERVING.md): the GPT-small shape
    through the AOT ``ServingEngine``, timing the compiled decode step
    with its donated cache threaded call-to-call (the autoregressive
    loop itself: sampled tokens feed back as the next step's input).

    - ``gpt_decode_tok_per_sec_b1``: a ``max_seqs=1`` program, one
      active sequence — the latency leg; 1/value is the per-token
      interval a single user sees.
    - ``gpt_decode_tok_per_sec_sat``: a ``max_seqs=sat_slots`` program
      with every slot active — the throughput leg continuous batching
      sustains at saturation.

    ``vs_baseline`` is measured/roofline against the HBM-bound bound
    (params read once per step + each active slot's LIVE cache stripe at
    the measured mean length, over the chip's ``DeviceSpec`` bandwidth)
    — necessarily < 1; the gap is the decode overhead. Known v1
    contributor: the kernel's pipelined block fetches are max_len-shaped
    (compute past the cursor is skipped, fetches are not), so expect the
    gap to track mean_len/max_len until the bounded-grid variant lands
    (docs/SERVING.md). Each line carries
    ``temp_bytes``/``peak_hbm_bytes`` (decode program), the decode-step
    pyprof attribution, and a ``prefill_step_ms`` + prefill attribution
    so the prefill/decode split rides the bench history.

    Each leg also drives a short continuous-batching run through the
    SAME AOT engine (no extra compiles) and carries the per-request
    latency percentiles off the ``serve/*`` histograms —
    ``ttft_p50/p95/p99_ms``, ``tpot_p50/p95/p99_ms`` — plus ``goodput``
    under the stated ``DECODE_SLO``; the sat leg's goodput is
    additionally emitted as the ``gpt_decode_goodput`` line
    (docs/SERVING.md "TTFT, TPOT and the SLO"). CPU numbers are
    structural; read real latencies off a TPU run."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.observability.costs import device_spec
    from apex_tpu.observability.registry import MetricsRegistry
    from apex_tpu.observability.reqtrace import (LATENCY_BUCKETS_MS,
                                                 RequestTrace)
    from apex_tpu.observability.slo import SLOTarget, SLOTracker
    from apex_tpu.serving import Request, ServingEngine, SlotScheduler

    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_len,
                    compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    param_bytes = sum(l.size * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(params))
    prompt = np.random.RandomState(0).randint(
        1, vocab, size=prefill_len).tolist()
    slo_targets = tuple(SLOTarget(m, q, t) for m, q, t in DECODE_SLO)

    def serve_leg(eng, slots):
        """A short continuous-batching run through the already-compiled
        engine: real request latencies -> serve/* histogram percentiles
        + goodput under DECODE_SLO. Rides the leg (no new config-budget
        entry, no extra AOT compiles)."""
        sreg = MetricsRegistry()
        tracker = SLOTracker(slo_targets, registry=sreg,
                             trace=RequestTrace(capacity=64),
                             on_violation="skip")
        sched = SlotScheduler(eng, registry=sreg,
                              trace=tracker.trace, slo=tracker)
        sched.run([Request(prompt=prompt[: 1 + (3 * i) % prefill_len],
                           max_new_tokens=8)
                   for i in range(2 * slots)])
        extras = {}
        for short, name in (("ttft", "serve/ttft_ms"),
                            ("tpot", "serve/tpot_ms")):
            hist = sreg.histogram(name, LATENCY_BUCKETS_MS)
            extras.update({f"{short}_p{q}_ms":
                           round(float(hist.percentile(q)), 3)
                           for q in (50, 95, 99)})
        extras["goodput"] = round(tracker.goodput(), 4)
        extras["slo"] = "; ".join(t.describe() for t in slo_targets)
        # resilience wiring (docs/SERVING.md "Resilience"): a burst at
        # 4x the queue bound against the SAME compiled engine — the
        # admission bound must hold with typed rejections while every
        # ADMITTED request still completes (rides the leg: no new
        # config-budget entry, no extra AOT compiles)
        t2 = SLOTracker(slo_targets, registry=sreg, on_violation="skip")
        over = SlotScheduler(eng, registry=sreg, slo=t2,
                             max_queue=slots,
                             default_deadline_ms=120000.0)
        burst = [over.submit(Request(prompt=prompt[: 1 + i],
                                     max_new_tokens=4))
                 for i in range(4 * slots)]
        over.run([])
        snap = sreg.snapshot()
        extras["rejected"] = int(snap.get("serve/rejected", 0.0))
        extras["expired"] = int(snap.get("serve/expired", 0.0))
        extras["overload_admitted_goodput"] = round(t2.goodput(), 4)
        assert extras["rejected"] == sum(
            1 for b in burst if not isinstance(b, int))
        return extras

    def measure(slots):
        eng = ServingEngine(model, params, max_seqs=slots,
                            max_len=max_len, prefill_len=prefill_len)
        key = eng._next_key()
        temps = jnp.zeros((slots,), jnp.float32)

        # prefill leg: the compiled prefill threaded the _timeit way
        # (slot 0 overwritten each call — timing, not generation)
        ptok = eng.pad_prompt(prompt)
        zero = jnp.asarray(0, jnp.int32)
        plen = jnp.asarray(prefill_len, jnp.int32)

        def pwrap(cache, tok):
            cache, tok = eng.prefill_compiled(
                params, cache, ptok, zero, plen, jnp.float32(0.0), key)
            return cache, tok
        ptimes = _timeit(pwrap, (eng.cache, jnp.asarray(0, jnp.int32)),
                         prefill_iters, 1)
        prefill_ms = float(np.mean(ptimes) * 1e3)
        # the timing loop consumed the engine's donated cache outside its
        # bookkeeping — give it a fresh one, then fill every slot so the
        # decode leg runs fully active
        from apex_tpu.serving import KVCache
        eng.cache = KVCache.create(layers, slots, heads, max_len,
                                   cfg.head_dim, dtype=jnp.bfloat16)
        for s in range(slots):
            eng.prefill(prompt, slot=s)

        all_active = jnp.ones((slots,), jnp.bool_)

        def dwrap(cache, toks):
            cache, toks = eng.decode_compiled(params, cache, toks, temps,
                                              all_active, key)
            return cache, toks
        times = _timeit(dwrap, (eng.cache, jnp.zeros((slots,),
                                                     jnp.int32)),
                        iters, warmup)
        step_ms = float(np.mean(times) * 1e3)
        tok_per_sec = slots / float(np.mean(times))

        # HBM roofline: params once per step + each slot's K+V stripe at
        # the mean decoded length (two dtype-width bytes per element)
        mean_len = prefill_len + (warmup + iters) / 2.0
        stripe = (2 * layers * heads * mean_len * cfg.head_dim
                  * jnp.dtype(jnp.bfloat16).itemsize)
        spec = device_spec()
        step_bytes = param_bytes + slots * stripe
        roofline = slots / (step_bytes / (spec.hbm_gbps * 1e9))
        extras = dict(_mem_extra(eng.decode_compiled))
        extras.update(_attrib_extra(eng.decode_traced, step_ms))
        extras.update({f"prefill_{k}": v for k, v in _attrib_extra(
            eng.prefill_traced, prefill_ms).items()
            if k not in ("attribution", "step_time_ms")})
        # request-lifecycle percentiles: the timing loop consumed the
        # donated cache again — fresh one, then a real scheduler run on
        # the same compiled programs
        eng.cache = KVCache.create(layers, slots, heads, max_len,
                                   cfg.head_dim, dtype=jnp.bfloat16)
        extras.update(serve_leg(eng, slots))
        return (tok_per_sec, roofline, step_ms,
                float(np.std(times) * 1e3), prefill_ms, extras)

    goodput = None
    for metric, slots in (("gpt_decode_tok_per_sec_b1", 1),
                          ("gpt_decode_tok_per_sec_sat", sat_slots)):
        tps, roof, step_ms, std_ms, prefill_ms, extras = measure(slots)
        goodput = extras["goodput"]
        _emit(metric, tps, "tokens/sec", tps / roof,
              anchor="hbm_roofline_this_chip",
              roofline_tok_per_sec=round(roof, 2),
              step_ms=round(step_ms, 3), std_ms=round(std_ms, 3),
              prefill_step_ms=round(prefill_ms, 3),
              slots=slots, max_len=max_len, prefill_len=prefill_len,
              iters=iters, **extras)
    # the headline goodput row: the saturating grid scored under the
    # stated SLO (100 = every request met every target; the serving
    # quality number next to the serving speed numbers above). Emitted
    # in PERCENT: _emit rounds value to 2 decimals, and against a 1%
    # p99 error budget a fraction would quantize away sub-0.5%
    # violation rates (0.996 would read as a perfect 1.0)
    _emit("gpt_decode_goodput", goodput * 100.0, "percent", None,
          slo="; ".join(t.describe() for t in slo_targets),
          slots=sat_slots, max_len=max_len, prefill_len=prefill_len)


def bench_gpt_decode_paged(iters=20, warmup=3, prefix_reps=5, hidden=768,
                           layers=12, heads=12, vocab=32768):
    """Paged serving legs (docs/SERVING.md "Paged serving"): the same
    GPT-small shape through the AOT ``PagedServingEngine`` — block-pool
    KV cache, bounded-grid decode kernel, copy-on-write prefix sharing.
    The engine config is the declarative
    ``BENCH_DECODE_CONFIGS["gpt_decode_paged"]`` entry, statically
    validated by scripts/check_bench_configs.py.

    - ``gpt_decode_tok_per_sec_paged``: every slot of the paged grid
      active — the throughput twin of ``gpt_decode_tok_per_sec_sat``,
      same slots/max_len/prefill_len so the delta isolates the paged
      machinery. ``vs_baseline`` is measured/roofline over the same
      live-stripe HBM bound as the dense legs; ``modeled_hbm_ratio``
      carries the pyprof-modeled ``decode_attention`` HBM of this
      program over the dense engine's — the O(actual_context) vs
      O(max_len) gap the bounded grid closes (expect it to track
      ``mean_context / max_len``).
    - ``gpt_decode_ttft_prefix_ms``: prefill latency for a prompt whose
      prefix is already registered in the pool (maps the shared blocks,
      decodes only the un-shared tail) vs the same-length cold path.
      ``vs_baseline`` is cold/warm (> 1 means prefix sharing pays);
      ``ttft_cold_ms`` rides the line.

    CPU numbers are structural (interpret-mode kernels); read real
    latencies off a TPU run."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.observability.costs import device_spec
    from apex_tpu.pyprof import model_program
    from apex_tpu.serving import (BlockAllocator, PagedKVCache,
                                  PagedServingEngine, ServingEngine)

    spec = dict(BENCH_DECODE_CONFIGS["gpt_decode_paged"])
    slots, max_len = spec["max_seqs"], spec["max_len"]
    prefill_len, block_size = spec["prefill_len"], spec["block_size"]
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_len,
                    compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    param_bytes = sum(l.size * l.dtype.itemsize
                      for l in jax.tree_util.tree_leaves(params))
    rs = np.random.RandomState(0)
    eng = PagedServingEngine(model, params, **spec)

    # --- TTFT leg first (the throughput _timeit consumes the donated
    # cache outside the engine's bookkeeping) ---
    shared = rs.randint(1, vocab, size=prefill_len).tolist()
    cold_ms = []
    for _ in range(prefix_reps):
        # distinct prompts so every rep takes the cold path
        t0 = time.perf_counter()
        eng.prefill(rs.randint(1, vocab, size=prefill_len).tolist(),
                    slot=0)
        cold_ms.append((time.perf_counter() - t0) * 1e3)
        eng.release_slot(0)
    eng.prefill(shared, slot=0)  # registers the shared prefix
    warm_ms = []
    for _ in range(prefix_reps):
        t0 = time.perf_counter()
        eng.prefill(shared, slot=1)
        warm_ms.append((time.perf_counter() - t0) * 1e3)
        assert not eng.last_admit.prefill, "prefix hit expected"
        eng.release_slot(1)
    eng.release_slot(0)
    cold = float(np.median(cold_ms))
    warm = float(np.median(warm_ms))

    # --- throughput leg: fresh pool, distinct prompts (no sharing —
    # the COW/refcount cost is the allocator tests' job), one host-path
    # step so every slot owns a live decode block, then the frozen
    # compiled step threaded the _timeit way ---
    eng.cache = PagedKVCache.create(layers, spec["num_blocks"], heads,
                                    block_size, cfg.head_dim,
                                    dtype=jnp.bfloat16)
    eng.allocator = BlockAllocator(spec["num_blocks"], block_size,
                                   eng.allocator.blocks_per_slot, slots)
    for s in range(slots):
        eng.prefill(rs.randint(1, vocab, size=prefill_len).tolist(),
                    slot=s)
    eng.decode(np.zeros(slots, np.int32), np.zeros(slots, np.float32))
    alloc = eng.allocator
    bids, offs = alloc.append_targets(np.ones(slots, bool))
    tables = jnp.asarray(alloc.tables)
    lengths = jnp.asarray(alloc.lengths)
    temps = jnp.zeros((slots,), jnp.float32)
    zs = jnp.zeros((slots,), jnp.int32)
    bids, offs = jnp.asarray(bids), jnp.asarray(offs)
    key = eng._next_key()

    def dwrap(cache, toks):
        cache, toks = eng.decode_compiled(params, cache, tables, lengths,
                                          toks, temps, bids, offs, zs,
                                          zs, key)
        return cache, toks

    times = _timeit(dwrap, (eng.cache, zs), iters, warmup)
    step_ms = float(np.mean(times) * 1e3)
    tok_per_sec = slots / float(np.mean(times))

    # the same live-stripe roofline as the dense legs, at the ACTUAL
    # mean context — the paged step's HBM target, not max_len's
    mean_len = float(np.mean(np.asarray(alloc.lengths)))
    stripe = (2 * layers * heads * mean_len * cfg.head_dim
              * jnp.dtype(jnp.bfloat16).itemsize)
    dspec = device_spec()
    roofline = slots / ((param_bytes + slots * stripe)
                        / (dspec.hbm_gbps * 1e9))

    extras = dict(_mem_extra(eng.decode_compiled))
    extras.update(_attrib_extra(eng.decode_traced, step_ms))
    # the modeled attention-HBM gap: this program's decode_attention
    # bytes over the dense engine's at identical shapes — the number
    # the bounded grid exists to shrink (CostEstimate-priced, so it
    # reflects the clamped grid, not the dense worst case)
    try:
        dense = ServingEngine(model, params, max_seqs=slots,
                              max_len=max_len, prefill_len=prefill_len)
        paged_hbm = model_program(
            eng.decode_traced).regions["decode_attention"].hbm_bytes
        dense_hbm = model_program(
            dense.decode_traced).regions["decode_attention"].hbm_bytes
        if dense_hbm > 0:
            extras["modeled_hbm_ratio"] = round(paged_hbm / dense_hbm, 4)
    except Exception:
        pass

    _emit("gpt_decode_tok_per_sec_paged", tok_per_sec, "tokens/sec",
          tok_per_sec / roofline, anchor="hbm_roofline_this_chip",
          roofline_tok_per_sec=round(roofline, 2),
          step_ms=round(step_ms, 3),
          std_ms=round(float(np.std(times) * 1e3), 3),
          slots=slots, max_len=max_len, prefill_len=prefill_len,
          block_size=block_size, num_blocks=spec["num_blocks"],
          mean_context=spec["mean_context"], iters=iters, **extras)
    _emit("gpt_decode_ttft_prefix_ms", warm,
          "ms", None if warm <= 0 else cold / warm,
          ttft_cold_ms=round(cold, 3), prefill_len=prefill_len,
          shared_tokens=prefill_len - 1, reps=prefix_reps)


def bench_gpt_decode_spec(new_tokens=48, requests=8, hidden=768,
                          layers=12, heads=12, vocab=32768):
    """Speculative-decoding A/B (docs/SERVING.md "Speculative
    decoding"): the SAME GPT-small weights and request set through a
    non-speculative dense engine and a ``speculate_k`` one
    (``BENCH_DECODE_CONFIGS["gpt_decode_spec"]``), both driven by the
    full scheduler loop so the number includes the host drafting cost.

    - ``gpt_decode_tok_per_sec_spec``: end-to-end generated tokens per
      second under speculation; ``vs_baseline`` is the ratio against
      the same-session non-speculative run (> 1 means speculation
      pays), with the non-spec rate, acceptance rate and verify-step
      count riding the line.

    Workload: repetitive text — greedy decoding of a random-weight
    GPT settles into short repetition loops, exactly the regime
    prompt-lookup drafting serves (real repetitive workloads: code,
    templated prose, retrieval contexts). The win is k tokens per
    memory-bound step at ~1 step's HBM traffic; CPU numbers compress
    it (the XLA-fallback verify pays k× compute that a TPU hides under
    the HBM stream — BASELINE.md carries the sandbox ratio), so read
    the real delta off a TPU run."""
    from apex_tpu.models import GPTConfig, GPTModel
    from apex_tpu.observability.registry import MetricsRegistry
    from apex_tpu.serving import Request, ServingEngine, SlotScheduler

    spec = dict(BENCH_DECODE_CONFIGS["gpt_decode_spec"])
    k = spec["speculate_k"]
    slots, max_len = spec["max_seqs"], spec["max_len"]
    prefill_len = spec["prefill_len"]
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                    num_layers=layers, num_attention_heads=heads,
                    max_position_embeddings=max_len,
                    compute_dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    pattern = np.random.RandomState(0).randint(
        1, vocab, size=8).tolist()

    def leg(speculate):
        eng = ServingEngine(model, params,
                            **{**spec, "speculate_k": speculate})
        reg = MetricsRegistry()
        sched = SlotScheduler(eng, registry=reg, speculate_k=speculate)
        # warm run: first-dispatch host paths + any lazy sampling
        # compiles, outside the timed window
        sched.run([Request(prompt=pattern, max_new_tokens=2)])
        reqs = [Request(prompt=(pattern * 32)[i: i + prefill_len],
                        max_new_tokens=new_tokens)
                for i in range(requests)]
        t0 = time.perf_counter()
        done = sched.run(reqs)
        dt = time.perf_counter() - t0
        gen = sum(len(c.tokens) for c in done.values())
        return gen / dt, dict(reg.snapshot())

    base_tps, _ = leg(0)
    spec_tps, snap = leg(k)
    _emit("gpt_decode_tok_per_sec_spec", spec_tps, "tokens/sec",
          None if base_tps <= 0 else spec_tps / base_tps,
          anchor="same_session_nonspec_ab",
          nonspec_tok_per_sec=round(base_tps, 2),
          accept_rate=round(snap.get("serve/spec_accept_rate", 0.0), 4),
          spec_steps=int(snap.get("serve/spec_steps", 0)),
          speculate_k=k, slots=slots, max_len=max_len,
          prefill_len=prefill_len, new_tokens=new_tokens,
          requests=requests)


def bench_flash_long(seq=4096, b=8, h=12, d=64):
    """Long-context evidence: flash (auto 512-blocks) vs XLA attention
    fwd+bwd at seq 4096 — the regime the reference cannot reach at all
    (its fused kernels cap at 2048/512)."""
    from apex_tpu.ops.flash_attention import flash_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)
    dy = jnp.asarray(rng.randn(b, h, seq, d), jnp.bfloat16)

    def make_step(use_pallas):
        def loss(q, k, v):
            out = flash_attention(q, k, v, causal=True,
                                  use_pallas=use_pallas)
            return jnp.sum(out.astype(jnp.float32)
                           * dy.astype(jnp.float32))

        def step(carry):
            q, k, v = carry
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            return (dq.astype(q.dtype), dk.astype(k.dtype),
                    dv.astype(v.dtype))
        return step

    flash_ms, flash_std = _device_loop_ms(make_step(True), (q, k, v), k=10,
                                          reps=3)
    xla_ms, _ = _device_loop_ms(make_step(False), (q, k, v), k=10, reps=3)
    _emit("flash_attention_seq4096_fwd_bwd_ms", flash_ms, "ms",
          xla_ms / flash_ms, xla_ms=round(xla_ms, 3),
          std_ms=round(flash_std, 3), batch=b, heads=h, seq=seq)


def _write_configs():
    with open("BENCH_CONFIGS.json", "w") as f:
        json.dump(_RESULTS, f, indent=1)


def main():
    # default = everything, headline LAST (a driver keeping the final
    # stdout line gets the headline); --headline skips the config benches.
    # Config benches are budgeted so a slow compile can never starve the
    # headline, results are checkpointed to BENCH_CONFIGS.json after every
    # config, and a config failure is recorded in the file (not just
    # printed) via _emit.
    headline_only = "--headline" in sys.argv
    if not headline_only:
        budget_s = 420.0
        t0 = time.perf_counter()
        # the multi-compile configs run LAST, newest first to be starved:
        # sp_ovl (two GPT TP=2 compiles) after the longer-tracked configs
        # above it, remat (FOUR GPT-small train-step compiles) next,
        # gpt_fast (two full hybrid-trainer compiles) after that, and
        # gpt_decode (two serving engines = four AOT compiles) next,
        # and gpt_decode_paged (one paged engine = three AOT compiles
        # plus a dense twin for the modeled-HBM ratio) next, and
        # gpt_decode_spec (two dense engines = seven AOT compiles for
        # the speculative A/B, the newest leg) dead last so a tight
        # budget drops the newest metrics, never the established
        # baseline rows
        for fn in (bench_layernorm, bench_optimizer, bench_gpt,
                   bench_flash_long, bench_dp_accumulate_overlap,
                   bench_gpt_sp_overlap, bench_gpt_remat,
                   bench_gpt_fast, bench_gpt_decode,
                   bench_gpt_decode_paged, bench_gpt_decode_spec):
            if time.perf_counter() - t0 > budget_s:
                _emit(fn.__name__, -1.0, "skipped", None,
                      error="config budget exhausted; headline protected")
                continue
            try:
                fn()
            except Exception as e:  # a config bench must not sink the run
                _emit(fn.__name__, -1.0, "error", None, error=str(e))
            _write_configs()
    try:
        bench_headline()
    finally:
        if not headline_only:
            _write_configs()


if __name__ == "__main__":
    main()
