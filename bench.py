"""Headline benchmark — ImageNet ResNet-50 train-step throughput per chip.

Matches BASELINE.json's metric ("ImageNet RN50 imgs/sec/chip, amp O2+DDP"):
bf16 compute / fp32 master params (amp O2 semantics), FusedSGD momentum
(the imagenet example's optimizer), synthetic data (the reference's
``--prof`` style synthetic path; input pipeline is out of scope for a
kernel/runtime library benchmark on both sides).

``vs_baseline`` compares against NVIDIA's published DGX-A100
DeepLearningExamples ResNet-50 AMP number (~2470 imgs/sec per A100), the
"8xA100 amp-O2+DDP" north-star divided per chip; the reference repo itself
publishes no numbers (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp.scaler import DynamicLossScale, all_finite
from apex_tpu.models import ResNet50, ResNetConfig
from apex_tpu.optimizers import FusedSGD

A100_AMP_RN50_IMGS_PER_SEC = 2470.0  # per-chip baseline (see docstring)

BATCH = 128
IMG = 224
WARMUP = 3
ITERS = 10


def main():
    cfg = ResNetConfig(num_classes=1000, compute_dtype=jnp.bfloat16)
    model = ResNet50(cfg)
    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0 ** 12)
    ls = scaler.init()

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(BATCH, IMG, IMG, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, BATCH))

    def loss_fn(params, bn_state, scale):
        logits, new_bn = model(params, bn_state, x, training=True)
        onehot = jax.nn.one_hot(labels, 1000)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return loss * scale, (loss, new_bn)

    @jax.jit
    def step(params, bn_state, opt_state, ls):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
            params, bn_state, ls.loss_scale)
        grads = scaler.unscale(ls, grads)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite)
        return params, new_bn, opt_state, new_ls, loss

    # warmup/compile
    for _ in range(WARMUP):
        params, bn_state, opt_state, ls, loss = step(
            params, bn_state, opt_state, ls)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        params, bn_state, opt_state, ls, loss = step(
            params, bn_state, opt_state, ls)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec",
        "vs_baseline": round(imgs_per_sec / A100_AMP_RN50_IMGS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
