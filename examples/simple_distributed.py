"""Minimal data-parallel training — ``reference:examples/simple/
distributed/distributed_data_parallel.py`` rebuilt on apex_tpu.

The reference spawns one process per GPU and wraps the model in apex DDP;
on TPU one process drives all devices and "DDP" is the
``DistributedDataParallel.value_and_grad`` wrapper inside ``shard_map``.

    python examples/simple_distributed.py
"""

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import DistributedDataParallel


def main(steps: int = 20):
    mesh = Mesh(np.array(jax.devices()), ("data",))
    n_dev = jax.device_count()
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(32, 16) * 0.1, jnp.float32),
              "b": jnp.zeros(16, jnp.float32)}
    x = jnp.asarray(rng.randn(8 * n_dev, 32), jnp.float32)
    y = jnp.asarray(rng.randn(8 * n_dev, 16), jnp.float32)

    ddp = DistributedDataParallel(axis_name="data")
    opt = FusedAdam(lr=1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, x, y):
        def inner(params, opt_state, x, y):
            def loss_fn(p, x, y):
                return jnp.mean((x @ p["w"] + p["b"] - y) ** 2)
            loss, grads = ddp.value_and_grad(loss_fn)(params, x, y)
            params, opt_state = opt.step(grads, opt_state, params)
            return params, opt_state, jax.lax.pmean(loss, "data")
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), P(), P("data"), P("data")),
                         out_specs=(P(), P(), P()))(params, opt_state, x, y)

    for i in range(steps):
        params, opt_state, loss = step(params, opt_state, x, y)
        if i % 5 == 0 or i == steps - 1:
            print(f"step {i}: loss {float(loss):.5f}")
    return float(loss)


if __name__ == "__main__":
    main()
