"""Continuous-batching GPT serving demo — the decode-side counterpart of
``gpt_pretrain.py`` (docs/SERVING.md).

Builds a small randomly-initialized GPT, compiles the AOT prefill/decode
steps once (donated KV cache), enqueues a mixed bag of requests (greedy
and sampled, different lengths), streams tokens as slots produce them,
and prints the ``serve/*`` metric summary. On 2 slots and 6 requests the
log shows the continuous-batching shape: short requests retire and their
slots re-admit from the queue while long ones keep decoding.

    python examples/gpt_serve.py --max-seqs 2 --requests 6
"""

import argparse

import jax
import numpy as np

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.serving import Request, ServingEngine, SlotScheduler


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-seqs", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--prefill-len", type=int, default=16)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--int8-cache", action="store_true",
                    help="quantized KV cache (per-(position,head) "
                         "scales); halves cache HBM per slot")
    args = ap.parse_args(argv)

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_position_embeddings=args.max_len)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    import jax.numpy as jnp
    engine = ServingEngine(
        model, params, max_seqs=args.max_seqs, max_len=args.max_len,
        prefill_len=args.prefill_len, top_k=args.top_k,
        cache_dtype=jnp.int8 if args.int8_cache else jnp.bfloat16)
    print(f"engine: {args.max_seqs} slots x {args.max_len} tokens, "
          f"{engine.bytes_per_slot()} cache bytes/slot; a 16GB chip "
          f"would hold ~{engine.suggest_max_seqs(16 << 30)} slots")

    reg = MetricsRegistry()
    sched = SlotScheduler(engine, registry=reg)
    rng = np.random.RandomState(0)
    for i in range(args.requests):
        prompt = rng.randint(1, args.vocab,
                             size=1 + i % args.prefill_len).tolist()
        sched.submit(Request(prompt=prompt,
                             max_new_tokens=1 + (args.max_new_tokens
                                                 * (i + 1)) // 2,
                             temperature=0.0 if i % 2 == 0 else 0.8))

    # the steady-state loop runs under the analysis engine's
    # zero-recompile guard (docs/ANALYSIS.md): after the first (warmup)
    # step, any retrace of the serving programs raises loudly
    from apex_tpu.analysis import recompile_guard

    seen = {}
    steps = 0
    with recompile_guard("gpt_serve loop") as guard:
        while sched.pending:
            sched.step()
            steps += 1
            if steps == 1:
                guard.rebase()
            # stream: print each request's tokens as they extend
            for slot, st in sched.active.items():
                rid = st.request.request_id
                if len(st.generated) != seen.get(rid):
                    seen[rid] = len(st.generated)
                    print(f"  req {rid} (slot {slot}): "
                          f"{st.generated[-4:]} "
                          f"({len(st.generated)} tokens)")

    results = {c.request_id: c for c in sched.completed}
    for rid in sorted(results):
        c = results[rid]
        print(f"req {rid}: {len(c.tokens)} tokens, "
              f"finished by {c.finish_reason}")
    snap = {k: v for k, v in reg.snapshot().items()
            if k.startswith("serve/")}
    print("serve/* summary:", {k: round(v, 1) for k, v in snap.items()})
    return {"completions": results, "metrics": snap}


if __name__ == "__main__":
    main()
