"""Continuous-batching GPT serving demo — the decode-side counterpart of
``gpt_pretrain.py`` (docs/SERVING.md).

Builds a small randomly-initialized GPT, compiles the AOT prefill/decode
steps once (donated KV cache), enqueues a mixed bag of requests (greedy
and sampled, different lengths), streams tokens as slots produce them,
and prints the ``serve/*`` metric summary — including the per-request
latency percentiles (TTFT/TPOT p50/p95/p99 off the ``serve/*_ms``
histograms) and the rolling goodput under a demo SLO, plus a per-slot
Chrome swimlane trace (``--trace-out``). On 2 slots and 6 requests the
log shows the continuous-batching shape: short requests retire and their
slots re-admit from the queue while long ones keep decoding.

    python examples/gpt_serve.py --max-seqs 2 --requests 6
"""

import argparse

import jax
import numpy as np

from apex_tpu.models import GPTConfig, GPTModel
from apex_tpu.observability.registry import MetricsRegistry
from apex_tpu.serving import (Rejection, Request, RequestTrace,
                              ServingEngine, SLOTarget, SLOTracker,
                              SlotScheduler)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--max-seqs", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--prefill-len", type=int, default=16)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--max-new-tokens", type=int, default=8)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--int8-cache", action="store_true",
                    help="quantized KV cache (per-(position,head) "
                         "scales); halves cache HBM per slot")
    ap.add_argument("--trace-out", default=None,
                    help="write the per-request Chrome trace (one "
                         "swimlane per slot) to this path")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="admission bound: submissions past this queue "
                         "depth get a typed Rejection(queue_full) "
                         "instead of growing the queue without bound "
                         "(docs/SERVING.md Resilience)")
    ap.add_argument("--deadline-ms", type=float, default=None,
                    help="default per-request deadline: requests expire "
                         "(finish_reason 'expired') while queued or "
                         "mid-flight once this budget elapses")
    ap.add_argument("--speculate-k", type=int, default=0,
                    help="speculative decoding: draft k tokens per slot "
                         "from the self-drafting n-gram source and "
                         "verify them in ONE step (docs/SERVING.md "
                         "'Speculative decoding'); prints the "
                         "acceptance rate and the TPOT delta against a "
                         "same-session non-speculative baseline")
    ap.add_argument("--ttft-slo-ms", type=float, default=5000.0,
                    help="demo SLO: TTFT p95 threshold")
    ap.add_argument("--tpot-slo-ms", type=float, default=1000.0,
                    help="demo SLO: TPOT p99 threshold")
    args = ap.parse_args(argv)

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_position_embeddings=args.max_len)
    model = GPTModel(cfg)
    params = model.init(jax.random.PRNGKey(0))
    import jax.numpy as jnp
    engine = ServingEngine(
        model, params, max_seqs=args.max_seqs, max_len=args.max_len,
        prefill_len=args.prefill_len, top_k=args.top_k,
        cache_dtype=jnp.int8 if args.int8_cache else jnp.bfloat16,
        speculate_k=args.speculate_k)
    print(f"engine: {args.max_seqs} slots x {args.max_len} tokens, "
          f"{engine.bytes_per_slot()} cache bytes/slot; a 16GB chip "
          f"would hold ~{engine.suggest_max_seqs(16 << 30)} slots")

    reg = MetricsRegistry()
    targets = (SLOTarget("ttft_ms", 95, args.ttft_slo_ms),
               SLOTarget("tpot_ms", 99, args.tpot_slo_ms))
    trace = RequestTrace(capacity=256)
    slo = SLOTracker(targets, registry=reg, trace=trace,
                     on_violation="skip")
    sched = SlotScheduler(engine, registry=reg, trace=trace, slo=slo,
                          max_queue=args.max_queue,
                          default_deadline_ms=args.deadline_ms,
                          speculate_k=args.speculate_k)

    def demo_requests():
        rng = np.random.RandomState(0)
        return [Request(prompt=rng.randint(
                            1, args.vocab,
                            size=1 + i % args.prefill_len).tolist(),
                        max_new_tokens=1 + (args.max_new_tokens
                                            * (i + 1)) // 2,
                        temperature=0.0 if i % 2 == 0 else 0.8)
                for i in range(args.requests)]

    rejections = []
    for i, req in enumerate(demo_requests()):
        res = sched.submit(req)
        if isinstance(res, Rejection):
            rejections.append(res)
            print(f"  req {i} rejected: {res.reason} ({res.detail})")

    # the steady-state loop runs under the analysis engine's
    # zero-recompile guard (docs/ANALYSIS.md): after the first (warmup)
    # step, any retrace of the serving programs raises loudly
    from apex_tpu.analysis import recompile_guard

    seen = {}
    steps = 0
    with recompile_guard("gpt_serve loop") as guard:
        while sched.pending:
            sched.step()
            steps += 1
            if steps == 1:
                guard.rebase()
            # stream: print each request's tokens as they extend
            for slot, st in sched.active.items():
                rid = st.request.request_id
                if len(st.generated) != seen.get(rid):
                    seen[rid] = len(st.generated)
                    print(f"  req {rid} (slot {slot}): "
                          f"{st.generated[-4:]} "
                          f"({len(st.generated)} tokens)")

    results = {c.request_id: c for c in sched.completed}
    for rid in sorted(results):
        c = results[rid]
        if c.queue_wait_ms is None:  # retired before admission
            print(f"req {rid}: {len(c.tokens)} tokens, "
                  f"finished by {c.finish_reason}")
            continue
        print(f"req {rid}: {len(c.tokens)} tokens, "
              f"finished by {c.finish_reason} "
              f"(wait {c.queue_wait_ms:.1f}ms, ttft {c.ttft_ms:.1f}ms, "
              f"e2e {c.e2e_ms:.1f}ms)")
    snap = {k: v for k, v in reg.snapshot().items()
            if k.startswith("serve/") and "_bucket_le_" not in k
            and not k.endswith(("_count", "_sum"))}
    print("serve/* summary:", {k: round(v, 1) for k, v in snap.items()})

    # the latency/SLO summary: percentiles off the serve/*_ms histograms
    # (the same readout bench_gpt_decode ships), goodput off the tracker.
    # LATENCY_BUCKETS_MS matters on the get-or-create: a histogram the
    # scheduler never touched (tpot with --max-new-tokens 1) must still
    # land on the documented latency grid, not DEFAULT_BUCKETS
    from apex_tpu.observability import LATENCY_BUCKETS_MS
    latency = {}
    for short, name in (("ttft", "serve/ttft_ms"),
                        ("tpot", "serve/tpot_ms"),
                        ("queue_wait", "serve/queue_wait_ms"),
                        ("e2e", "serve/e2e_ms")):
        hist = reg.histogram(name, LATENCY_BUCKETS_MS)
        latency.update({f"{short}_p{q}_ms": round(hist.percentile(q), 2)
                        for q in (50, 95, 99)})
    goodput = slo.goodput()
    print("latency percentiles (ms):",
          {k: v for k, v in latency.items()
           if k.startswith(("ttft", "tpot"))})
    print(f"goodput {goodput:.3f} under SLO "
          f"[{'; '.join(t.describe() for t in targets)}]")
    # the resilience counts (docs/SERVING.md "Resilience"): typed
    # rejections at the admission bound, expiries against the deadline
    full_snap = reg.snapshot()
    rejected = int(full_snap.get("serve/rejected", 0.0))
    expired = int(full_snap.get("serve/expired", 0.0))
    print(f"rejected {rejected} (typed: "
          f"{[r.reason for r in rejections]}), expired {expired} "
          f"(max_queue={args.max_queue}, deadline_ms={args.deadline_ms})")
    if args.trace_out:
        trace.write_chrome_trace(args.trace_out)
        print(f"chrome request trace ({len(trace)} records, one lane "
              f"per slot) -> {args.trace_out}")
    spec = None
    if args.speculate_k:
        # same-session A/B: the identical request mix on a
        # non-speculative engine gives the honest TPOT baseline (the
        # repetitive loops a greedy tiny model falls into are exactly
        # what the n-gram source predicts)
        base_engine = ServingEngine(
            model, params, max_seqs=args.max_seqs, max_len=args.max_len,
            prefill_len=args.prefill_len, top_k=args.top_k,
            cache_dtype=jnp.int8 if args.int8_cache else jnp.bfloat16)
        base_reg = MetricsRegistry()
        SlotScheduler(base_engine, registry=base_reg).run(demo_requests())
        base_tpot = base_reg.histogram(
            "serve/tpot_ms", LATENCY_BUCKETS_MS).percentile(50)
        accept = full_snap.get("serve/spec_accept_rate", 0.0)
        spec = {"k": args.speculate_k,
                "accept_rate": accept,
                "drafted": int(full_snap.get("serve/spec_drafted", 0)),
                "accepted": int(full_snap.get("serve/spec_accepted", 0)),
                "spec_steps": int(full_snap.get("serve/spec_steps", 0)),
                "tpot_p50_ms": latency["tpot_p50_ms"],
                "baseline_tpot_p50_ms": round(base_tpot, 2),
                "tpot_delta_ms": round(base_tpot
                                       - latency["tpot_p50_ms"], 2)}
        print(f"speculative: k={spec['k']}, accepted "
              f"{spec['accepted']}/{spec['drafted']} drafts "
              f"(rate {accept:.3f}) over {spec['spec_steps']} verify "
              f"steps; tpot p50 {spec['tpot_p50_ms']:.2f}ms vs "
              f"{spec['baseline_tpot_p50_ms']:.2f}ms non-speculative "
              f"(delta {spec['tpot_delta_ms']:+.2f}ms)")
    return {"completions": results, "metrics": snap, "latency": latency,
            "goodput": goodput, "slo": [t.describe() for t in targets],
            "rejected": rejected, "expired": expired,
            "rejections": rejections, "spec": spec}


if __name__ == "__main__":
    main()
