"""Long-context attention — the capabilities the reference does not have.

Three tools from the long-context layer on one script:

1. packed-varlen attention: several documents packed into one sequence
   with ``segment_ids`` (the TPU-native ``cu_seqlens``), masked blockwise
   inside the flash kernel;
2. ring attention: the sequence sharded across every local device, k/v
   chunks rotating over the ring;
3. Ulysses: the all-to-all re-shard alternative, head-parallel inside.

    python examples/long_context.py --seq-per-device 512
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import shard_map_unchecked as shard_map
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.transformer.context_parallel import (ring_attention,
                                                   ulysses_attention)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-per-device", type=int, default=256)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=32)
    args = ap.parse_args(argv)

    cp = jax.device_count()
    mesh = Mesh(np.array(jax.devices()), ("context",))
    s = args.seq_per_device * cp
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, args.heads, s, args.head_dim),
                           jnp.float32) for _ in range(3))

    # 1. packed varlen: four documents in one sequence
    bounds = sorted(rng.choice(np.arange(1, s), 3, replace=False))
    ids = np.zeros((1, s), np.int32)
    for b in bounds:
        ids[0, b:] += 1
    packed = flash_attention(q, k, v, causal=True,
                             segment_ids=jnp.asarray(ids))
    print(f"packed-varlen over {s} tokens / 4 docs:",
          float(jnp.sum(packed ** 2)))

    spec = P(None, None, "context", None)

    def run(fn):
        return jax.jit(shard_map(
            lambda q, k, v: fn(q, k, v, "context", causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))(q, k, v)

    ring = run(ring_attention)
    print(f"ring attention over {cp} devices:", float(jnp.sum(ring ** 2)))
    if args.heads % cp == 0:
        uly = run(ulysses_attention)
        print("ulysses attention:", float(jnp.sum(uly ** 2)))
        np.testing.assert_allclose(np.asarray(ring), np.asarray(uly),
                                   rtol=2e-4, atol=2e-4)
        print("ring == ulysses == dense ✓")
    dense = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)
    return float(jnp.sum(ring ** 2))


if __name__ == "__main__":
    main()
