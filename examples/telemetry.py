"""Training telemetry end to end — the worked example for
``docs/OBSERVABILITY.md``.

A pipe x data mesh runs an amp + DDP + pipelined-1F1B + fused-optimizer
toy step with a telemetry collector reaping the in-graph metrics, a
StepReporter streaming JSONL + a Chrome trace, and the runtime compile
listeners counting (re)compiles; then the numerics health watchdog
(``HealthConfig(level="cheap")``) catches an injected inf gradient,
names the offending leaf, and writes a structured crash dump — every
layer of the subsystem in ~150 lines:

    python examples/telemetry.py --steps 5
"""

import argparse
import json
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import observability as obs
from apex_tpu.amp.scaler import DynamicLossScale, all_finite
from apex_tpu.observability import health, ingraph
from apex_tpu.optimizers import FusedSGD
from apex_tpu.optimizers.fused_sgd import SGDState
from apex_tpu.parallel.distributed import allreduce_grads
from apex_tpu.transformer.pipeline_parallel.schedules import (
    forward_backward_pipelining_without_interleaving)
from apex_tpu.utils.compat import shard_map
from apex_tpu.utils.timers import Timers


def demo_health_watchdog(out_dir, inject_at=3, steps=5):
    """The numerics watchdog end to end: a cheap-level policy watches the
    amp grad check; at step ``inject_at`` the loss gains a term whose
    gradient overflows fp32 in exactly one leaf (``['bad']``), the
    watchdog attributes it by path, and the reporter's health hook writes
    a structured CrashDump (``on_nonfinite="dump"``)."""
    hcfg = health.HealthConfig(level="cheap", on_nonfinite="dump",
                               dump_dir=out_dir)
    scaler = DynamicLossScale(init_scale=2.0)
    params = {"w": jnp.ones((4,)), "bad": jnp.ones((2,))}
    x = jnp.arange(4.0)
    big = jnp.float32(3e38)  # d/d_bad = big * big -> inf in fp32

    def loss_fn(p, poison):
        clean = jnp.sum(p["w"] * x) ** 2
        # select between inf and 0 (a plain `* poison` would backprop
        # inf * 0 = NaN into the clean steps too)
        inject = jnp.where(poison > 0, big * big, jnp.float32(0.0))
        return clean + jnp.sum(p["bad"]) * inject

    def step(params, ls, poison):
        # activate at TRACE time: the watchdog's gates are trace-time
        # checks, exactly like ingraph.record's collector stack
        with health.activate(hcfg):
            def body(params, ls, poison):
                grads = jax.grad(loss_fn)(params, poison)
                finite = all_finite(grads)   # health/grads/* + attribution
                return scaler.update(ls, finite)
            return ingraph.reap(body)(params, ls, poison)

    jsonl_path = os.path.join(out_dir, "health.jsonl")
    hook = hcfg.reporter_hook()
    ls = scaler.init()
    jit_step = jax.jit(step)  # one wrapper: compile once, reuse each step
    with obs.StepReporter([obs.JSONLSink(jsonl_path)],
                          registry=obs.MetricsRegistry(),
                          hooks=[hook]) as reporter:
        for i in range(steps):
            poison = jnp.float32(1.0 if i == inject_at else 0.0)
            ls, metrics = jit_step(params, ls, poison)
            payload = reporter.report(i, metrics=metrics)
            blame = health.decode_attribution(payload)
            print(f"health step {i}: nonfinite "
                  f"{payload['health/grads/nonfinite_count']:.0f} "
                  f"scale {payload['amp/loss_scale']:.0f}"
                  + (f"  first bad leaf: {blame['grads']}" if blame else ""))
    assert hook.dumps, "the injected inf must have produced a dump"
    dump = json.load(open(hook.dumps[0]))
    print(f"crash dump -> {hook.dumps[0]}")
    print(f"  attribution: {dump['attribution']} "
          f"(jax {dump['versions']['jax']})")
    return hook.dumps[0]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out-dir", default=None,
                    help="where telemetry.jsonl / host_trace.json land "
                         "(default: a temp dir, paths printed)")
    args = ap.parse_args(argv)
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="apex_tpu_telemetry_")
    jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
    trace_path = os.path.join(out_dir, "host_trace.json")

    # runtime layer: compile counters into the default host registry —
    # a climbing jax/compiles after step 0 would flag a recompile storm
    obs.install_compile_listeners()

    # adapt to whatever mesh the host offers (pp=dp=1 degenerates fine)
    pp = 2 if jax.device_count() >= 2 else 1
    dp = max(1, min(2, jax.device_count() // pp))
    mesh = Mesh(np.array(jax.devices()[:pp * dp]).reshape(pp, dp),
                ("pipe", "data"))
    M, mb, D = 4, 2, 16
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(pp, D, D) * 0.3, jnp.float32)
    scaler = DynamicLossScale(init_scale=2.0 ** 8)
    opt = FusedSGD(lr=1e-2, momentum=0.9)
    opt_state, ls = opt.init(ws), scaler.init()

    def stage(p, x, s):
        return jnp.tanh(x @ p["w"])

    def body(ws, opt_state, ls, micro):
        loss, grads = forward_backward_pipelining_without_interleaving(
            stage, micro, {"w": ws[0]},
            loss_fn=lambda y, m: jnp.mean(y ** 2),
            grad_scale=ls.loss_scale)
        grads = allreduce_grads(grads["w"][None], "data")  # ddp/* metrics
        finite = all_finite(grads, axis_names=("pipe",))
        new_ls = scaler.update(ls, finite)                 # amp/* metrics
        new_w, new_s = opt.step(grads, opt_state, ws,      # optim/* metrics
                                grads_finite=finite)
        return jax.lax.pmean(loss, "data"), new_w, new_s, new_ls

    def inner(*a):
        out, metrics = ingraph.reap(body)(*a)
        return out + (ingraph.aggregate(metrics, ("pipe", "data")),)

    ospec = SGDState(step=P(), momentum_buf=P("pipe"))
    step = jax.jit(lambda w, s, l, m: shard_map(
        inner, mesh=mesh,
        in_specs=(P("pipe"), ospec, P(), P(None, "data")),
        out_specs=(P(), P("pipe"), ospec, P(), P()))(w, s, l, m))

    timers = Timers()
    last = None
    with obs.StepReporter(
            [obs.JSONLSink(jsonl_path), obs.ChromeTraceSink(trace_path)],
            timers=timers, capture_spans=True) as reporter:
        for i in range(args.steps):
            micro = jnp.asarray(
                rng.randn(M, dp * mb, D), jnp.float32)
            timers("step").start()
            loss, ws, opt_state, ls, metrics = step(ws, opt_state, ls,
                                                    micro)
            timers("step").stop(wait_for=ws)
            obs.sample_memory_stats()  # HBM gauges (no-op on CPU)
            last = reporter.report(i, metrics=metrics,
                                   extra={"loss": float(loss)})
            print(f"step {i}: loss {last['loss']:.5f} "
                  f"scale {last['amp/loss_scale']:.0f} "
                  f"grad_norm {last['optim/grad_norm']:.4f} "
                  f"bubble {last['pipeline/bubble_fraction']:.3f} "
                  f"allreduce {last['ddp/allreduce_bytes']:.0f}B "
                  f"compiles {last.get('jax/compiles', 0):.0f}")

    with open(jsonl_path) as f:
        n_lines = sum(1 for _ in f)
    print(f"wrote {n_lines} JSONL events -> {jsonl_path}")
    print(f"host spans + counter tracks -> {trace_path} "
          f"(load in chrome://tracing or ui.perfetto.dev)")
    assert json.load(open(trace_path))["traceEvents"]

    demo_health_watchdog(out_dir)
    return last


if __name__ == "__main__":
    main()
