"""DCGAN mixed-precision training — ``reference:examples/dcgan/main_amp.py``
rebuilt on apex_tpu.

The reference example exists to show amp with MULTIPLE models and
optimizers (``amp.initialize([netD, netG], [optD, optG], num_losses=3)``);
the functional translation is simply: one policy, one loss-scale state and
one optimizer state per network, three scaled backward passes per step
(errD_real + errD_fake for D, errG for G). Synthetic data; tiny conv
generator/discriminator (the architecture is not the point — the
multi-loss amp wiring is).

    python examples/dcgan_amp.py --steps 5
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp import all_finite, get_policy, make_loss_scale
from apex_tpu.optimizers import FusedAdam

IMG, NZ, CH = 16, 16, 8


def _conv(x, w, stride):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _deconv(x, w, stride):
    return jax.lax.conv_transpose(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def init_nets(key):
    kd, kg = jax.random.split(key)
    kd1, kd2, kd3 = jax.random.split(kd, 3)
    kg1, kg2, kg3 = jax.random.split(kg, 3)
    std = 0.05
    netD = {
        "c1": std * jax.random.normal(kd1, (4, 4, 3, CH)),
        "c2": std * jax.random.normal(kd2, (4, 4, CH, 2 * CH)),
        "fc": std * jax.random.normal(kd3, (2 * CH * (IMG // 4) ** 2, 1)),
    }
    netG = {
        "fc": std * jax.random.normal(kg1, (NZ, 2 * CH * (IMG // 4) ** 2)),
        "d1": std * jax.random.normal(kg2, (4, 4, 2 * CH, CH)),
        "d2": std * jax.random.normal(kg3, (4, 4, CH, 3)),
    }
    return netD, netG


def discriminate(p, x):
    h = jax.nn.leaky_relu(_conv(x, p["c1"], 2), 0.2)
    h = jax.nn.leaky_relu(_conv(h, p["c2"], 2), 0.2)
    return (h.reshape(h.shape[0], -1) @ p["fc"].astype(h.dtype))[:, 0]


def generate(p, z):
    h = (z @ p["fc"].astype(z.dtype)).reshape(
        z.shape[0], IMG // 4, IMG // 4, 2 * CH)
    h = jax.nn.relu(_deconv(h, p["d1"], 2))
    return jnp.tanh(_deconv(h, p["d2"], 2))


def bce_logits(logits, target):
    # stable binary cross entropy with logits, fp32
    logits = logits.astype(jnp.float32)
    return jnp.mean(jnp.maximum(logits, 0) - logits * target
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--opt-level", default="O1")
    args = ap.parse_args(argv)

    policy = get_policy(args.opt_level)
    # one scaler per loss, as the reference's num_losses=3 (D keeps one:
    # its two losses backward into the same grads)
    scalers = [make_loss_scale(policy.loss_scale) for _ in range(2)]
    lsD, lsG = (s.init() for s in scalers)

    netD, netG = init_nets(jax.random.PRNGKey(0))
    optD, optG = FusedAdam(lr=2e-4, betas=(0.5, 0.999)), \
        FusedAdam(lr=2e-4, betas=(0.5, 0.999))
    stateD, stateG = optD.init(netD), optG.init(netG)

    rng = np.random.RandomState(0)
    real = jnp.asarray(rng.randn(args.batch, IMG, IMG, 3),
                       policy.compute_dtype)

    @jax.jit
    def train_step(netD, netG, stateD, stateG, lsD, lsG, z):
        z = z.astype(policy.compute_dtype)

        def lossD(pD):
            fake = generate(netG, z)
            errD = bce_logits(discriminate(pD, real), 1.0) + \
                bce_logits(discriminate(pD, jax.lax.stop_gradient(fake)),
                           0.0)
            return scalers[0].scale(lsD, errD), errD

        gD, errD = jax.grad(lossD, has_aux=True)(netD)
        gD = scalers[0].unscale(lsD, gD)
        finD = all_finite(gD)
        netD2, stateD = optD.step(gD, stateD, netD, grads_finite=finD)

        def lossG(pG):
            errG = bce_logits(discriminate(netD2, generate(pG, z)), 1.0)
            return scalers[1].scale(lsG, errG), errG

        gG, errG = jax.grad(lossG, has_aux=True)(netG)
        gG = scalers[1].unscale(lsG, gG)
        finG = all_finite(gG)
        netG2, stateG = optG.step(gG, stateG, netG, grads_finite=finG)
        return (netD2, netG2, stateD, stateG,
                scalers[0].update(lsD, finD), scalers[1].update(lsG, finG),
                errD, errG)

    for i in range(args.steps):
        z = jnp.asarray(np.random.RandomState(i).randn(args.batch, NZ))
        (netD, netG, stateD, stateG, lsD, lsG, errD, errG) = train_step(
            netD, netG, stateD, stateG, lsD, lsG, z)
        print(f"step {i}: errD {float(errD):.4f} errG {float(errG):.4f}")
    return float(errD), float(errG)


if __name__ == "__main__":
    main()
