"""ResNet-50 mixed-precision training — ``reference:examples/imagenet/
main_amp.py`` rebuilt on apex_tpu.

Demonstrates the O0-O3 policy surface, dynamic loss scaling with on-device
overflow skip, the FlatOptimizer tier, data-parallel training over every
local device (the DDP role), per-step timers, and checkpoint/resume.
Synthetic data by default (the reference's ``--prof`` path); swap
``synthetic_batches`` for a real input pipeline.

Run (any backend; uses all visible devices as the data axis)::

    python examples/imagenet_amp.py --opt-level O2 --steps 20
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from apex_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp import all_finite, get_policy, make_loss_scale
from apex_tpu.checkpoint import restore_checkpoint, save_checkpoint
from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                             TrainConfig)
from apex_tpu.parallel import allreduce_grads
from apex_tpu.utils.timers import Timers
from apex_tpu.utils.vma import cast_to_vma


def synthetic_batches(rng, n, per_device_batch, devices, img=64, classes=100):
    b = per_device_batch * devices
    for _ in range(n):
        yield (rng.randn(b, img, img, 3).astype(np.float32),
               rng.randint(0, classes, b))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--opt-level", default="O2",
                    choices=["O0", "O1", "O2", "O3"])
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--per-device-batch", type=int, default=4)
    ap.add_argument("--img", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--ckpt-dir", default=None,
                    help="save/resume a checkpoint here")
    args = ap.parse_args(argv)

    n_dev = jax.device_count()
    mesh = Mesh(np.array(jax.devices()), ("data",))
    cfg = TrainConfig(
        model=ModelConfig(name="resnet50", num_classes=100),
        batch=BatchConfig(global_batch_size=args.per_device_batch * n_dev,
                          micro_batch_size=args.per_device_batch),
        optimizer=OptimizerConfig(name="sgd", lr=args.lr, momentum=0.9,
                                  weight_decay=1e-4, flat=True),
        opt_level=args.opt_level)
    policy = cfg.build_policy()
    model = cfg.build_model()
    opt = cfg.build_optimizer()
    scaler = cfg.build_scaler()

    params, bn_state = model.init(jax.random.PRNGKey(0))
    opt_state = opt.init(params)
    ls = scaler.init()
    start_step = 0
    if args.ckpt_dir:
        try:
            state, host = restore_checkpoint(
                args.ckpt_dir,
                {"params": params, "bn": bn_state, "opt": opt_state,
                 "ls": ls})
            params, bn_state = state["params"], state["bn"]
            opt_state, ls = state["opt"], state["ls"]
            start_step = host["step"]
            print(f"resumed from step {start_step}")
        except FileNotFoundError:
            pass

    def loss_fn(params, bn_state, x, labels, scale):
        logits, new_bn = model(params, bn_state,
                               x.astype(policy.compute_dtype), training=True)
        onehot = jax.nn.one_hot(labels, cfg.model.num_classes)
        loss = -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits.astype(jnp.float32)) * onehot, -1))
        return loss * scale, (loss, new_bn)

    @jax.jit
    def train_step(params, bn_state, opt_state, ls, x, labels):
        def inner(params, bn_state, opt_state, ls, x, labels):
            # DDP pattern: differentiate per-replica, allreduce explicitly
            varying = jax.tree_util.tree_map(
                lambda p: cast_to_vma(p, frozenset({"data"})), params)
            grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(
                varying, bn_state, x, labels, ls.loss_scale)
            grads = allreduce_grads(grads, "data")
            grads = scaler.unscale(ls, grads)
            finite = all_finite(grads)
            new_ls = scaler.update(ls, finite)
            params, opt_state = opt.step(grads, opt_state, params,
                                         grads_finite=finite)
            new_bn = jax.tree_util.tree_map(
                lambda s: jax.lax.pmean(s, "data") if s.dtype != jnp.int32
                else s, new_bn)
            return params, new_bn, opt_state, new_ls, \
                jax.lax.pmean(loss, "data")

        return shard_map(
            inner, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P("data"), P("data")),
            out_specs=(P(), P(), P(), P(), P()))(
                params, bn_state, opt_state, ls, x, labels)

    timers = Timers()
    rng = np.random.RandomState(0)
    for step, (x, labels) in enumerate(
            synthetic_batches(rng, args.steps, args.per_device_batch,
                              n_dev, args.img, cfg.model.num_classes),
            start=start_step):
        timers("step").start()
        params, bn_state, opt_state, ls, loss = train_step(
            params, bn_state, opt_state, ls, jnp.asarray(x),
            jnp.asarray(labels))
        timers("step").stop(wait_for=loss)
        print(f"step {step}: loss {float(loss):.4f} "
              f"scale {float(ls.loss_scale):.0f}")
    timers.log(["step"], normalizer=max(args.steps, 1))

    if args.ckpt_dir:
        save_checkpoint(args.ckpt_dir,
                        {"params": params, "bn": bn_state,
                         "opt": opt_state, "ls": ls},
                        step=start_step + args.steps,
                        host_state={"step": start_step + args.steps})
        print(f"checkpointed at step {start_step + args.steps}")
    return float(loss)


if __name__ == "__main__":
    main()
