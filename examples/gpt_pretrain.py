"""GPT pretraining with hybrid TP x PP x DP — the ``reference:tests/L0/
run_transformer/run_gpt_minimal_test.py`` / ``gpt_scaling_test.py`` role
as a runnable example.

Drives the whole model-parallel toolkit from one config: vocab-parallel
embedding pipelined on stage 0, tied head + vocab-parallel loss on the
last stage, tensor-parallel layers inside each stage, data-parallel grad
averaging, Megatron sampler feeding token batches, MP-synced dynamic loss
scaling, and checkpointing.

    python examples/gpt_pretrain.py --tp 2 --pp 2 --steps 5
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.config import (BatchConfig, ModelConfig, OptimizerConfig,
                             ParallelConfig, TrainConfig)
from apex_tpu.training import GPTHybridTrainer
from apex_tpu.transformer import parallel_state


def main(argv=None, on_metrics=None):
    """``on_metrics`` (tests): called with the server's base URL while
    the ``--metrics-port`` endpoint is still live, after training."""
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers-per-stage", type=int, default=2)
    ap.add_argument("--seq", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--micro-batch", type=int, default=2)
    ap.add_argument("--num-micro", type=int, default=4)
    ap.add_argument("--zero", action="store_true",
                    help="shard optimizer state 1/dp over the data axis "
                         "(DistributedFusedAdam; reduce_scatter grads, "
                         "all_gather params)")
    ap.add_argument("--bucket-bytes", type=int, default=None,
                    help="DP-sync bucket size in bytes: route the grad "
                         "sync (and the ZeRO reduce_scatter/all_gather) "
                         "through the bucketed overlap engine in B "
                         "fixed-size flat fp32 buckets (docs/PERF.md "
                         "'DP overlap + ZeRO'; default: unbucketed)")
    ap.add_argument("--remat-policy", default=None,
                    choices=["none", "full", "selective", "offload"],
                    help="activation rematerialization policy "
                         "(apex_tpu/remat.py): selective keeps the "
                         "registry-tagged GEMM/flash outputs resident "
                         "and recomputes only the LN/gelu tier "
                         "(docs/PERF.md 'Remat & HBM')")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="enable the elastic runtime "
                         "(apex_tpu/elastic/): async checkpoints to this "
                         "dir every --save-interval steps, SIGTERM/"
                         "APEX_TPU_TERMINATE preemption handling (drain "
                         "+ final save + exit 0), and automatic bitwise "
                         "resume from the latest COMMITTED checkpoint "
                         "(docs/ROBUSTNESS.md)")
    ap.add_argument("--keep-last", type=int, default=3,
                    help="checkpoint GC depth: keep the newest N "
                         "COMMITTED checkpoints (torn dirs are never "
                         "GC'd)")
    ap.add_argument("--save-interval", type=int, default=2,
                    help="steps between async checkpoints")
    ap.add_argument("--sequence-parallel", action="store_true",
                    help="Megatron-LM sequence parallelism (tp > 1, "
                         "pp == 1, VMA jax — the trainer refuses on the "
                         "pre-VMA 0.4.x line)")
    ap.add_argument("--tp-comm-overlap", action="store_true",
                    help="ring-decomposed SP collectives overlapping "
                         "their GEMMs (implies --sequence-parallel; see "
                         "docs/PERF.md)")
    ap.add_argument("--fastpath", action="store_true",
                    help="the compound overlap preset "
                         "(TrainConfig.fastpath): ZeRO-1 with "
                         "backward-interleaved per-bucket RS/AG chains, "
                         "roofline-autotuned DP buckets "
                         "(--bucket-bytes overrides), selective remat, "
                         "and — at tp>1, pp==1 on VMA jax — "
                         "sequence-parallel tp_comm_overlap "
                         "(docs/PERF.md 'Flagship tuning')")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve the local metrics registry over HTTP "
                         "while training: /metrics in Prometheus text "
                         "exposition (registry.render_prometheus — the "
                         "single-process face of the fleet endpoint the "
                         "elastic supervisor serves; docs/"
                         "OBSERVABILITY.md 'Fleet observability'); 0 "
                         "picks an ephemeral port")
    args = ap.parse_args(argv)
    if args.tp_comm_overlap:
        args.sequence_parallel = True

    tp, pp = args.tp, args.pp
    dp = jax.device_count() // (tp * pp)
    M, mb, seq = args.num_micro, args.micro_batch, args.seq
    cfg = TrainConfig(
        model=ModelConfig(name="gpt", vocab_size=args.vocab,
                          hidden_size=args.hidden,
                          num_layers=args.layers_per_stage * pp,
                          num_attention_heads=4,
                          max_position_embeddings=seq,
                          remat_policy=args.remat_policy,
                          sequence_parallel=args.sequence_parallel,
                          tp_comm_overlap=args.tp_comm_overlap),
        parallel=ParallelConfig(tensor_model_parallel_size=tp,
                                pipeline_model_parallel_size=pp),
        batch=BatchConfig(global_batch_size=M * mb * dp,
                          micro_batch_size=mb),
        optimizer=OptimizerConfig(name="adam", lr=1e-3, weight_decay=0.0,
                                  zero=args.zero),
        opt_level="O0", ddp_bucket_bytes=args.bucket_bytes)
    if args.fastpath:
        # one declarative preset over the flags above; an explicit
        # --bucket-bytes (already in the config) is kept, otherwise the
        # pyprof roofline resolves "auto" at trainer construction
        cfg = cfg.fastpath()

    server = metrics_registry = None
    if args.metrics_port is not None:
        # the single-process face of the supervisor's fleet endpoint:
        # serve THIS process's registry (render_prometheus) — same route,
        # no aggregation layer needed at world size 1
        from apex_tpu.observability import get_registry
        from apex_tpu.observability.fleet import MetricsServer
        metrics_registry = get_registry()
        server = MetricsServer(metrics_registry.render_prometheus,
                               port=args.metrics_port)
        port = server.start()
        print(f"serving /metrics on http://127.0.0.1:{port}/metrics")

    def _finish(result):
        if server is not None:
            if on_metrics is not None:
                on_metrics(server.url)
            server.close()
        return result

    # everything below runs under the server's try/finally:
    # the exception path must not leak the listening socket
    # (_finish already closed it on the success paths; close()
    # is idempotent)
    try:
        mesh = cfg.initialize_mesh()
        trainer = GPTHybridTrainer(cfg, mesh)
        calc = cfg.build_microbatch_calculator(dp)
        assert calc.get() == M
        rng = np.random.RandomState(0)
        data = rng.randint(0, args.vocab, (10_000, seq + 1))

        if args.checkpoint_dir:
            # elastic path: seeded resumable sharded data + async checkpoints
            # + preemption-safe loop; restart the same command line to resume
            from jax.sharding import NamedSharding, PartitionSpec as P

            from apex_tpu.elastic import (ElasticRunner, PrefetchingIterator,
                                          ShardedIndexIterator,
                                          token_batch_fetcher)
            it = PrefetchingIterator(
                ShardedIndexIterator(10_000, M * dp * mb, seed=0),
                token_batch_fetcher(data, M, dp * mb, seq), depth=2,
                sharding=NamedSharding(mesh, P(None, "data")))
            try:
                runner = ElasticRunner(
                    trainer, it, args.checkpoint_dir,
                    save_interval=args.save_interval,
                    keep_last=args.keep_last,
                    on_step=lambda k, loss: print(f"step {k}: loss "
                                                  f"{float(loss):.4f}"))
                res = runner.fit(args.steps, key=jax.random.PRNGKey(0))
            finally:
                parallel_state.destroy_model_parallel()
            return _finish(res.loss)

        state = list(trainer.init_state(jax.random.PRNGKey(0)))

        # Megatron sampler drives the host data order
        sampler = cfg.build_sampler(total_samples=10_000, consumed_samples=0,
                                    data_parallel_rank=0, data_parallel_size=1,
                                    shuffle=True)
        batches = iter(sampler)

        # donated jit: stage/shared/opt_state update in place — the loop below
        # only ever touches the returned state, never a consumed buffer
        step_fn = trainer.jit_train_step()
        loss = None
        try:
            for i in range(args.steps):
                # one sampler batch == one global batch (M * dp * mb rows);
                # native memcpy row-gather packs it
                from apex_tpu._native import gather_rows
                rows = next(batches)
                chunk = gather_rows(data, rows).reshape(M, dp * mb, seq + 1)
                tokens = jnp.asarray(chunk[..., :-1])
                targets = jnp.asarray(chunk[..., 1:])
                loss, *state = step_fn(*state, tokens, targets)
                if metrics_registry is not None:
                    metrics_registry.counter("train/steps").inc()
                ls = state[-1]
                print(f"step {i}: loss {float(loss):.4f} "
                      f"scale {float(ls.loss_scale):.0f}")
        finally:
            parallel_state.destroy_model_parallel()
        return _finish(float(loss))
    finally:
        if server is not None:
            server.close()


if __name__ == "__main__":
    main()
