import time, sys
import jax, jax.numpy as jnp, numpy as np
from apex_tpu.amp.scaler import DynamicLossScale, all_finite
from apex_tpu.models import ResNet50, ResNetConfig
from apex_tpu.optimizers import FlatOptimizer, FusedSGD
from apex_tpu.utils.timers import device_fence

def run(BATCH):
    IMG = 224
    cfg = ResNetConfig(num_classes=1000, compute_dtype=jnp.bfloat16)
    model = ResNet50(cfg)
    params, bn = model.init(jax.random.PRNGKey(0))
    opt = FlatOptimizer(FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4))
    opt_state = opt.init(params)
    scaler = DynamicLossScale(init_scale=2.0**12)
    ls = scaler.init()
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(BATCH, IMG, IMG, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, BATCH))
    def loss_fn(params, bn_state, scale):
        logits, new_bn = model(params, bn_state, x, training=True)
        onehot = jax.nn.one_hot(labels, 1000)
        loss = -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))
        return loss * scale, (loss, new_bn)
    @(lambda f: jax.jit(f, donate_argnums=(0,1,2,3)))
    def step(params, bn_state, opt_state, ls):
        grads, (loss, new_bn) = jax.grad(loss_fn, has_aux=True)(params, bn_state, ls.loss_scale)
        finite = all_finite(grads)
        new_ls = scaler.update(ls, finite)
        params, opt_state = opt.step(grads, opt_state, params, grads_finite=finite, scale=1.0/ls.loss_scale)
        return params, new_bn, opt_state, new_ls
    s = (params, bn, opt_state, ls)
    for _ in range(5): s = step(*s)
    device_fence(s)
    t0=time.perf_counter(); device_fence(s); rtt=time.perf_counter()-t0
    ts=[]
    for _ in range(3):
        t0=time.perf_counter()
        for _ in range(15): s = step(*s)
        device_fence(s)
        ts.append((time.perf_counter()-t0-rtt)/15)
    print(f"batch={BATCH}: {np.mean(ts)*1e3:.2f} ms/step  {BATCH/np.mean(ts):.1f} imgs/s")

for b in [int(a) for a in sys.argv[1:]]:
    run(b)
