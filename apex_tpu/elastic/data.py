"""Deterministic, resumable, prefetching input pipeline for the dp axis.

Two layers, both pure functions of ``(seed, batch_number)`` so a restart
can reproduce any point of the stream from one integer cursor:

- :class:`ShardedIndexIterator` — a seeded per-host sampler over row
  indices: epoch ``e``'s order is a ``numpy.random.RandomState``
  permutation keyed on ``(seed, e)`` (no wall-clock entropy), each global
  batch is a contiguous slice of it, and each host takes its own
  contiguous sub-slice (``global_batch / num_hosts`` rows feeding this
  host's dp ranks). The cursor is a single integer — batches consumed —
  and :meth:`~ShardedIndexIterator.batch_indices` is random-access, so
  seek == assignment.
- :class:`PrefetchingIterator` — wraps a sampler + a host ``fetch``
  function with a ``depth``-deep ``jax.device_put`` pipeline: while the
  step consumes batch ``k``, batches ``k+1..k+depth`` are already
  dispatched host→HBM (``device_put`` is async), so the copy rides under
  the step instead of serializing with it. Its ``state_dict`` reports the
  *consumed* cursor, not the fetched one — prefetched-but-unconsumed
  batches are refetched after a restore, which is exact because batch
  ``k`` is a pure function.

The cursor rides in the checkpoint's host sidecar (see
:class:`~apex_tpu.elastic.runner.ElasticRunner`), making N steps +
preempt + restore + M steps consume byte-identical data to N+M straight
steps.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

__all__ = ["ShardedIndexIterator", "PrefetchingIterator",
           "token_batch_fetcher"]

# epoch-key mixing: a fixed odd multiplier keeps (seed, epoch) streams
# distinct without wall-clock entropy; modulo keeps RandomState's u32 seed
_EPOCH_MIX = 1_000_003


class ShardedIndexIterator:
    """Seeded, seekable per-host index sampler.

    ``next()`` yields this host's ``global_batch // num_hosts`` row
    indices for the next global batch. ``drop_last`` semantics: each
    epoch uses the first ``batches_per_epoch * global_batch`` rows of its
    permutation; the remainder is dropped (never silently wrapped).
    """

    def __init__(self, total_samples: int, global_batch: int, *,
                 seed: int, host_id: int = 0, num_hosts: int = 1,
                 shuffle: bool = True):
        if global_batch < 1 or total_samples < global_batch:
            raise ValueError(
                f"need total_samples >= global_batch >= 1, got "
                f"{total_samples} / {global_batch}")
        if num_hosts < 1 or not 0 <= host_id < num_hosts:
            raise ValueError(f"bad host grid {host_id}/{num_hosts}")
        if global_batch % num_hosts:
            raise ValueError(
                f"global_batch {global_batch} not divisible by num_hosts "
                f"{num_hosts}")
        self.total_samples = int(total_samples)
        self.global_batch = int(global_batch)
        self.seed = int(seed)
        self.host_id = int(host_id)
        self.num_hosts = int(num_hosts)
        self.shuffle = bool(shuffle)
        self.batches_per_epoch = self.total_samples // self.global_batch
        self.consumed = 0  # batches handed out so far == the cursor
        self._perm_epoch: Optional[int] = None
        self._perm: Optional[np.ndarray] = None

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        if self._perm_epoch != epoch:
            if self.shuffle:
                rs = np.random.RandomState(
                    (self.seed + _EPOCH_MIX * (epoch + 1)) % (2 ** 32))
                self._perm = rs.permutation(self.total_samples)
            else:
                self._perm = np.arange(self.total_samples)
            self._perm_epoch = epoch
        return self._perm

    def batch_indices(self, k: int) -> np.ndarray:
        """This host's row indices for global batch ``k`` — pure in
        ``(seed, k)``, the property resume correctness rests on."""
        if k < 0:
            raise ValueError(f"batch number must be >= 0, got {k}")
        epoch, b = divmod(k, self.batches_per_epoch)
        rows = self._epoch_perm(epoch)[b * self.global_batch:
                                       (b + 1) * self.global_batch]
        per_host = self.global_batch // self.num_hosts
        return rows[self.host_id * per_host:(self.host_id + 1) * per_host]

    def __iter__(self) -> "ShardedIndexIterator":
        return self

    def __next__(self) -> np.ndarray:
        out = self.batch_indices(self.consumed)
        self.consumed += 1
        return out

    # -- resume -----------------------------------------------------------
    def state_dict(self) -> Dict[str, int]:
        return {"consumed": int(self.consumed), "seed": self.seed,
                "num_hosts": self.num_hosts,
                "global_batch": self.global_batch}

    def _check_stream_identity(self, state: Dict[str, int]) -> None:
        """The fields that define the GLOBAL stream — any mismatch means
        the cursor indexes a different sequence and no reseek can fix
        it."""
        seed = state.get("seed")
        if seed is not None and int(seed) != self.seed:
            raise ValueError(
                f"data cursor was saved under seed {seed} but this "
                f"iterator is seeded with {self.seed}; resuming would "
                f"replay a different stream")
        gb = state.get("global_batch")
        if gb is not None and int(gb) != self.global_batch:
            raise ValueError(
                f"data cursor was saved with global_batch {gb} but this "
                f"iterator batches {self.global_batch} rows globally; "
                f"the cursor counts batches of the SAVED size, so "
                f"resuming would skip or replay rows. Keep the global "
                f"batch fixed across world-size changes (scale the "
                f"microbatch count instead).")

    def load_state_dict(self, state: Dict[str, int]) -> None:
        """Same-world restore. A cursor saved under a **different**
        ``num_hosts`` is rejected loudly: the per-host slice of every
        global batch is a function of ``(host_id, num_hosts)``, so a
        stale cursor would silently shift which rows each host consumes
        (rows double-consumed on some hosts, skipped on others). A
        world-size change must go through :meth:`reseek`, which
        re-derives this host's slices from the new grid."""
        self._check_stream_identity(state)
        hosts = state.get("num_hosts")
        if hosts is not None and int(hosts) != self.num_hosts:
            raise ValueError(
                f"data cursor was saved under num_hosts={hosts} but this "
                f"iterator shards for num_hosts={self.num_hosts}. "
                f"Loading it as-is would silently shift which rows each "
                f"host consumes (the per-host slice is a function of the "
                f"host grid). If the world size changed on purpose "
                f"(elastic shrink/grow), call it.reseek(state): batch k "
                f"is a pure function of (seed, k), so the GLOBAL sample "
                f"sequence is preserved and the new grid just re-slices "
                f"it.")
        self.consumed = int(state["consumed"])

    def reseek(self, state: Dict[str, int]) -> None:
        """The world-size-change restore path (elastic shrink/grow):
        accept a cursor saved under a different ``num_hosts``. Safe
        because the cursor is GLOBAL (batches consumed) and
        :meth:`batch_indices` is pure in ``(seed, k)`` — the global
        sample sequence continues exactly where the old world left it
        (no row skipped or double-consumed); only the per-host slicing
        of each batch follows the new grid. Stream identity (seed,
        global_batch) must still match."""
        self._check_stream_identity(state)
        self.consumed = int(state["consumed"])


class PrefetchingIterator:
    """Double-buffered (``depth``-deep) device prefetch over a sampler.

    ``fetch(indices) -> host batch pytree``; each fetched batch is
    ``jax.device_put`` (with ``sharding`` when given — pass the batch's
    ``NamedSharding`` so shards land on their owners) as soon as it is
    produced, ``depth`` batches ahead of consumption. ``state_dict`` /
    ``load_state_dict`` expose the *consumed* cursor; loading clears the
    prefetch buffer and seeks the sampler, so the next ``next()`` after a
    restore yields exactly the batch the interrupted run would have
    consumed.
    """

    def __init__(self, sampler: ShardedIndexIterator,
                 fetch: Callable[[np.ndarray], Any], *,
                 depth: int = 2, sharding: Any = None):
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1")
        self.sampler = sampler
        self.fetch = fetch
        self.depth = depth
        self.sharding = sharding
        self.consumed = 0
        self._buf: deque = deque()

    def _put(self, batch: Any) -> Any:
        if self.sharding is None:
            return jax.tree_util.tree_map(jax.device_put, batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self.sharding), batch)

    def _fill(self) -> None:
        while len(self._buf) < self.depth:
            self._buf.append(self._put(self.fetch(next(self.sampler))))

    def __iter__(self) -> "PrefetchingIterator":
        return self

    def __next__(self) -> Any:
        self._fill()
        batch = self._buf.popleft()
        self.consumed += 1
        self._fill()  # keep the pipeline primed while the step runs
        return batch

    @property
    def num_hosts(self) -> int:
        return self.sampler.num_hosts

    # -- resume -----------------------------------------------------------
    def state_dict(self) -> Dict[str, int]:
        # the CONSUMED cursor: prefetched-but-unconsumed batches are
        # in-flight state the restore deliberately refetches. The
        # sampler's grid identity (seed/num_hosts/global_batch) rides
        # along so a restore into a different world is caught loudly.
        state = self.sampler.state_dict()
        state["consumed"] = int(self.consumed)
        return state

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.sampler.load_state_dict(state)  # seeks sampler.consumed too
        self.consumed = int(state["consumed"])
        self._buf.clear()

    def reseek(self, state: Dict[str, int]) -> None:
        """World-size-change restore: see
        :meth:`ShardedIndexIterator.reseek`."""
        self.sampler.reseek(state)
        self.consumed = int(state["consumed"])
        self._buf.clear()


def token_batch_fetcher(data: np.ndarray, num_micro: int, rows: int,
                        seq: int) -> Callable[[np.ndarray], Any]:
    """Fetch closure for the GPT trainer: gathers ``num_micro * rows``
    dataset rows of length ``seq + 1`` and splits them into the
    ``(tokens, targets)`` pair of ``(num_micro, rows, seq)`` arrays the
    hybrid step consumes (next-token targets = the same rows shifted)."""
    data = np.asarray(data)
    if data.ndim != 2 or data.shape[1] < seq + 1:
        raise ValueError(
            f"dataset must be (N, >={seq + 1}), got {data.shape}")

    def fetch(indices: np.ndarray) -> Any:
        if len(indices) != num_micro * rows:
            raise ValueError(
                f"fetch got {len(indices)} indices, expected "
                f"{num_micro} * {rows}")
        chunk = np.take(data, indices, axis=0)[:, :seq + 1]
        chunk = chunk.reshape(num_micro, rows, seq + 1)
        return chunk[..., :-1], chunk[..., 1:]

    return fetch
