"""Localhost multi-process launcher + elastic supervisor.

The supervisor half of the multi-host runtime (the worker half —
rendezvous env protocol, ``jax.distributed.initialize`` bootstrap — is
:mod:`apex_tpu.parallel.multiproc`). :class:`LocalLauncher` spawns a
gang of ``num_processes`` worker processes (each driving its own
``devices_per_process`` virtual CPU devices: the 2-process x 4-device
localhost simulation of a multi-host TPU slice) and supervises them
through the elastic policy docs/ROBUSTNESS.md specifies:

- **heartbeats** — each worker touches ``run_dir/hb/rank_<r>`` every
  step (:class:`Heartbeat`); the supervisor treats a stale heartbeat as
  a hung rank (a SIGKILLed peer leaves survivors stuck inside gloo
  collectives — observed live — so liveness cannot be inferred from
  process exit alone).
- **gang failure domain** — ranks of one jax.distributed world share a
  coordinator and open collectives; one rank's death poisons the rest
  (coordination-service abort or a gloo connection error at the next
  collective). The supervisor therefore tears down the WHOLE gang on any
  failure (SIGTERM, grace, then SIGKILL — survivors stuck in native
  collectives ignore SIGTERM) and relaunches it with a fresh coordinator
  port; relaunched workers resume from the last COMMITTED checkpoint.
- **bounded restart-with-backoff** — up to ``max_restarts`` relaunches
  at the SAME world size (transient deaths: OOM-kill, spurious runtime
  abort), with exponential backoff between rounds.
- **shrink** — when the restart budget at a world size is exhausted, the
  failure is declared permanent and the gang relaunches with ``world-1``
  processes (ranks relabel ``0..world-1``). Survivors restore the last
  COMMITTED checkpoint onto the smaller mesh — the dp-reshard path in
  :mod:`apex_tpu.elastic.runner` / :mod:`apex_tpu.elastic.reshard` —
  and continue the run. Exhausting the policy below ``min_processes``
  returns ``LaunchReport(succeeded=False)`` (CLI exit 1); exceptions
  are reserved for supervisor bugs.

Metrics (host registry, docs/OBSERVABILITY.md): ``elastic/world_size``
(gauge), ``elastic/restarts`` / ``elastic/shrinks`` (counters),
``elastic/heartbeat_age_s`` (gauge, max staleness over live ranks).
Fleet layer (docs/OBSERVABILITY.md "Fleet observability"): the
supervisor merges every rank's published registry snapshot
(:class:`~apex_tpu.observability.fleet.FleetAggregator`) into the
``fleet/*`` straggler gauges, writes a
:class:`~apex_tpu.observability.fleet.PostmortemReport` under
``run_dir/postmortem/`` on every non-ok round, and (``metrics_port``)
serves the merged view on ``/metrics``+``/fleet``. The hang detector
distinguishes liveness from PROGRESS: a rank whose heartbeat mtime
keeps moving but whose reported step stays put for a full
``heartbeat_timeout_s`` is declared stalled (cause ``"stall"``).

Exit discipline: :func:`_supervisor_exit` is the ONE blessed process
exit in this package besides ``AutoResume.request_resume`` — the CLI
must propagate the gang's success as an exit code, and the
``ast-elastic-exits`` analysis rule pins it to exactly this chokepoint
(everything else raises, so supervisor bugs stay distinguishable from
worker failures).

CLI: ``python -m apex_tpu.elastic.launch -n 2 -- python worker.py ...``
(also reachable as ``python -m apex_tpu.parallel.multiproc``).
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from apex_tpu.observability.registry import MetricsRegistry, get_registry
from apex_tpu.parallel import multiproc

__all__ = ["Heartbeat", "LaunchReport", "LocalLauncher", "RoundResult",
           "main"]

_HB_DIR = "hb"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class Heartbeat:
    """File-mtime heartbeat between one worker rank and the supervisor.

    Worker side: ``Heartbeat(run_dir).beat(step)`` each step — an atomic
    JSON payload (``{"schema", "step", "time"}``) into
    ``rank_<r>.json`` FIRST, then the atomic tmp+rename mtime touch of
    the legacy ``rank_<r>`` text file (``"<step> <unix_time>"``). The
    ordering matters: the mtime file is the supervisor's change
    detector, so by the time an mtime moves the step payload it vouches
    for is already on disk — the progress (stall) detector never reads a
    step older than the beat it observed. Supervisor side: :meth:`age_s`
    reads staleness off the file mtime — no shared memory, no sockets,
    works across SIGKILL (the files outlive the writer, so the
    supervisor can also read :meth:`last_step` of a dead rank when
    deciding what the restart will resume from).
    """

    SCHEMA = 1

    def __init__(self, run_dir: str, rank: Optional[int] = None):
        if rank is None:
            rank = multiproc.process_id()
        self.rank = int(rank)
        self.path = os.path.join(run_dir, _HB_DIR, f"rank_{self.rank}")
        os.makedirs(os.path.dirname(self.path), exist_ok=True)

    def beat(self, step: int = 0) -> None:
        import json
        payload = {"schema": self.SCHEMA, "step": int(step),
                   "time": time.time()}
        tmp = f"{self.path}.json.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, f"{self.path}.json")
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{int(step)} {time.time()}\n")
        os.replace(tmp, self.path)

    @staticmethod
    def age_s(run_dir: str, rank: int,
              default: Optional[float] = None) -> Optional[float]:
        """Seconds since rank ``rank`` last beat; ``default`` when it
        never has. Wall-clock (mtime-based) — a debugging convenience;
        the supervisor's hang detection uses the mtime only as a change
        detector and ages with monotonic deltas
        (:meth:`LocalLauncher._heartbeat_age`), so a system clock step
        cannot fake staleness there."""
        path = os.path.join(run_dir, _HB_DIR, f"rank_{rank}")
        try:
            return max(0.0, time.time() - os.stat(path).st_mtime)
        except OSError:
            return default

    @staticmethod
    def last_step(run_dir: str, rank: int) -> Optional[int]:
        """The last completed step rank ``rank`` reported — read from
        the JSON payload when present, falling back to the legacy text
        format (external writers that only speak the text protocol stay
        supported; pinned by the stub-worker launcher tests)."""
        import json
        path = os.path.join(run_dir, _HB_DIR, f"rank_{rank}")
        try:
            with open(path + ".json") as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError, TypeError):
            pass
        try:
            with open(path) as f:
                return int(f.read().split()[0])
        except (OSError, ValueError, IndexError):
            return None

    @staticmethod
    def clear(run_dir: str) -> None:
        """Remove every rank's heartbeat (between rounds: a stale file
        from the previous gang must not vouch for the new one)."""
        shutil.rmtree(os.path.join(run_dir, _HB_DIR), ignore_errors=True)


@dataclasses.dataclass
class RoundResult:
    """One gang launch: its world size, every rank's exit code (negative
    = killed by that signal; ``None`` never materializes — teardown
    always reaps), why the round ended, and — for every non-ok round —
    the path of the :class:`~apex_tpu.observability.fleet
    .PostmortemReport` JSON written at teardown (the ``.md`` twin sits
    next to it)."""

    world_size: int
    returncodes: Dict[int, int]
    cause: str  # "ok" | "exit" | "heartbeat" | "stall" | "timeout"
    postmortem: Optional[str] = None


@dataclasses.dataclass
class LaunchReport:
    """What :meth:`LocalLauncher.run` did end to end."""

    succeeded: bool     # the gang completed (every rank exited 0)
    world_size: int     # world size of the last round actually run
    restarts: int       # same-world relaunches taken
    shrinks: int        # world-size reductions taken
    rounds: List[RoundResult]


class LocalLauncher:
    """Spawn + supervise a localhost multi-process worker gang.

    ``worker_argv`` is the full worker command line; each rank gets it
    verbatim plus the :mod:`~apex_tpu.parallel.multiproc` env block
    (coordinator address on a fresh port per round, world size, rank,
    ``devices_per_process``, ``run_dir``). Worker stdout/stderr stream to
    ``run_dir/logs/round<k>_rank<r>.log``.

    The policy knobs mirror the docstring above: ``max_restarts``
    same-world relaunches (backoff ``restart_backoff_s * 2**k``), then
    shrink by one process per permanent failure down to
    ``min_processes``; ``heartbeat_timeout_s`` declares a silent rank
    hung; ``round_timeout_s`` bounds a whole round; ``grace_s`` is the
    SIGTERM→SIGKILL escalation window at teardown.
    """

    def __init__(self, worker_argv: Sequence[str], *, num_processes: int,
                 run_dir: str, devices_per_process: int = 4,
                 min_processes: int = 1, max_restarts: int = 1,
                 restart_backoff_s: float = 0.5,
                 heartbeat_timeout_s: float = 300.0,
                 round_timeout_s: float = 900.0, grace_s: float = 5.0,
                 poll_s: float = 0.05,
                 env: Optional[Dict[str, str]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 metrics_port: Optional[int] = None,
                 fleet_refresh_s: float = 0.5):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not 1 <= min_processes <= num_processes:
            raise ValueError(
                f"need 1 <= min_processes <= num_processes, got "
                f"{min_processes}/{num_processes}")
        if max_restarts < 0:
            raise ValueError("max_restarts must be >= 0")
        self.worker_argv = list(worker_argv)
        self.num_processes = num_processes
        self.devices_per_process = devices_per_process
        self.run_dir = run_dir
        self.min_processes = min_processes
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.round_timeout_s = round_timeout_s
        self.grace_s = grace_s
        self.poll_s = poll_s
        self.env = env
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._m_world = reg.gauge("elastic/world_size")
        self._m_restarts = reg.counter("elastic/restarts")
        self._m_shrinks = reg.counter("elastic/shrinks")
        self._m_hb_age = reg.gauge("elastic/heartbeat_age_s")
        # the fleet layer (observability/fleet.py): rank snapshots merged
        # into one registry (/metrics, fleet/* straggler gauges) and the
        # gang postmortems written at teardown
        from apex_tpu.observability.fleet import FleetAggregator
        self.fleet = FleetAggregator(run_dir, registry=reg)
        self.metrics_port = metrics_port
        self.bound_metrics_port: Optional[int] = None
        self.fleet_refresh_s = fleet_refresh_s
        self._fleet_refreshed = 0.0  # monotonic of the last refresh
        os.makedirs(os.path.join(run_dir, "logs"), exist_ok=True)

    # -- one gang ---------------------------------------------------------
    def _spawn(self, world: int, round_idx: int) -> List[subprocess.Popen]:
        port = _free_port()  # fresh coordinator per round: the previous
        # gang's service may still hold the old one in TIME_WAIT
        procs = []
        for rank in range(world):
            env = dict(os.environ)
            if self.env:
                env.update(self.env)
            env.update(multiproc.process_env(
                rank, world, f"127.0.0.1:{port}",
                local_devices=self.devices_per_process,
                run_dir=self.run_dir))
            log_path = os.path.join(self.run_dir, "logs",
                                    f"round{round_idx}_rank{rank}.log")
            with open(log_path, "ab") as log:
                procs.append(subprocess.Popen(
                    self.worker_argv, env=env, stdout=log,
                    stderr=subprocess.STDOUT))
        return procs

    def _teardown(self, procs: List[subprocess.Popen]) -> None:
        """Reap the whole gang: SIGTERM, grace, SIGKILL. The SIGKILL leg
        is not optional politeness — a survivor of a dead peer sits
        inside a native gloo collective and never services SIGTERM."""
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + self.grace_s
        while time.monotonic() < deadline and any(
                p.poll() is None for p in procs):
            time.sleep(self.poll_s)
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in procs:
            p.wait()

    def _heartbeat_age(self, procs: List[subprocess.Popen],
                       started: float, seen: Dict[int, list]) -> float:
        """Max staleness over ranks still running; a rank that never
        beat ages from the round start (it may be compiling — the
        timeout budget covers first-compile).

        The file mtime is used only as a CHANGE detector: ``seen`` maps
        rank -> [last mtime observed, monotonic time of that
        observation, last reported step, monotonic time the STEP last
        advanced], and age is the monotonic delta since the mtime last
        moved. Aging ``time.time() - st_mtime`` directly would mix
        the wall clock into a monotonic budget — an NTP step or VM
        suspend/resume larger than ``heartbeat_timeout_s`` would then
        declare a perfectly healthy gang hung and tear it down.

        The step columns feed :meth:`_stalled_ranks` — liveness (mtime
        moving) is tracked separately from progress (step advancing),
        because a rank wedged inside one step keeps beating forever."""
        now = time.monotonic()
        ages = []
        for rank, p in enumerate(procs):
            if p.poll() is not None:
                continue
            path = os.path.join(self.run_dir, _HB_DIR, f"rank_{rank}")
            try:
                mtime = os.stat(path).st_mtime
            except OSError:
                ages.append(now - started)  # never beat yet
                continue
            last = seen.get(rank)
            if last is None or last[0] != mtime:
                step = Heartbeat.last_step(self.run_dir, rank)
                if last is None or step is None or step != last[2]:
                    seen[rank] = [mtime, now, step, now]
                else:  # mtime moved, step did not: keep the step clock
                    seen[rank] = [mtime, now, step, last[3]]
                ages.append(0.0)
            else:
                ages.append(now - last[1])
        return max(ages) if ages else 0.0

    def _stalled_ranks(self, procs: List[subprocess.Popen],
                       seen: Dict[int, list]) -> List[int]:
        """Ranks whose heartbeat mtime keeps moving but whose reported
        step has not advanced for a full ``heartbeat_timeout_s`` budget
        — liveness is not progress (a worker spinning inside a wedged
        collective, or deadlocked after a peer's silent failure, still
        touches its heartbeat). The budget also covers first-compile:
        the step clock starts at the first observed beat, exactly like
        the never-beat clock starts at round start. Ranks whose
        heartbeat carries no parseable step are exempt (external
        writers may only speak the mtime protocol)."""
        now = time.monotonic()
        out = []
        for rank, p in enumerate(procs):
            if p.poll() is not None:
                continue
            last = seen.get(rank)
            if last is None or last[2] is None:
                continue
            if now - last[3] > self.heartbeat_timeout_s:
                out.append(rank)
        return out

    def _fleet_refresh(self, force: bool = False) -> None:
        """Throttled fleet merge: publish the ``fleet/*`` straggler
        gauges off the rank snapshots. Never lethal — the supervisor
        must keep supervising even over a corrupt fleet dir."""
        now = time.monotonic()
        if not force and now - self._fleet_refreshed < self.fleet_refresh_s:
            return
        self._fleet_refreshed = now
        try:
            self.fleet.refresh()
        except Exception:
            pass

    def _run_round(self, world: int, round_idx: int) -> RoundResult:
        Heartbeat.clear(self.run_dir)
        self.fleet.clear()  # rank files of the previous gang must not
        # vouch for (or skew) this one — same rule as the heartbeats
        procs = self._spawn(world, round_idx)
        started = time.monotonic()
        hb_seen: Dict[int, list] = {}
        cause = "timeout"
        stalled: List[int] = []
        pre_rcs: Dict[int, Optional[int]] = {}
        try:
            while True:
                time.sleep(self.poll_s)
                rcs = [p.poll() for p in procs]
                if any(rc not in (None, 0) for rc in rcs):
                    cause = "exit"
                    break
                if all(rc == 0 for rc in rcs):
                    cause = "ok"
                    break
                age = self._heartbeat_age(procs, started, hb_seen)
                self._m_hb_age.set(age)
                self._fleet_refresh()
                if age > self.heartbeat_timeout_s:
                    cause = "heartbeat"
                    break
                stalled = self._stalled_ranks(procs, hb_seen)
                if stalled:
                    cause = "stall"
                    break
                if time.monotonic() - started > self.round_timeout_s:
                    cause = "timeout"
                    break
        finally:
            # exit codes BEFORE teardown: ranks the supervisor is about
            # to SIGKILL must not be framed as self-dead in the
            # postmortem (only a rank that died on its own carries a
            # pre-teardown code)
            pre_rcs = {r: p.poll() for r, p in enumerate(procs)}
            self._teardown(procs)
        postmortem = None
        if cause != "ok":
            postmortem = self._write_postmortem(
                round_idx, world, cause, pre_rcs, hb_seen, stalled,
                started)
        self._fleet_refresh(force=True)  # the final snapshots (ranks
        # publish on exit) reach /metrics and the fleet/* gauges even
        # after the gang is gone
        return RoundResult(
            world_size=world,
            returncodes={r: p.returncode for r, p in enumerate(procs)},
            cause=cause, postmortem=postmortem)

    def _write_postmortem(self, round_idx: int, world: int, cause: str,
                          pre_rcs: Dict[int, Optional[int]],
                          hb_seen: Dict[int, list],
                          stalled: List[int],
                          started: float) -> Optional[str]:
        """Harvest the dead gang into ``run_dir/postmortem/round<k>``
        (strict JSON + markdown). Monotonic heartbeat ages come from the
        supervisor's own change detector (``hb_seen``), not file
        mtimes, so the culprit ordering survives wall-clock steps; a
        rank that NEVER beat ages from the round start — the same clock
        the hang detector used to tear the gang down, so a
        wedged-before-first-beat rank is nameable as the culprit
        instead of dissolving into "unknown". Failure to write is
        logged into the report path as None, never raised — forensics
        must not mask the failure being dissected."""
        from apex_tpu.observability.fleet import PostmortemReport
        now = time.monotonic()
        ages = {}
        for rank in range(world):
            if rank in hb_seen:
                ages[rank] = now - hb_seen[rank][1]
            elif pre_rcs.get(rank) is None:
                # alive pre-teardown and never beat: wedged before its
                # first heartbeat (e.g. inside distributed init) — ages
                # from round start, exactly like the hang detector aged
                # it. A rank that EXITED without beating keeps no
                # supervisor age (clean fast exits must not be framed).
                ages[rank] = now - started
        try:
            report = PostmortemReport.collect(
                self.run_dir, round_index=round_idx, world_size=world,
                cause=cause, returncodes=pre_rcs, heartbeat_ages=ages,
                stalled_ranks=stalled,
                heartbeat_timeout_s=self.heartbeat_timeout_s)
            json_path, _ = report.write(
                os.path.join(self.run_dir, "postmortem"))
            return json_path
        except Exception:
            return None

    # -- the supervisor loop ----------------------------------------------
    def run(self) -> LaunchReport:
        """Launch and supervise until the gang completes (every rank
        exits 0) or the elastic policy is exhausted (the world would
        shrink below ``min_processes``). Policy exhaustion is an
        OUTCOME, not a supervisor bug: it returns
        ``LaunchReport(succeeded=False, ...)`` with the per-round
        forensics (worker logs stay under ``run_dir/logs``), and the
        CLI maps it to exit code 1 through ``_supervisor_exit`` —
        exceptions out of ``run`` are reserved for real supervisor
        failures.

        With ``metrics_port`` set (0 = ephemeral; the bound port lands
        in ``bound_metrics_port``), the supervisor serves the MERGED
        view for the whole run: ``/metrics`` renders its own
        ``elastic/``+``fleet/`` registry combined with every rank
        snapshot (counters summed, gauges averaged) in Prometheus text
        format, ``/fleet`` returns the raw merged JSON. The server
        lives in :mod:`apex_tpu.observability.fleet` and adds no
        process-exit path to this package."""
        server = None
        if self.metrics_port is not None:
            from apex_tpu.observability.fleet import MetricsServer

            def _render() -> str:
                # one disk read + one cross-rank merge per scrape, and
                # the fleet/* gauges describe the same snapshot
                # generation the rendered counters came from (two
                # independent reads could straddle a rank's os.replace)
                _, merged = self.fleet.scrape()
                return merged.render_prometheus()

            server = MetricsServer(_render, self.fleet.view,
                                   port=self.metrics_port)
            self.bound_metrics_port = server.start()
        try:
            world = self.num_processes
            restarts = shrinks = attempts_at_world = 0
            rounds: List[RoundResult] = []
            while True:
                self._m_world.set(world)
                result = self._run_round(world, len(rounds))
                rounds.append(result)
                if result.cause == "ok":
                    return LaunchReport(succeeded=True, world_size=world,
                                        restarts=restarts,
                                        shrinks=shrinks, rounds=rounds)
                if attempts_at_world < self.max_restarts:
                    # transient-death policy: same world, backoff,
                    # relaunch
                    attempts_at_world += 1
                    restarts += 1
                    self._m_restarts.inc()
                    time.sleep(self.restart_backoff_s
                               * (2.0 ** (attempts_at_world - 1)))
                    continue
                # restart budget exhausted: the failure is permanent at
                # this world size. A shrink is only a shrink if the
                # smaller gang may actually launch — exhausting the
                # policy AT min_processes must not count (or emit) a
                # world-size reduction that never happened.
                if world - 1 < self.min_processes:
                    return LaunchReport(
                        succeeded=False, world_size=world,  # last RUN
                        restarts=restarts, shrinks=shrinks,
                        rounds=rounds)
                world -= 1
                shrinks += 1
                attempts_at_world = 0
                self._m_shrinks.inc()
        finally:
            if server is not None:
                server.close()


def main(argv=None) -> int:
    """CLI: ``python -m apex_tpu.elastic.launch -n N [opts] -- worker
    cmd...``. Returns the process exit code (0 = the gang completed)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.elastic.launch",
        description="localhost multi-process elastic supervisor")
    ap.add_argument("-n", "--num-processes", type=int, required=True)
    ap.add_argument("--devices-per-process", type=int, default=4)
    ap.add_argument("--run-dir", default=None)
    ap.add_argument("--min-processes", type=int, default=1)
    ap.add_argument("--max-restarts", type=int, default=1)
    ap.add_argument("--heartbeat-timeout", type=float, default=300.0)
    ap.add_argument("--round-timeout", type=float, default=900.0)
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve the merged fleet registry over HTTP: "
                         "/metrics (Prometheus text) and /fleet (raw "
                         "merged JSON); 0 picks an ephemeral port")
    ap.add_argument("worker", nargs=argparse.REMAINDER,
                    help="worker command line (prefix with --)")
    args = ap.parse_args(argv)
    # strip only the LEADING separator: a later "--" belongs to the
    # worker's own command line and must pass through verbatim
    worker = list(args.worker)
    if worker and worker[0] == "--":
        worker = worker[1:]
    if not worker:
        ap.error("missing worker command (pass it after --)")
    import tempfile

    run_dir = args.run_dir or tempfile.mkdtemp(prefix="apex_tpu_launch_")
    launcher = LocalLauncher(
        worker, num_processes=args.num_processes,
        devices_per_process=args.devices_per_process, run_dir=run_dir,
        min_processes=args.min_processes, max_restarts=args.max_restarts,
        heartbeat_timeout_s=args.heartbeat_timeout,
        round_timeout_s=args.round_timeout,
        metrics_port=args.metrics_port)
    report = launcher.run()
    return 0 if report.succeeded else 1


def _supervisor_exit(code: int) -> None:
    """The single blessed process exit of the supervisor CLI — the
    ``ast-elastic-exits`` analysis rule pins ``sys.exit`` in this
    package to exactly here (plus ``AutoResume.request_resume`` for the
    runner's preemption path); every other failure must raise."""
    sys.exit(int(code))


if __name__ == "__main__":
    _supervisor_exit(main())
