"""Deterministic fault injection for the elastic runtime.

A :class:`FaultPlan` scripts *when* and *how* a training run fails, so
preemption and filesystem faults are a tested path, not a hope:

- ``sigterm_at_step=K`` — deliver a real ``SIGTERM`` to this process
  right before step ``K`` runs (the Cloud-TPU preemption signal; the
  installed :class:`~apex_tpu.utils.autoresume.AutoResume` handler
  latches it and the runner drains + saves + exits inside the grace
  window).
- ``save_errors={step: n}`` — raise ``n`` transient ``OSError``\\ s from
  the first ``n`` serialization attempts of the checkpoint at ``step``,
  exercising the :class:`~apex_tpu.elastic.ckpt.AsyncCheckpointer`
  bounded retry-with-backoff.
- ``tear_after_step=K`` — after the checkpoint at step ``K`` commits,
  remove its COMMITTED marker: the on-disk picture of a writer killed
  mid-save. Restore must fall back to the previous COMMITTED step, with
  a warning naming the torn one.
- ``slow_save_s=t`` — stretch every serialization by ``t`` seconds, to
  widen the in-flight window deterministically (so a preemption reliably
  lands while a save is being written).
- ``kill_process={rank: K}`` — the multi-host failure domain: deliver
  ``SIGKILL`` (not SIGTERM — no drain, no final save, no grace window)
  to THIS process right before its step ``K``, but only when this
  process's multiproc rank (:func:`apex_tpu.parallel.multiproc
  .process_id`) is ``rank``. The whole-process murder the elastic
  supervisor (:mod:`apex_tpu.elastic.launch`) must detect, and the
  survivors must shrink-resume from. After a shrink, surviving ranks
  are relabeled ``0..world-1``; key the kill on a NON-ZERO rank so the
  shrunk world does not re-trigger it (and rank 0 usually hosts the
  rendezvous coordinator — killing it tests the coordinator, not a
  worker).

**Serving faults** (consumed by
:class:`~apex_tpu.serving.scheduler.SlotScheduler` — steps here are
DECODE steps, 1-based, counted by the scheduler):

- ``poison_logits={step: slot}`` — at decode step ``step``, inject NaN
  into ``slot``'s sampling-path logits (an array-argument add inside the
  already-compiled quarantine decode program — zero extra compiles).
  The poison-slot quarantine must retire exactly that slot with
  ``finish_reason="poisoned"`` and leave every other stream untouched.
- ``slow_decode_s=t`` — stretch every decode step by ``t`` seconds
  (host-side sleep), deterministically inflating TPOT/e2e so deadline
  expiry and SLO-brownout paths fire on schedule.
- ``flood={step: n}`` — the overload schedule: the loop driving the
  scheduler submits ``n`` extra requests right before decode step
  ``step`` (the scheduler cannot fabricate requests, so this hook is
  read by the driver — see :meth:`flood_n`).

Plans are *explicitly seeded* and fully serializable: :meth:`sample`
(training) and :meth:`sample_serving` (serving chaos) derive one from an
integer seed via ``numpy.random.RandomState`` (no wall-clock entropy
anywhere), and :meth:`to_json` / :meth:`from_json` carry a plan across a
process boundary (the kill-and-resume subprocess tests hand the child
its plan on the command line).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["FaultPlan"]


@dataclasses.dataclass
class FaultPlan:
    """A scripted failure schedule. All fields optional; an empty plan
    injects nothing (every hook is a no-op)."""

    sigterm_at_step: Optional[int] = None
    save_errors: Dict[int, int] = dataclasses.field(default_factory=dict)
    tear_after_step: Optional[int] = None
    slow_save_s: float = 0.0
    kill_process: Dict[int, int] = dataclasses.field(default_factory=dict)
    # serving faults (decode-step keyed, 1-based; see module docstring)
    poison_logits: Dict[int, int] = dataclasses.field(default_factory=dict)
    slow_decode_s: float = 0.0
    flood: Dict[int, int] = dataclasses.field(default_factory=dict)
    seed: Optional[int] = None  # provenance when built via sample()

    # -- injection hooks --------------------------------------------------
    def before_step(self, step: int) -> None:
        """Runner hook, called before step ``step`` executes. Delivers
        the scripted SIGTERM to *this* process — through the real signal
        machinery, so the AutoResume handler path is the one exercised.
        ``kill_process`` entries deliver SIGKILL instead (a hard
        whole-process death) when this process's multiproc rank
        matches."""
        if self.kill_process:
            from apex_tpu.parallel.multiproc import process_id
            k = self.kill_process.get(process_id())
            if k is not None and step == k:
                os.kill(os.getpid(), signal.SIGKILL)
        if self.sigterm_at_step is not None and step == self.sigterm_at_step:
            os.kill(os.getpid(), signal.SIGTERM)

    def on_save_attempt(self, step: int, attempt: int) -> None:
        """:class:`~apex_tpu.elastic.ckpt.AsyncCheckpointer` fault hook:
        called before serialization attempt ``attempt`` (0-based) of the
        checkpoint at ``step``."""
        if self.slow_save_s > 0.0:
            time.sleep(self.slow_save_s)
        if attempt < int(self.save_errors.get(step, 0)):
            raise OSError(
                f"injected transient save fault (step {step}, attempt "
                f"{attempt})")

    def after_save(self, step: int, path: str) -> None:
        """Post-commit hook: tears the scripted checkpoint by removing
        its COMMITTED marker (simulating a writer killed between the
        array write and the commit)."""
        if self.tear_after_step is not None and step == self.tear_after_step:
            from apex_tpu.checkpoint import _COMMIT_FILE
            marker = os.path.join(path, _COMMIT_FILE)
            if os.path.exists(marker):
                os.remove(marker)

    # -- serving hooks ----------------------------------------------------
    def before_decode(self, step: int) -> None:
        """:class:`~apex_tpu.serving.scheduler.SlotScheduler` hook,
        called right before decode step ``step`` dispatches: applies the
        scripted ``slow_decode_s`` stretch."""
        if self.slow_decode_s > 0.0:
            time.sleep(self.slow_decode_s)

    def poison_slot(self, step: int) -> Optional[int]:
        """The slot whose sampling-path logits the scheduler must NaN at
        decode step ``step`` (None: no injection this step). Injection
        requires the engine's quarantine check to be compiled in; the
        scheduler refuses a poison plan on a quarantine-off engine
        instead of silently dropping the fault."""
        return self.poison_logits.get(step)

    def flood_n(self, step: int) -> int:
        """How many extra requests the DRIVER should submit right before
        decode step ``step`` (the scheduler cannot fabricate requests;
        chaos tests and the dryrun leg read this)."""
        return int(self.flood.get(step, 0))

    # -- construction / transport ----------------------------------------
    @classmethod
    def sample(cls, seed: int, total_steps: int, *,
               save_interval: int = 1, transient_errors: bool = True,
               tear: bool = False) -> "FaultPlan":
        """Derive a plan deterministically from ``seed``: one preemption
        at a uniform step in ``[1, total_steps)``, optionally 1-2
        transient save errors, optionally tearing the preemption-time
        checkpoint.

        ``save_interval`` must match the runner's: the error step is
        snapped to a step at which a save actually happens (a multiple of
        the interval ≤ the preemption step, else the preemption save
        itself) — an error keyed to a never-saved step would silently
        inject nothing and the retry path would go untested.
        """
        if total_steps < 2:
            raise ValueError("total_steps must be >= 2 to place a fault")
        if save_interval < 1:
            raise ValueError("save_interval must be >= 1")
        rs = np.random.RandomState(seed)
        k = int(rs.randint(1, total_steps))
        plan = cls(sigterm_at_step=k, seed=int(seed))
        if transient_errors:
            save_steps = list(range(save_interval, k + 1, save_interval))
            if not save_steps:
                save_steps = [k]  # only the preemption save exists
            plan.save_errors = {int(rs.choice(save_steps)):
                                int(rs.randint(1, 3))}
        if tear:
            plan.tear_after_step = k
        return plan

    @classmethod
    def sample_serving(cls, seed: int, total_steps: int, *,
                       max_slots: int, flood_n: int = 4,
                       slow_decode_s: float = 0.0) -> "FaultPlan":
        """Derive a serving chaos plan deterministically from ``seed``:
        one flood of ``flood_n`` extra requests early in the run (while
        slots are still busy), one poisoned slot at a later decode step,
        and an optional per-step decode stretch — the flood + poison +
        slow-step combination the chaos test drives in one run.

        The poison step is drawn from the second half of
        ``[1, total_steps)`` so the flood has already saturated every
        slot (a poison aimed at an idle slot injects nothing); the slot
        is uniform over ``[0, max_slots)``.
        """
        if total_steps < 4:
            raise ValueError("total_steps must be >= 4 to place "
                             "flood and poison faults")
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        rs = np.random.RandomState(seed)
        flood_step = int(rs.randint(1, max(2, total_steps // 4)))
        poison_step = int(rs.randint(total_steps // 2, total_steps))
        return cls(flood={flood_step: int(flood_n)},
                   poison_logits={poison_step: int(rs.randint(max_slots))},
                   slow_decode_s=float(slow_decode_s), seed=int(seed))

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        for key in ("save_errors", "kill_process", "poison_logits",
                    "flood"):
            d[key] = {str(k): v for k, v in getattr(self, key).items()}
        return json.dumps(d)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        d = json.loads(text)
        for key in ("save_errors", "kill_process", "poison_logits",
                    "flood"):
            d[key] = {int(k): int(v) for k, v in d.get(key, {}).items()}
        return cls(**d)
