"""Async (off-critical-path) checkpointing.

CheckFreq-style split of :func:`apex_tpu.checkpoint.save_checkpoint` into
two phases with different latency budgets:

1. **snapshot** (:func:`host_snapshot`) — runs on the training thread
   inside the step cadence: one ``jax.device_get`` pulls the state pytree
   to host memory. This is the only part the step loop waits on; it costs
   a device→host copy, never a disk write.
2. **serialize** — runs on a background writer thread: the orbax write,
   the ``host.json`` sidecar, the COMMITTED marker, and ``keep_last`` GC,
   exactly the :func:`~apex_tpu.checkpoint.save_checkpoint` protocol
   (COMMITTED is written LAST, so a process killed mid-serialize leaves a
   torn dir that :func:`~apex_tpu.checkpoint.restore_checkpoint` skips
   loudly, never a COMMITTED-but-partial one).

At most one save is in flight: a new :meth:`AsyncCheckpointer.save`
first drains the previous one (and re-raises its failure, if any — a
background save error is never silent). Transient filesystem errors
(``OSError``) during serialization are retried with bounded exponential
backoff before the save is declared failed.

Snapshot scope: ``jax.device_get`` requires every shard to be addressable
from this process (single-controller / fully-addressable deployments —
the CPU mesh, single-host TPU slices). Multi-controller jobs should call
the synchronous collective :func:`~apex_tpu.checkpoint.save_checkpoint`
directly.

Metrics (host registry, PR 1): ``ckpt/save_ms`` (histogram, serialize
wall per save), ``ckpt/bytes`` (counter, snapshot bytes handed to the
writer), ``ckpt/inflight`` (gauge, 0/1), ``ckpt/saves`` (counter,
committed saves), ``ckpt/retries`` (counter, transient-error retries).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import checkpoint as _ckpt
from apex_tpu.observability.registry import MetricsRegistry, get_registry

__all__ = ["AsyncCheckpointer", "host_snapshot", "owned_copy",
           "snapshot_nbytes"]


def host_snapshot(state: Any) -> Any:
    """Device→host copy of ``state``, ready for off-thread serialization.

    Typed PRNG keys are converted to their raw uint32 key data first
    (``jax.device_get`` cannot fetch extended-dtype arrays; the raw form
    is exactly what :func:`~apex_tpu.checkpoint.save_checkpoint` stores,
    and restore rebuilds typed keys from the *target* tree). Blocks until
    the state's producing computation is done and the bytes are on host —
    the snapshot is a consistent cut of the step it follows.

    Every leaf is an OWNED copy, never a view: on the CPU backend
    ``jax.device_get`` can return zero-copy numpy views of the device
    buffer, and the donated train step (``jit_train_step`` aliases
    stage/shared/opt_state) reuses exactly those buffers on its next
    dispatch — a viewing snapshot would hand the background writer memory
    that is being overwritten/freed under it (observed as glibc heap
    corruption). The explicit ``np.array(..., copy=True)`` is the
    snapshot's whole point: after it returns, the live state is free to
    be donated.
    """

    def conv(x):
        if _ckpt._is_prng_key(x):
            x = jax.random.key_data(x)
        return np.array(jax.device_get(x), copy=True)

    return jax.tree_util.tree_map(conv, state, is_leaf=_ckpt._is_prng_key)


def owned_copy(state: Any) -> Any:
    """XLA-owned deep copy of a pytree of jax arrays, shardings preserved.

    ``jnp.copy`` emits a real device ``copy`` op the compiler cannot
    buffer-forward, so every output leaf is a buffer XLA allocated and
    owns. Restored checkpoints MUST pass through this before entering a
    donating step: orbax-restored arrays can alias host memory the XLA
    runtime does not own, and donating such a buffer corrupts the heap
    (observed as intermittent glibc malloc/segfault aborts on the CPU
    backend — ``ElasticRunner._restore`` calls this unconditionally).
    Typed PRNG keys round-trip through their raw key data.
    """

    def conv(x):
        if _ckpt._is_prng_key(x):
            data = jnp.copy(jax.random.key_data(x))
            return jax.random.wrap_key_data(
                data, impl=jax.random.key_impl(x))
        return jnp.copy(x)

    return jax.tree_util.tree_map(conv, state, is_leaf=_ckpt._is_prng_key)


def snapshot_nbytes(snapshot: Any) -> int:
    """Total bytes of a snapshot (the serialized payload scale). Works
    on host snapshots AND live global arrays (collective mode cannot
    ``np.asarray`` a shard another process owns — ``.nbytes`` is global
    metadata and needs no transfer)."""

    def leaf_nbytes(leaf) -> int:
        try:
            return int(leaf.nbytes)
        except Exception:
            pass
        try:
            return int(np.asarray(leaf).nbytes)
        except Exception:
            return 0

    return sum(leaf_nbytes(leaf)
               for leaf in jax.tree_util.tree_leaves(snapshot)
               if hasattr(leaf, "nbytes") or hasattr(leaf, "dtype"))


class AsyncCheckpointer:
    """Background writer around :func:`~apex_tpu.checkpoint.save_checkpoint`.

    ::

        ckpt = AsyncCheckpointer(dir, keep_last=3)
        for step in ...:
            state = step_fn(state)
            if step % interval == 0:
                ckpt.save(state, step, host_state={"step": step})
        ckpt.drain()          # join the in-flight save; re-raise failures

    ``fault_hook(step, attempt)`` is called before every serialization
    attempt (the :class:`~apex_tpu.elastic.faults.FaultPlan` injection
    point); an ``OSError`` it raises is treated like a real transient
    filesystem error and retried. ``after_save(step, path)`` runs on the
    writer thread after a successful commit (fault plans use it to tear
    markers; production code normally leaves it unset). ``save_fn``
    overrides the serializer (tests substitute slow/counting stand-ins).

    **Retry backoff**: attempt ``a`` sleeps
    ``min(retry_backoff_cap_s, retry_backoff_s * 2**(a-1))`` scaled by
    ``1 + retry_jitter * u`` with ``u ~ U[0, 1)`` drawn from a
    ``RandomState`` seeded on ``(host_id, step)`` — N hosts retrying a
    flaky shared filesystem in LOCKSTEP are a thundering herd that
    re-breaks it on every attempt; per-host jitter decorrelates them,
    and the host_id seed keeps every test (and every rank's schedule)
    deterministic. ``backoff_s`` is the legacy spelling of
    ``retry_backoff_s``.

    **Collective mode** (``collective=True``): for multi-controller
    worlds, where ``jax.device_get`` cannot snapshot non-addressable
    shards — ``save`` serializes *synchronously* on the calling thread,
    handing the live sharded state straight to the collective
    :func:`~apex_tpu.checkpoint.save_checkpoint` (each process writes
    the shards it owns; the COMMITTED protocol is fenced by
    cross-process barriers there). The async split is a single-host
    optimization; the interface (save/drain/metrics) is unchanged so
    :class:`~apex_tpu.elastic.runner.ElasticRunner` is world-size
    agnostic. Collective saves never retry (``max_retries`` is ignored):
    an asymmetric transient failure would have one rank re-entering the
    begin barrier while its peers wait in the arrays barrier — a gang
    deadlock. A failed collective save raises; recovery is the
    supervisor's gang restart from the last COMMITTED generation.
    """

    def __init__(self, directory: str, *, fp32_on_disk: bool = True,
                 keep_last: Optional[int] = None, max_retries: int = 3,
                 backoff_s: Optional[float] = None,
                 retry_backoff_s: Optional[float] = None,
                 retry_backoff_cap_s: Optional[float] = None,
                 retry_jitter: float = 0.25,
                 host_id: Optional[int] = None,
                 collective: bool = False,
                 registry: Optional[MetricsRegistry] = None,
                 fault_hook: Optional[Callable[[int, int], None]] = None,
                 after_save: Optional[Callable[[int, str], None]] = None,
                 save_fn: Optional[Callable[..., str]] = None):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if (backoff_s is not None and retry_backoff_s is not None
                and backoff_s != retry_backoff_s):
            raise ValueError(
                f"backoff_s={backoff_s} and retry_backoff_s="
                f"{retry_backoff_s} are the same parameter spelled "
                f"twice; pass only retry_backoff_s")
        if retry_backoff_s is None:
            retry_backoff_s = 0.05 if backoff_s is None else backoff_s
        if retry_backoff_cap_s is None:
            # the default cap must not invalidate a legal base — a
            # legacy backoff_s=60.0 predates the cap and keeps working
            retry_backoff_cap_s = max(30.0, retry_backoff_s)
        elif retry_backoff_cap_s < retry_backoff_s:
            raise ValueError(
                f"retry_backoff_cap_s={retry_backoff_cap_s} below the "
                f"base retry_backoff_s={retry_backoff_s}")
        if retry_jitter < 0.0:
            raise ValueError("retry_jitter must be >= 0")
        self.directory = directory
        self.fp32_on_disk = fp32_on_disk
        self.keep_last = keep_last
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_cap_s = retry_backoff_cap_s
        self.retry_jitter = retry_jitter
        if host_id is None:
            from apex_tpu.parallel.multiproc import process_id
            host_id = process_id()
        self.host_id = int(host_id)
        self.collective = collective
        self.fault_hook = fault_hook
        self.after_save = after_save
        self._save_fn = save_fn or _ckpt.save_checkpoint
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.last_saved_step: Optional[int] = None
        reg = registry if registry is not None else get_registry()
        self._m_save_ms = reg.histogram("ckpt/save_ms")
        self._m_bytes = reg.counter("ckpt/bytes")
        self._m_inflight = reg.gauge("ckpt/inflight")
        self._m_saves = reg.counter("ckpt/saves")
        self._m_retries = reg.counter("ckpt/retries")
        self._m_inflight.set(0)

    @property
    def backoff_s(self) -> float:
        """Legacy alias of ``retry_backoff_s``."""
        return self.retry_backoff_s

    def _backoff_sleep_s(self, step: int, attempt: int) -> float:
        """Deterministic jittered backoff before retry ``attempt``
        (1-based) of the save at ``step``."""
        base = min(self.retry_backoff_cap_s,
                   self.retry_backoff_s * (2.0 ** (attempt - 1)))
        if self.retry_jitter <= 0.0:
            return base
        rs = np.random.RandomState(
            (self.host_id * 1_000_003 + step * 7919 + 1) % (2 ** 32))
        u = float(rs.uniform(0.0, 1.0, size=attempt)[-1])
        return base * (1.0 + self.retry_jitter * u)

    # -- writer side ------------------------------------------------------
    def _serialize(self, snapshot: Any, step: int,
                   host_state: Optional[Dict[str, Any]]) -> None:
        last: Optional[BaseException] = None
        # collective mode NEVER retries: the collective save is fenced
        # by named cross-process barriers, and an ASYMMETRIC transient
        # failure (one rank errors out of the orbax write while its
        # peers sit in the arrays-durable barrier) would have the
        # retrying rank re-enter the begin barrier while the others wait
        # in a different one — a gang deadlock the supervisor can only
        # break by teardown. Fail the save loudly instead; multi-host
        # recovery is the supervisor's restart-from-last-COMMITTED, not
        # an in-process retry. (Per-host retry-with-jitter remains the
        # single-controller path's tool.)
        retry_budget = 0 if self.collective else self.max_retries
        for attempt in range(retry_budget + 1):
            if attempt:
                # bounded exponential backoff between transient
                # failures, host-decorrelated by deterministic jitter
                time.sleep(self._backoff_sleep_s(step, attempt))
                self._m_retries.inc()
            try:
                if self.fault_hook is not None:
                    self.fault_hook(step, attempt)
                t0 = time.perf_counter()
                path = self._save_fn(
                    self.directory, snapshot, step,
                    fp32_on_disk=self.fp32_on_disk,
                    host_state=host_state, keep_last=self.keep_last)
                self._m_save_ms.observe((time.perf_counter() - t0) * 1e3)
                self._m_saves.inc()
                self.last_saved_step = step
                if self.after_save is not None:
                    self.after_save(step, path)
                return
            except OSError as e:  # transient class: retry with backoff
                last = e
        raise OSError(
            f"checkpoint save at step {step} failed after "
            f"{retry_budget + 1} attempt(s)"
            + (" (collective saves never retry — an asymmetric retry "
               "would deadlock the barrier protocol; recovery is the "
               "supervisor's restart from the last COMMITTED "
               "checkpoint)" if self.collective else "")) from last

    def _run(self, snapshot: Any, step: int,
             host_state: Optional[Dict[str, Any]]) -> None:
        try:
            self._serialize(snapshot, step, host_state)
        except BaseException as e:  # latched; re-raised on next save/drain
            self._error = e
        finally:
            self._m_inflight.set(0)

    # -- trainer side -----------------------------------------------------
    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def save(self, state: Any, step: int, *,
             host_state: Optional[Dict[str, Any]] = None,
             block: bool = False) -> None:
        """Snapshot ``state`` now; serialize it in the background.

        Drains (and error-checks) the previous save first, so at most one
        write is in flight and a failure surfaces within one save
        interval. ``block=True`` additionally waits for THIS save (the
        final/preemption save path).

        In ``collective`` mode the save is synchronous and collective:
        no snapshot (``device_get`` cannot see other processes' shards),
        no thread (every process must be inside the orbax save and its
        barriers at the same time) — the live state goes straight to the
        serializer and this call returns only after COMMITTED is
        visible.
        """
        if self.collective:
            self._m_bytes.inc(snapshot_nbytes(state))
            self._serialize(state, step, host_state)
            return
        self.drain()
        snapshot = host_snapshot(state)
        self._m_bytes.inc(snapshot_nbytes(snapshot))
        self._m_inflight.set(1)
        self._thread = threading.Thread(
            target=self._run, args=(snapshot, step, host_state),
            name=f"ckpt-writer-step{step}", daemon=True)
        self._thread.start()
        if block:
            self.drain()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Join the in-flight save (no-op when idle) and re-raise any
        latched writer failure. Call before exiting — a preemption must
        drain, not abandon, the write in progress."""
        th = self._thread
        if th is not None:
            th.join(timeout)
            if th.is_alive():
                raise TimeoutError(
                    f"in-flight checkpoint save did not finish within "
                    f"{timeout}s")
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    close = drain

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.drain()
        else:  # already unwinding: don't mask the primary exception
            try:
                self.drain()
            except Exception:
                pass
