"""Async (off-critical-path) checkpointing.

CheckFreq-style split of :func:`apex_tpu.checkpoint.save_checkpoint` into
two phases with different latency budgets:

1. **snapshot** (:func:`host_snapshot`) — runs on the training thread
   inside the step cadence: one ``jax.device_get`` pulls the state pytree
   to host memory. This is the only part the step loop waits on; it costs
   a device→host copy, never a disk write.
2. **serialize** — runs on a background writer thread: the orbax write,
   the ``host.json`` sidecar, the COMMITTED marker, and ``keep_last`` GC,
   exactly the :func:`~apex_tpu.checkpoint.save_checkpoint` protocol
   (COMMITTED is written LAST, so a process killed mid-serialize leaves a
   torn dir that :func:`~apex_tpu.checkpoint.restore_checkpoint` skips
   loudly, never a COMMITTED-but-partial one).

At most one save is in flight: a new :meth:`AsyncCheckpointer.save`
first drains the previous one (and re-raises its failure, if any — a
background save error is never silent). Transient filesystem errors
(``OSError``) during serialization are retried with bounded exponential
backoff before the save is declared failed.

Snapshot scope: ``jax.device_get`` requires every shard to be addressable
from this process (single-controller / fully-addressable deployments —
the CPU mesh, single-host TPU slices). Multi-controller jobs should call
the synchronous collective :func:`~apex_tpu.checkpoint.save_checkpoint`
directly.

Metrics (host registry, PR 1): ``ckpt/save_ms`` (histogram, serialize
wall per save), ``ckpt/bytes`` (counter, snapshot bytes handed to the
writer), ``ckpt/inflight`` (gauge, 0/1), ``ckpt/saves`` (counter,
committed saves), ``ckpt/retries`` (counter, transient-error retries).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu import checkpoint as _ckpt
from apex_tpu.observability.registry import MetricsRegistry, get_registry

__all__ = ["AsyncCheckpointer", "host_snapshot", "owned_copy",
           "snapshot_nbytes"]


def host_snapshot(state: Any) -> Any:
    """Device→host copy of ``state``, ready for off-thread serialization.

    Typed PRNG keys are converted to their raw uint32 key data first
    (``jax.device_get`` cannot fetch extended-dtype arrays; the raw form
    is exactly what :func:`~apex_tpu.checkpoint.save_checkpoint` stores,
    and restore rebuilds typed keys from the *target* tree). Blocks until
    the state's producing computation is done and the bytes are on host —
    the snapshot is a consistent cut of the step it follows.

    Every leaf is an OWNED copy, never a view: on the CPU backend
    ``jax.device_get`` can return zero-copy numpy views of the device
    buffer, and the donated train step (``jit_train_step`` aliases
    stage/shared/opt_state) reuses exactly those buffers on its next
    dispatch — a viewing snapshot would hand the background writer memory
    that is being overwritten/freed under it (observed as glibc heap
    corruption). The explicit ``np.array(..., copy=True)`` is the
    snapshot's whole point: after it returns, the live state is free to
    be donated.
    """

    def conv(x):
        if _ckpt._is_prng_key(x):
            x = jax.random.key_data(x)
        return np.array(jax.device_get(x), copy=True)

    return jax.tree_util.tree_map(conv, state, is_leaf=_ckpt._is_prng_key)


def owned_copy(state: Any) -> Any:
    """XLA-owned deep copy of a pytree of jax arrays, shardings preserved.

    ``jnp.copy`` emits a real device ``copy`` op the compiler cannot
    buffer-forward, so every output leaf is a buffer XLA allocated and
    owns. Restored checkpoints MUST pass through this before entering a
    donating step: orbax-restored arrays can alias host memory the XLA
    runtime does not own, and donating such a buffer corrupts the heap
    (observed as intermittent glibc malloc/segfault aborts on the CPU
    backend — ``ElasticRunner._restore`` calls this unconditionally).
    Typed PRNG keys round-trip through their raw key data.
    """

    def conv(x):
        if _ckpt._is_prng_key(x):
            data = jnp.copy(jax.random.key_data(x))
            return jax.random.wrap_key_data(
                data, impl=jax.random.key_impl(x))
        return jnp.copy(x)

    return jax.tree_util.tree_map(conv, state, is_leaf=_ckpt._is_prng_key)


def snapshot_nbytes(snapshot: Any) -> int:
    """Total bytes of a host snapshot (the serialized payload scale)."""
    return int(sum(np.asarray(leaf).nbytes
                   for leaf in jax.tree_util.tree_leaves(snapshot)
                   if hasattr(leaf, "nbytes") or hasattr(leaf, "dtype")))


class AsyncCheckpointer:
    """Background writer around :func:`~apex_tpu.checkpoint.save_checkpoint`.

    ::

        ckpt = AsyncCheckpointer(dir, keep_last=3)
        for step in ...:
            state = step_fn(state)
            if step % interval == 0:
                ckpt.save(state, step, host_state={"step": step})
        ckpt.drain()          # join the in-flight save; re-raise failures

    ``fault_hook(step, attempt)`` is called before every serialization
    attempt (the :class:`~apex_tpu.elastic.faults.FaultPlan` injection
    point); an ``OSError`` it raises is treated like a real transient
    filesystem error and retried. ``after_save(step, path)`` runs on the
    writer thread after a successful commit (fault plans use it to tear
    markers; production code normally leaves it unset). ``save_fn``
    overrides the serializer (tests substitute slow/counting stand-ins).
    """

    def __init__(self, directory: str, *, fp32_on_disk: bool = True,
                 keep_last: Optional[int] = None, max_retries: int = 3,
                 backoff_s: float = 0.05,
                 registry: Optional[MetricsRegistry] = None,
                 fault_hook: Optional[Callable[[int, int], None]] = None,
                 after_save: Optional[Callable[[int, str], None]] = None,
                 save_fn: Optional[Callable[..., str]] = None):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.directory = directory
        self.fp32_on_disk = fp32_on_disk
        self.keep_last = keep_last
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.fault_hook = fault_hook
        self.after_save = after_save
        self._save_fn = save_fn or _ckpt.save_checkpoint
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.last_saved_step: Optional[int] = None
        reg = registry if registry is not None else get_registry()
        self._m_save_ms = reg.histogram("ckpt/save_ms")
        self._m_bytes = reg.counter("ckpt/bytes")
        self._m_inflight = reg.gauge("ckpt/inflight")
        self._m_saves = reg.counter("ckpt/saves")
        self._m_retries = reg.counter("ckpt/retries")
        self._m_inflight.set(0)

    # -- writer side ------------------------------------------------------
    def _serialize(self, snapshot: Any, step: int,
                   host_state: Optional[Dict[str, Any]]) -> None:
        last: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            if attempt:
                # bounded exponential backoff between transient failures
                time.sleep(self.backoff_s * (2.0 ** (attempt - 1)))
                self._m_retries.inc()
            try:
                if self.fault_hook is not None:
                    self.fault_hook(step, attempt)
                t0 = time.perf_counter()
                path = self._save_fn(
                    self.directory, snapshot, step,
                    fp32_on_disk=self.fp32_on_disk,
                    host_state=host_state, keep_last=self.keep_last)
                self._m_save_ms.observe((time.perf_counter() - t0) * 1e3)
                self._m_saves.inc()
                self.last_saved_step = step
                if self.after_save is not None:
                    self.after_save(step, path)
                return
            except OSError as e:  # transient class: retry with backoff
                last = e
        raise OSError(
            f"checkpoint save at step {step} failed after "
            f"{self.max_retries + 1} attempt(s)") from last

    def _run(self, snapshot: Any, step: int,
             host_state: Optional[Dict[str, Any]]) -> None:
        try:
            self._serialize(snapshot, step, host_state)
        except BaseException as e:  # latched; re-raised on next save/drain
            self._error = e
        finally:
            self._m_inflight.set(0)

    # -- trainer side -----------------------------------------------------
    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def save(self, state: Any, step: int, *,
             host_state: Optional[Dict[str, Any]] = None,
             block: bool = False) -> None:
        """Snapshot ``state`` now; serialize it in the background.

        Drains (and error-checks) the previous save first, so at most one
        write is in flight and a failure surfaces within one save
        interval. ``block=True`` additionally waits for THIS save (the
        final/preemption save path).
        """
        self.drain()
        snapshot = host_snapshot(state)
        self._m_bytes.inc(snapshot_nbytes(snapshot))
        self._m_inflight.set(1)
        self._thread = threading.Thread(
            target=self._run, args=(snapshot, step, host_state),
            name=f"ckpt-writer-step{step}", daemon=True)
        self._thread.start()
        if block:
            self.drain()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Join the in-flight save (no-op when idle) and re-raise any
        latched writer failure. Call before exiting — a preemption must
        drain, not abandon, the write in progress."""
        th = self._thread
        if th is not None:
            th.join(timeout)
            if th.is_alive():
                raise TimeoutError(
                    f"in-flight checkpoint save did not finish within "
                    f"{timeout}s")
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    close = drain

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.drain()
        else:  # already unwinding: don't mask the primary exception
            try:
                self.drain()
            except Exception:
                pass
