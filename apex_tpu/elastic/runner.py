"""Preemption-safe elastic run loop over :class:`GPTHybridTrainer`.

``ElasticRunner`` owns the production step loop: periodic async
checkpoints off the critical path, SIGTERM/env/hook termination detection
through :class:`~apex_tpu.utils.autoresume.AutoResume`, drain-then-save
inside the preemption grace window, and deterministic restart — restore
the latest COMMITTED checkpoint (params, optimizer state incl. the ZeRO
``bucket_stamp``-guarded flat shards, loss-scale), seek the data iterator
to the sidecar cursor, and continue. The contract, proven in
``tests/test_elastic_resume.py`` and the dryrun kill-and-resume leg:

    N steps + preempt + restore + M steps  ==  N+M straight steps,
    bitwise, for params, optimizer state, loss scale, and data cursor.

The trainer protocol is :class:`~apex_tpu.training.GPTHybridTrainer`'s
surface: ``init_state(key) -> state tuple``, ``jit_train_step() ->
fn(*state, *batch) -> (loss, *state)``; the data protocol is
``next(data) -> batch tuple`` plus ``state_dict()/load_state_dict()``
(see :mod:`apex_tpu.elastic.data`).

Exit discipline: the ONLY process exit in this package is
``AutoResume.request_resume`` (enforced statically by
``scripts/check_elastic_exits.py``) — every other failure propagates as
an exception the scheduler can distinguish from a clean preemption.

Metrics (host registry): ``resume/restore_ms``, ``resume/restored_step``
(gauges), ``resume/resumes``, ``resume/preempt_exits`` (counters), plus
the ``ckpt/*`` family from :class:`~apex_tpu.elastic.ckpt
.AsyncCheckpointer`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax

from apex_tpu import checkpoint as _ckpt
from apex_tpu.elastic.ckpt import AsyncCheckpointer, owned_copy
from apex_tpu.elastic.faults import FaultPlan
from apex_tpu.observability.registry import MetricsRegistry, get_registry
from apex_tpu.utils.autoresume import AutoResume

__all__ = ["ElasticRunner", "FitResult"]


@dataclasses.dataclass
class FitResult:
    """What a (possibly interrupted) :meth:`ElasticRunner.fit` produced."""

    state: Any                    # trainer state tuple after the last step
    step: int                     # completed steps
    loss: Optional[float]         # last step's loss (None if no step ran)
    preempted: bool               # True: stopped on a termination request
    restored_from: Optional[int]  # checkpoint step this run resumed from


class ElasticRunner:
    """Elastic training loop: checkpoint cadence + preemption handling.

    ``directory`` is the checkpoint root. ``save_interval=K`` checkpoints
    every K completed steps (asynchronously — the loop never blocks on
    disk); ``keep_last`` bounds the on-disk generations. ``fault_plan``
    wires a :class:`~apex_tpu.elastic.faults.FaultPlan` into both the
    step loop and the checkpointer. ``exit_on_preempt=False`` makes a
    preemption return a ``FitResult(preempted=True)`` instead of calling
    ``AutoResume.request_resume`` (in-process tests; production keeps the
    exit-0-so-the-scheduler-restarts default).
    """

    def __init__(self, trainer: Any, data: Any, directory: str, *,
                 save_interval: int = 50, keep_last: Optional[int] = 3,
                 fp32_on_disk: bool = True,
                 autoresume: Optional[AutoResume] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 registry: Optional[MetricsRegistry] = None,
                 exit_on_preempt: bool = True, final_save: bool = True,
                 on_step: Optional[Callable[[int, Any], None]] = None,
                 checkpointer: Optional[AsyncCheckpointer] = None):
        if save_interval < 1:
            raise ValueError("save_interval must be >= 1")
        self.trainer = trainer
        self.data = data
        self.directory = directory
        self.save_interval = save_interval
        self.fault_plan = fault_plan
        self.autoresume = autoresume
        self.exit_on_preempt = exit_on_preempt
        self.final_save = final_save
        self.on_step = on_step
        self._registry = (registry if registry is not None
                          else get_registry())
        self.ckpt = checkpointer if checkpointer is not None else \
            AsyncCheckpointer(
                directory, fp32_on_disk=fp32_on_disk, keep_last=keep_last,
                registry=self._registry,
                fault_hook=(fault_plan.on_save_attempt if fault_plan
                            else None),
                after_save=(fault_plan.after_save if fault_plan else None))

    # -- sidecar ----------------------------------------------------------
    def _host_state(self, step: int) -> dict:
        host = {"step": int(step)}
        if self.data is not None and hasattr(self.data, "state_dict"):
            host["data"] = self.data.state_dict()
        return host

    def _restore(self, state: tuple) -> tuple:
        """Latest-COMMITTED restore onto the live state's layout; returns
        ``(state, completed_steps, restored_from)``."""
        latest = _ckpt.latest_step(self.directory)
        if latest is None:
            # still warn about torn dirs a dead writer left behind
            torn = _ckpt.torn_steps(self.directory)
            if torn:
                import warnings
                warnings.warn(
                    f"no committed checkpoint under {self.directory!r}; "
                    f"ignoring torn dir(s) at step(s) {torn} and starting "
                    "from scratch")
            return state, 0, None
        t0 = time.perf_counter()
        restored, host = _ckpt.restore_checkpoint(self.directory, state)
        self._registry.gauge("resume/restore_ms").set(
            (time.perf_counter() - t0) * 1e3)
        step = int(host.get("step", latest))
        self._registry.gauge("resume/restored_step").set(step)
        self._registry.counter("resume/resumes").inc()
        if (self.data is not None and "data" in host
                and hasattr(self.data, "load_state_dict")):
            self.data.load_state_dict(host["data"])
        # the restored step IS durably on disk — mark it saved, so a fit
        # that runs zero further steps (restart after completion, or a
        # preemption landing immediately) does not re-save it:
        # save_checkpoint rmtree's the existing dir before rewriting, and
        # a kill in that window would destroy the newest (with
        # keep_last=1, the only) COMMITTED checkpoint
        self.ckpt.last_saved_step = step
        # materialize XLA-owned buffers before the state can be DONATED:
        # orbax-restored arrays may alias host memory the runtime does not
        # own, and jit_train_step's donate_argnums would free/reuse it
        # under the allocator's feet (see elastic/ckpt.owned_copy)
        return tuple(owned_copy(restored)), step, step

    # -- preemption -------------------------------------------------------
    def _preempt(self, ar: AutoResume, state: tuple, step: int,
                 loss: Any, restored_from: Optional[int]) -> FitResult:
        """The grace-window path: drain the in-flight save, write a final
        checkpoint at the current completed step, then hand control back
        to the scheduler (exit 0 via ``request_resume``)."""
        self.ckpt.drain()
        if self.ckpt.last_saved_step != step:
            self.ckpt.save(state, step, host_state=self._host_state(step),
                           block=True)
        self._registry.counter("resume/preempt_exits").inc()
        if self.exit_on_preempt:
            ar.request_resume()  # sys.exit(0): scheduler restarts the job
        return FitResult(state=state, step=step,
                         loss=None if loss is None else float(loss),
                         preempted=True, restored_from=restored_from)

    # -- the loop ---------------------------------------------------------
    def fit(self, steps: int, *, key: Optional[jax.Array] = None,
            state: Optional[tuple] = None,
            no_recompile: bool = False) -> FitResult:
        """Run until ``steps`` total steps are COMPLETED (counting the
        restored prefix), checkpointing on the way. ``state`` overrides
        the freshly-initialized state used as the restore target (its
        shapes/dtypes/shardings define the checkpoint layout).

        ``no_recompile=True`` wraps the step loop in the analysis
        engine's :class:`~apex_tpu.analysis.program.recompile_guard`:
        the first iteration (including its save, whose fp32-cast path
        compiles once) is the warmup baseline; any compile-storm counter
        movement after it raises ``AnalysisError`` — a shape or
        static-arg leak retracing the production step fails loudly
        instead of silently multiplying step time."""
        from contextlib import nullcontext

        if state is None:
            state = self.trainer.init_state(
                key if key is not None else jax.random.PRNGKey(0))
        state, step, restored_from = self._restore(tuple(state))
        ar = self.autoresume
        own_ar = ar is None
        if own_ar:
            ar = AutoResume(interval=1)
        step_fn = self.trainer.jit_train_step()
        loss = None
        if no_recompile:
            from apex_tpu.analysis.program import recompile_guard
            guard = recompile_guard("ElasticRunner.fit")
        else:
            guard = nullcontext()
        warm_steps, saved_once = 0, False
        preempted = False
        try:
            # the guard covers ONLY the steady-state loop: the preempt
            # drain and the final checkpoint are one-shot paths whose
            # first-use compiles (fp32-on-disk casts) are not a storm
            with guard:
                while step < steps:
                    if self.fault_plan is not None:
                        self.fault_plan.before_step(step)
                    if ar.termination_requested(step):
                        preempted = True
                        break
                    batch = next(self.data)
                    loss, *state = step_fn(*state, *batch)
                    state = tuple(state)
                    step += 1
                    if self.on_step is not None:
                        self.on_step(step, loss)
                    saved = False
                    if step % self.save_interval == 0 and step < steps:
                        self.ckpt.save(state, step,
                                       host_state=self._host_state(step))
                        saved = True
                    # warmup baselines: the first TWO dispatches compile
                    # the step (a freshly-initialized state and the
                    # donated step outputs differ in sharding
                    # memory-kind, so iteration 2 legitimately adds a
                    # second cache entry), and the first save compiles
                    # the storage casts — all expected; anything after
                    # them is the leak. The first save is drained so its
                    # async worker's compiles land BEFORE the rebase,
                    # not racing it.
                    if no_recompile and (warm_steps < 2
                                         or (saved and not saved_once)):
                        if saved and not saved_once:
                            self.ckpt.drain()
                        guard.rebase()
                    warm_steps += 1
                    saved_once = saved_once or saved
            if preempted:
                return self._preempt(ar, state, step, loss,
                                     restored_from)
            # run complete: drain the tail save, then commit the final one
            self.ckpt.drain()
            if ar.termination_requested(step):
                return self._preempt(ar, state, step, loss, restored_from)
            if self.final_save and self.ckpt.last_saved_step != step:
                self.ckpt.save(state, step,
                               host_state=self._host_state(step),
                               block=True)
            return FitResult(state=state, step=step,
                             loss=None if loss is None else float(loss),
                             preempted=False, restored_from=restored_from)
        finally:
            if own_ar:
                ar.close()
