"""Preemption-safe elastic run loop over :class:`GPTHybridTrainer`.

``ElasticRunner`` owns the production step loop: periodic async
checkpoints off the critical path, SIGTERM/env/hook termination detection
through :class:`~apex_tpu.utils.autoresume.AutoResume`, drain-then-save
inside the preemption grace window, and deterministic restart — restore
the latest COMMITTED checkpoint (params, optimizer state incl. the ZeRO
``bucket_stamp``-guarded flat shards, loss-scale), seek the data iterator
to the sidecar cursor, and continue. The contract, proven in
``tests/test_elastic_resume.py`` and the dryrun kill-and-resume leg:

    N steps + preempt + restore + M steps  ==  N+M straight steps,
    bitwise, for params, optimizer state, loss scale, and data cursor.

The trainer protocol is :class:`~apex_tpu.training.GPTHybridTrainer`'s
surface: ``init_state(key) -> state tuple``, ``jit_train_step() ->
fn(*state, *batch) -> (loss, *state)``; the data protocol is
``next(data) -> batch tuple`` plus ``state_dict()/load_state_dict()``
(see :mod:`apex_tpu.elastic.data`).

Exit discipline: process exits in this package are pinned to two blessed
chokepoints — ``AutoResume.request_resume`` (this runner's preemption
path) and ``launch.py::_supervisor_exit`` (the supervisor CLI's
exit-code propagation) — enforced statically by the
``ast-elastic-exits`` analysis rule (``scripts/check_elastic_exits.py``
shim); every other failure propagates as an exception the scheduler can
distinguish from a clean preemption.

Multi-controller worlds (``jax.process_count() > 1``): the checkpointer
switches to synchronous collective saves, and the per-step termination
poll is OR-reduced across processes (:func:`apex_tpu.parallel.multiproc
.any_process`) so every rank takes the drain path at the same step.
Cross-WORLD-SIZE restarts (elastic shrink/grow) reshard the ZeRO flat
shards through :mod:`apex_tpu.elastic.reshard` — see
``docs/ROBUSTNESS.md`` "Multi-host".

Metrics (host registry): ``resume/restore_ms``, ``resume/restored_step``
(gauges), ``resume/resumes``, ``resume/preempt_exits``, ``train/steps``
(counters), plus the ``ckpt/*`` family from :class:`~apex_tpu.elastic
.ckpt.AsyncCheckpointer`. A :class:`~apex_tpu.observability.fleet
.FleetPublisher` passed as ``publisher`` snapshots the registry (and
the completed-step counter) to ``run_dir/fleet/rank_<i>.json`` once per
step — host-side only, the step program is byte-identical with it on
or off (asserted in ``tests/test_fleet.py``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import signal as _signal
import threading
import time
from typing import Any, Callable, Optional

import jax

from apex_tpu import checkpoint as _ckpt
from apex_tpu.elastic.ckpt import AsyncCheckpointer, owned_copy
from apex_tpu.elastic.faults import FaultPlan
from apex_tpu.observability.registry import MetricsRegistry, get_registry
from apex_tpu.utils.autoresume import AutoResume

__all__ = ["DrainInterrupt", "ElasticRunner", "FitResult"]


class DrainInterrupt(KeyboardInterrupt):
    """A second SIGTERM/SIGINT arrived while the preemption drain was
    writing the final checkpoint. Raised from the signal handler so the
    drain aborts immediately — a stuck save must not make the job
    unkillable. Subclasses :class:`KeyboardInterrupt` on purpose: no
    ``except Exception`` on the unwind path can swallow it. The
    checkpoint being abandoned is at worst TORN (COMMITTED is written
    last), so the previous COMMITTED generation stays the restore
    point."""


@contextlib.contextmanager
def _second_signal_escalation():
    """Two-signal semantics for the drain window: the FIRST
    SIGTERM/SIGINT asked for the graceful drain that is now running; a
    SECOND one during it raises :class:`DrainInterrupt` instead of
    latching. Installed only around the drain (and only on the main
    thread — signal handlers cannot be installed elsewhere, and only the
    main thread receives them); the previous handlers are restored on
    exit either way."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return

    def escalate(signum, frame):
        raise DrainInterrupt(
            f"second termination signal "
            f"({_signal.Signals(signum).name}) during the preemption "
            f"drain — aborting the in-flight save so the job stays "
            f"killable; the previous COMMITTED checkpoint remains the "
            f"restore point")

    prev = {s: _signal.signal(s, escalate)
            for s in (_signal.SIGTERM, _signal.SIGINT)}
    try:
        yield
    finally:
        for s, h in prev.items():
            _signal.signal(s, h)


@dataclasses.dataclass
class FitResult:
    """What a (possibly interrupted) :meth:`ElasticRunner.fit` produced."""

    state: Any                    # trainer state tuple after the last step
    step: int                     # completed steps
    loss: Optional[float]         # last step's loss (None if no step ran)
    preempted: bool               # True: stopped on a termination request
    restored_from: Optional[int]  # checkpoint step this run resumed from
    resharded: bool = False       # restore crossed a world-size change


class ElasticRunner:
    """Elastic training loop: checkpoint cadence + preemption handling.

    ``directory`` is the checkpoint root. ``save_interval=K`` checkpoints
    every K completed steps (asynchronously — the loop never blocks on
    disk); ``keep_last`` bounds the on-disk generations. ``fault_plan``
    wires a :class:`~apex_tpu.elastic.faults.FaultPlan` into both the
    step loop and the checkpointer. ``exit_on_preempt=False`` makes a
    preemption return a ``FitResult(preempted=True)`` instead of calling
    ``AutoResume.request_resume`` (in-process tests; production keeps the
    exit-0-so-the-scheduler-restarts default). ``publisher`` attaches a
    :class:`~apex_tpu.observability.fleet.FleetPublisher`: one snapshot
    per completed step (throttled by its ``min_interval_s``) plus a
    forced final one on both exit paths, so the supervisor's merged view
    and postmortems always see this rank's last state.
    """

    def __init__(self, trainer: Any, data: Any, directory: str, *,
                 save_interval: int = 50, keep_last: Optional[int] = 3,
                 fp32_on_disk: bool = True,
                 autoresume: Optional[AutoResume] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 registry: Optional[MetricsRegistry] = None,
                 exit_on_preempt: bool = True, final_save: bool = True,
                 on_step: Optional[Callable[[int, Any], None]] = None,
                 checkpointer: Optional[AsyncCheckpointer] = None,
                 publisher: Optional[Any] = None):
        if save_interval < 1:
            raise ValueError("save_interval must be >= 1")
        self.trainer = trainer
        self.data = data
        self.directory = directory
        self.save_interval = save_interval
        self.fault_plan = fault_plan
        self.autoresume = autoresume
        self.exit_on_preempt = exit_on_preempt
        self.final_save = final_save
        self.on_step = on_step
        self.publisher = publisher
        self._registry = (registry if registry is not None
                          else get_registry())
        # the fleet snapshot's "completed steps" counter: host-side, no
        # device sync (the loss is deliberately NOT fetched per step)
        self._m_steps = self._registry.counter("train/steps")
        # multi-controller worlds checkpoint collectively+synchronously
        # (device_get cannot snapshot shards other processes own); the
        # async off-thread split stays the single-controller default
        try:
            self._multiprocess = jax.process_count() > 1
        except Exception:
            self._multiprocess = False
        self.ckpt = checkpointer if checkpointer is not None else \
            AsyncCheckpointer(
                directory, fp32_on_disk=fp32_on_disk, keep_last=keep_last,
                registry=self._registry, collective=self._multiprocess,
                fault_hook=(fault_plan.on_save_attempt if fault_plan
                            else None),
                after_save=(fault_plan.after_save if fault_plan else None))

    # -- sidecar ----------------------------------------------------------
    def _world_meta(self) -> Optional[dict]:
        """The world geometry this trainer's checkpoints are laid out
        for — rides in the host sidecar so a restart into a DIFFERENT
        world (elastic shrink/grow) can detect the mismatch and take the
        reshard path instead of a silent mis-restore. ``None`` for
        trainers without a mesh (the layout is then world-independent)."""
        mesh = getattr(self.trainer, "mesh", None)
        if mesh is None:
            return None
        shape = dict(mesh.shape)
        from apex_tpu.parallel.multiproc import process_count
        meta = {"dp": int(shape.get("data", 1)),
                "pp": int(shape.get("pipe", 1)),
                "tp": int(shape.get("tensor", 1)),
                "cp": int(shape.get("context", 1)),
                "num_hosts": int(process_count())}
        if getattr(self.trainer, "is_zero", False):
            lay = getattr(getattr(self.trainer, "opt", None), "_layout",
                          None)
            if lay is not None:
                meta["flat_total"] = int(lay.total)
                meta["bucket_bytes"] = int(
                    getattr(self.trainer.opt, "bucket_bytes", None) or 0)
        return meta

    def _host_state(self, step: int) -> dict:
        host = {"step": int(step)}
        if self.data is not None and hasattr(self.data, "state_dict"):
            host["data"] = self.data.state_dict()
        world = self._world_meta()
        if world is not None:
            host["world"] = world
        return host

    # -- restore ----------------------------------------------------------
    def _load_data_cursor(self, host: dict) -> None:
        """Seek the data iterator to the sidecar cursor. A cursor saved
        under a different host grid goes through the explicit ``reseek``
        path (world-size change: the GLOBAL sequence is preserved, the
        per-host slicing follows the new grid); same-grid restores keep
        the strict ``load_state_dict`` validation."""
        if (self.data is None or "data" not in host
                or not hasattr(self.data, "load_state_dict")):
            return
        dstate = host["data"]
        saved_hosts = (dstate.get("num_hosts")
                       if isinstance(dstate, dict) else None)
        cur_hosts = getattr(self.data, "num_hosts", None)
        if (saved_hosts is not None and cur_hosts is not None
                and int(saved_hosts) != int(cur_hosts)
                and hasattr(self.data, "reseek")):
            self.data.reseek(dstate)
        else:
            self.data.load_state_dict(dstate)

    def _restore_resharded(self, state: tuple, saved: dict,
                           cur: dict) -> tuple:
        """Cross-world-size restore: the checkpoint's ZeRO flat shards
        were laid out for ``saved['dp']``; re-partition them for
        ``cur['dp']`` (docs/ROBUSTNESS.md, "Elastic world-size
        shrink-resume"). Only the data axis may change — tp/pp/cp
        resharding would need the partition-rule engine (ROADMAP item 1)
        and is refused loudly above. Returns ``(restored, host)``."""
        import jax.numpy as jnp
        import numpy as np

        from apex_tpu.elastic import reshard as _reshard

        opt_state = state[2]
        if not (hasattr(opt_state, "master")
                and hasattr(opt_state, "bucket_stamp")):
            raise ValueError(
                f"world size changed (saved dp={saved['dp']}, live "
                f"dp={cur['dp']}) but the optimizer state "
                f"({type(opt_state).__name__}) is not a ZeRO flat-shard "
                f"state this runner knows how to reshard")
        if "flat_total" not in saved:
            raise ValueError(
                f"world size changed (saved dp={saved['dp']}, live "
                f"dp={cur['dp']}) but the checkpoint sidecar carries no "
                f"flat_total — it was not written by a ZeRO trainer, so "
                f"there is no flat-shard layout to reshard")
        total = int(saved["flat_total"])
        bb_old = int(saved.get("bucket_bytes", 0)) or None
        bb_new = int(getattr(self.trainer.opt, "bucket_bytes", None)
                     or 0) or None
        pp, tp = int(saved["pp"]), int(saved["tp"])
        dp_old, dp_new = int(saved["dp"]), int(cur["dp"])
        padded_old, _ = _reshard.flat_grid(total, dp_old, bb_old)
        # restore the old-layout flat vectors REPLICATED on the live
        # mesh: a target without a sharding makes orbax fall back to the
        # sharding stored in the checkpoint, which names the DEAD
        # world's devices. (Replicated = every surviving host reads the
        # full flat vector — fine at the optimizer-state scale this
        # serves; a shard-aware read is an optimization for later.)
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = getattr(self.trainer, "mesh", None)
        flat_sds = jax.ShapeDtypeStruct(
            (pp * tp * padded_old,), jnp.float32,
            sharding=(NamedSharding(mesh, PartitionSpec())
                      if mesh is not None else None))
        old_target = (*state[:2],
                      opt_state._replace(master=flat_sds,
                                         exp_avg=flat_sds,
                                         exp_avg_sq=flat_sds),
                      *state[3:])

        # leaves WITHOUT a mesh sharding (loss-scale scalars live on a
        # single default device) normally restore via orbax's
        # sharding-from-file fallback — but this checkpoint's file
        # shardings name the DEAD world's devices, so pin every such
        # leaf to replicated on the live mesh instead
        def pin(x):
            if _ckpt._is_prng_key(x):
                return x  # key leaves keep the default path
            sh = getattr(x, "sharding", None)
            if (sh is not None and not hasattr(sh, "mesh")
                    and mesh is not None):
                return jax.ShapeDtypeStruct(
                    x.shape, x.dtype,
                    sharding=NamedSharding(mesh, PartitionSpec()))
            return x

        old_target = jax.tree_util.tree_map(
            pin, old_target, is_leaf=_ckpt._is_prng_key)
        restored, host = _ckpt.restore_checkpoint(self.directory,
                                                  old_target)
        ropt = _reshard.reshard_zero_state(
            restored[2], total=total, dp_old=dp_old, dp_new=dp_new,
            bucket_bytes=bb_old, bucket_bytes_new=bb_new, pp=pp, tp=tp)
        # land each resharded flat leaf on the LIVE state's sharding (the
        # new mesh's shard spec, straight off the init_state target)
        put = lambda np_leaf, live: jax.device_put(
            np.asarray(np_leaf), live.sharding)
        new_opt = restored[2]._replace(
            master=put(ropt.master, opt_state.master),
            exp_avg=put(ropt.exp_avg, opt_state.exp_avg),
            exp_avg_sq=put(ropt.exp_avg_sq, opt_state.exp_avg_sq))
        if (bb_old or 0) != (bb_new or 0):
            # the reshard re-bucketed the shards for the NEW grid, so
            # the stamp must now certify the new layout — check_state at
            # the jit boundary re-validates it against the live config
            new_opt = new_opt._replace(
                bucket_stamp=jnp.asarray(bb_new or 0, jnp.int32))
        self._registry.counter("resume/reshards").inc()
        return (*restored[:2], new_opt, *restored[3:]), host

    def _restore(self, state: tuple) -> tuple:
        """Latest-COMMITTED restore onto the live state's layout; returns
        ``(state, completed_steps, restored_from, resharded)``."""
        latest = _ckpt.latest_step(self.directory)
        if latest is None:
            # still warn about torn dirs a dead writer left behind
            torn = _ckpt.torn_steps(self.directory)
            if torn:
                import warnings
                warnings.warn(
                    f"no committed checkpoint under {self.directory!r}; "
                    f"ignoring torn dir(s) at step(s) {torn} and starting "
                    "from scratch")
            return state, 0, None, False
        t0 = time.perf_counter()
        # peek at the saved world BEFORE building the restore target:
        # the ZeRO flat-shard shapes on disk are a function of the OLD dp
        _, peek = _ckpt.read_host_state(self.directory, latest)
        saved_world, cur_world = peek.get("world"), self._world_meta()
        resharded = False
        if (saved_world is not None and cur_world is not None
                and any(int(saved_world.get(k, cur_world[k]))
                        != cur_world[k] for k in ("pp", "tp", "cp"))):
            raise ValueError(
                f"checkpoint was saved on a pp={saved_world.get('pp')} x "
                f"tp={saved_world.get('tp')} x "
                f"cp={saved_world.get('cp')} grid but this trainer runs "
                f"pp={cur_world['pp']} x tp={cur_world['tp']} x "
                f"cp={cur_world['cp']}; only the data axis is elastic — "
                f"model-axis resharding needs the partition-rule engine")
        if (saved_world is not None and cur_world is not None
                and int(saved_world.get("dp", cur_world["dp"]))
                != cur_world["dp"]
                and getattr(self.trainer, "is_zero", False)):
            restored, host = self._restore_resharded(
                state, saved_world, cur_world)
            resharded = True
        else:
            # replicated/param leaves have dp-independent global shapes,
            # so a dp change without ZeRO state restores verbatim
            restored, host = _ckpt.restore_checkpoint(self.directory,
                                                      state)
        self._registry.gauge("resume/restore_ms").set(
            (time.perf_counter() - t0) * 1e3)
        step = int(host.get("step", latest))
        self._registry.gauge("resume/restored_step").set(step)
        self._registry.counter("resume/resumes").inc()
        self._load_data_cursor(host)
        # the restored step IS durably on disk — mark it saved, so a fit
        # that runs zero further steps (restart after completion, or a
        # preemption landing immediately) does not re-save it:
        # save_checkpoint rmtree's the existing dir before rewriting, and
        # a kill in that window would destroy the newest (with
        # keep_last=1, the only) COMMITTED checkpoint. EXCEPTION: a
        # resharded restore must re-save promptly — the on-disk layout
        # still belongs to the OLD world and a second restart would pay
        # the reshard again (and the old-world sidecar would keep
        # winning), so leave last_saved_step unset to let the cadence
        # write a new-world generation.
        if not resharded:
            self.ckpt.last_saved_step = step
        # materialize XLA-owned buffers before the state can be DONATED:
        # orbax-restored arrays may alias host memory the runtime does not
        # own, and jit_train_step's donate_argnums would free/reuse it
        # under the allocator's feet (see elastic/ckpt.owned_copy)
        return tuple(owned_copy(restored)), step, step, resharded

    # -- preemption -------------------------------------------------------
    def _preempt(self, ar: AutoResume, state: tuple, step: int,
                 loss: Any, restored_from: Optional[int],
                 resharded: bool = False) -> FitResult:
        """The grace-window path: drain the in-flight save, write a final
        checkpoint at the current completed step, then hand control back
        to the scheduler (exit 0 via ``request_resume``).

        Two-signal semantics: the drain runs under
        :func:`_second_signal_escalation` — a SECOND SIGTERM/SIGINT while
        the final save is being written raises :class:`DrainInterrupt`
        immediately (a stuck/slow save cannot make the job unkillable;
        the abandoned write is at worst a torn dir the next restore skips
        loudly)."""
        with _second_signal_escalation():
            self.ckpt.drain()
            if self.ckpt.last_saved_step != step:
                self.ckpt.save(state, step,
                               host_state=self._host_state(step),
                               block=True)
        self._registry.counter("resume/preempt_exits").inc()
        if self.publisher is not None:
            # the final snapshot must beat the exit: the supervisor's
            # postmortem reads it after this process is gone
            self.publisher.publish(step, force=True)
        if self.exit_on_preempt:
            ar.request_resume()  # sys.exit(0): scheduler restarts the job
        return FitResult(state=state, step=step,
                         loss=None if loss is None else float(loss),
                         preempted=True, restored_from=restored_from,
                         resharded=resharded)

    # -- the loop ---------------------------------------------------------
    def fit(self, steps: int, *, key: Optional[jax.Array] = None,
            state: Optional[tuple] = None,
            no_recompile: bool = False) -> FitResult:
        """Run until ``steps`` total steps are COMPLETED (counting the
        restored prefix), checkpointing on the way. ``state`` overrides
        the freshly-initialized state used as the restore target (its
        shapes/dtypes/shardings define the checkpoint layout).

        ``no_recompile=True`` wraps the step loop in the analysis
        engine's :class:`~apex_tpu.analysis.program.recompile_guard`:
        the first iteration (including its save, whose fp32-cast path
        compiles once) is the warmup baseline; any compile-storm counter
        movement after it raises ``AnalysisError`` — a shape or
        static-arg leak retracing the production step fails loudly
        instead of silently multiplying step time."""
        from contextlib import nullcontext

        if state is None:
            state = self.trainer.init_state(
                key if key is not None else jax.random.PRNGKey(0))
        state, step, restored_from, resharded = self._restore(tuple(state))
        ar = self.autoresume
        own_ar = ar is None
        if own_ar:
            ar = AutoResume(interval=1)
        step_fn = self.trainer.jit_train_step()
        loss = None
        if self._multiprocess:
            from apex_tpu.parallel.multiproc import any_process
        else:
            any_process = bool
        if no_recompile:
            from apex_tpu.analysis.program import recompile_guard
            guard = recompile_guard("ElasticRunner.fit")
        else:
            guard = nullcontext()
        warm_steps, saved_once = 0, False
        preempted = False
        try:
            # the guard covers ONLY the steady-state loop: the preempt
            # drain and the final checkpoint are one-shot paths whose
            # first-use compiles (fp32-on-disk casts) are not a storm
            with guard:
                while step < steps:
                    if self.fault_plan is not None:
                        self.fault_plan.before_step(step)
                    # multi-controller: the preemption decision must be
                    # COLLECTIVE — if any process saw the signal, every
                    # process must leave the loop at this same step, or
                    # the survivors deadlock in the next step's
                    # collectives while the drained rank waits in the
                    # checkpoint barrier (any_process is a tiny
                    # allgather; the identity in a 1-process world)
                    if any_process(ar.termination_requested(step)):
                        preempted = True
                        break
                    batch = next(self.data)
                    loss, *state = step_fn(*state, *batch)
                    state = tuple(state)
                    step += 1
                    self._m_steps.inc()
                    if self.publisher is not None:
                        self.publisher.publish(step)
                    if self.on_step is not None:
                        self.on_step(step, loss)
                    saved = False
                    if step % self.save_interval == 0 and step < steps:
                        self.ckpt.save(state, step,
                                       host_state=self._host_state(step))
                        saved = True
                    # warmup baselines: the first TWO dispatches compile
                    # the step (a freshly-initialized state and the
                    # donated step outputs differ in sharding
                    # memory-kind, so iteration 2 legitimately adds a
                    # second cache entry), and the first save compiles
                    # the storage casts — all expected; anything after
                    # them is the leak. The first save is drained so its
                    # async worker's compiles land BEFORE the rebase,
                    # not racing it.
                    if no_recompile and (warm_steps < 2
                                         or (saved and not saved_once)):
                        if saved and not saved_once:
                            self.ckpt.drain()
                        guard.rebase()
                    warm_steps += 1
                    saved_once = saved_once or saved
            if preempted:
                return self._preempt(ar, state, step, loss,
                                     restored_from, resharded)
            # run complete: drain the tail save, then commit the final one
            self.ckpt.drain()
            if any_process(ar.termination_requested(step)):
                return self._preempt(ar, state, step, loss, restored_from,
                                     resharded)
            if self.final_save and self.ckpt.last_saved_step != step:
                self.ckpt.save(state, step,
                               host_state=self._host_state(step),
                               block=True)
            if self.publisher is not None:
                self.publisher.publish(step, force=True)
            return FitResult(state=state, step=step,
                             loss=None if loss is None else float(loss),
                             preempted=False, restored_from=restored_from,
                             resharded=resharded)
        finally:
            if own_ar:
                ar.close()
