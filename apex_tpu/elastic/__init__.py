"""Elastic training runtime: survive preemption without losing the run.

The production run-loop layer over :class:`~apex_tpu.training
.GPTHybridTrainer` (ROADMAP item 4 — "heavy traffic" for training jobs):

- :mod:`~apex_tpu.elastic.ckpt` — CheckFreq-style async checkpointing:
  snapshot to host inside the step cadence, serialize off-thread with the
  COMMITTED-marker atomicity of :mod:`apex_tpu.checkpoint`, bounded
  retry-with-backoff, ``keep_last`` GC, ``ckpt/*`` metrics.
- :mod:`~apex_tpu.elastic.runner` — the preemption-safe step loop:
  polls :class:`~apex_tpu.utils.autoresume.AutoResume`, drains the
  in-flight save, writes a final checkpoint, and requests a clean
  restart inside the SIGTERM grace window; on startup restores the
  latest COMMITTED checkpoint and continues bitwise.
- :mod:`~apex_tpu.elastic.faults` — deterministic, seeded fault
  injection (SIGTERM-at-step-K, transient save ``OSError``\\ s, torn
  checkpoint dirs) so recovery is *tested*, not hoped for.
- :mod:`~apex_tpu.elastic.data` — seeded per-host sharded index
  iteration with a checkpointable cursor and double-buffered
  ``device_put`` prefetch.
- :mod:`~apex_tpu.elastic.launch` — the localhost multi-process
  launcher + elastic supervisor: heartbeat liveness AND step-progress
  (stall) detection, gang teardown with a
  :class:`~apex_tpu.observability.fleet.PostmortemReport` naming the
  likely culprit rank, bounded restart-with-backoff, world-size
  **shrink** when a process death is permanent (``elastic/*`` metrics),
  and — via the :mod:`~apex_tpu.observability.fleet` merge layer — a
  live ``/metrics``+``/fleet`` endpoint over the cross-rank merged
  registry (``fleet/*`` straggler signals).
- :mod:`~apex_tpu.elastic.reshard` — the cross-world-size restore math:
  bucket-major ZeRO flat shards re-partitioned dp_old → dp_new,
  element-identically on the natural flat-vector content.

See ``docs/ROBUSTNESS.md`` for the checkpoint format, the preemption
walkthrough, the bitwise-resume contract, and the multi-host
(coordinator bootstrap / heartbeat / shrink-resume) protocol.
"""

from apex_tpu.elastic.ckpt import (AsyncCheckpointer, host_snapshot,
                                   owned_copy, snapshot_nbytes)
from apex_tpu.elastic.data import (PrefetchingIterator,
                                   ShardedIndexIterator,
                                   token_batch_fetcher)
from apex_tpu.elastic.faults import FaultPlan
from apex_tpu.elastic.launch import (Heartbeat, LaunchReport,
                                     LocalLauncher, RoundResult)
from apex_tpu.elastic.runner import (DrainInterrupt, ElasticRunner,
                                     FitResult)

__all__ = ["AsyncCheckpointer", "DrainInterrupt", "ElasticRunner",
           "FaultPlan", "FitResult", "Heartbeat", "LaunchReport",
           "LocalLauncher", "PrefetchingIterator", "RoundResult",
           "ShardedIndexIterator", "host_snapshot", "owned_copy",
           "snapshot_nbytes", "token_batch_fetcher"]
