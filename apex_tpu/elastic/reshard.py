"""Cross-world-size resharding of bucket-major ZeRO flat shards.

The elastic shrink contract (ROADMAP item 3, docs/ROBUSTNESS.md): when a
process dies permanently, the survivors restore the last COMMITTED
checkpoint onto a **smaller** mesh and continue. Replicated leaves
(params, loss scale) restore as-is — their global shapes do not depend on
dp. The ZeRO optimizer state does NOT: each device owns a flat fp32
shard of the master params/moments whose *element order* is a function of
the dp grid twice over —

1. the padded flat length is ``ceil(total / dp) * dp`` (the layout pads
   to a multiple of the shard count), and
2. with bucketing, the shard is **bucket-major**: rank ``r``'s shard is
   the concatenation over buckets ``b`` of bucket ``b``'s ``r``-th
   ``1/dp`` slice (``optimizers/distributed_fused.py::_my_slice``), and
   the bucket spans themselves are rounded to multiples of dp
   (``optimizers/_flatten.bucket_bounds``).

So a dp=4 checkpoint restored verbatim into a dp=2 world would not just
be the wrong shape — trimmed or re-split it would silently permute every
master/moment element. This module is the exact inverse+forward of that
layout, built on the same span machinery: recover the **natural**
(leaf-order) flat vector from the old grid's global array, then re-emit
it in the new grid's bucket-major order. The round trip is a pure index
permutation — element-identical, no arithmetic — which is what makes the
shrink-resume parity guarantee provable (tier-1 asserts it on the
flat-vector content; the multichip gate proves the end-to-end run).

Axis layout: the trainer stores the ZeRO state sharded
``P(("pipe", "data", "tensor"))`` along dim 0, pipe-major then data then
tensor (``GPTHybridTrainer._zero_state_spec``). Every (pipe, tensor)
coordinate is an independent flat vector with the SAME layout (stage
stacks have identical per-rank shapes), so the global array reshapes to
``(pp, dp, tp, chunk)`` and each of the ``pp*tp`` columns reshards
independently.

All functions are host-side numpy on fp32 vectors — resharding happens
once per world-size change, between the orbax read and the device_put
onto the new mesh, never inside a traced program.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from apex_tpu.optimizers._flatten import FlatLayout, bucket_bounds

__all__ = ["flat_grid", "shard_permutation", "to_natural", "from_natural",
           "reshard_flat", "reshard_zero_state"]


def flat_grid(total: int, dp: int, bucket_bytes):
    """``(padded, bounds)`` of a ``total``-element flat vector sharded
    ``dp`` ways under ``bucket_bytes`` — the same grid
    :func:`~apex_tpu.optimizers._flatten.bucket_bounds` serves the
    optimizers, derived here from the two integers the checkpoint sidecar
    records (``flat_total``, ``bucket_bytes``) instead of a live param
    tree."""
    if total < 1 or dp < 1:
        raise ValueError(f"need total >= 1 and dp >= 1, got {total}/{dp}")
    bucket_bytes = bucket_bytes or None  # sidecars spell monolithic as 0
    padded = -(-total // dp) * dp
    lay = FlatLayout(treedef=None, shapes=(), dtypes=(), sizes=(),
                     offsets=(), total=total, padded=padded,
                     chunk=padded // dp)
    return padded, bucket_bounds(lay, bucket_bytes)


def shard_permutation(total: int, dp: int, bucket_bytes) -> np.ndarray:
    """Index map ``idx`` (length ``padded``) with
    ``data_axis_global = natural_padded[idx]``: position ``p`` of the
    dp-concatenated bucket-major global vector holds natural element
    ``idx[p]``. Rank-major outer order (the data-axis concatenation),
    bucket-major inner (``_my_slice``)."""
    padded, bounds = flat_grid(total, dp, bucket_bytes)
    idx = np.empty(padded, np.int64)
    pos = 0
    for r in range(dp):
        for goff, n in bounds:
            nb = n // dp
            idx[pos:pos + nb] = np.arange(goff + r * nb,
                                          goff + (r + 1) * nb)
            pos += nb
    return idx


def to_natural(col: np.ndarray, total: int, dp: int,
               bucket_bytes) -> np.ndarray:
    """One (pipe, tensor) column of the dp-sharded global vector back to
    natural leaf order, padding dropped — the inverse permutation."""
    col = np.asarray(col)
    padded, _ = flat_grid(total, dp, bucket_bytes)
    if col.shape != (padded,):
        raise ValueError(
            f"column has shape {col.shape}, expected ({padded},) for "
            f"total={total} sharded dp={dp}")
    nat = np.empty_like(col)
    nat[shard_permutation(total, dp, bucket_bytes)] = col
    return nat[:total]


def from_natural(nat: np.ndarray, dp: int, bucket_bytes) -> np.ndarray:
    """Natural leaf-order vector (length ``total``) to the dp-sharded
    bucket-major global order, zero-padded to the new grid."""
    nat = np.asarray(nat)
    total = nat.shape[0]
    padded, _ = flat_grid(total, dp, bucket_bytes)
    if padded != total:
        nat = np.concatenate([nat, np.zeros(padded - total, nat.dtype)])
    return nat[shard_permutation(total, dp, bucket_bytes)]


_SAME = object()  # "same grid on both sides" default sentinel


def reshard_flat(arr: np.ndarray, *, total: int, dp_old: int, dp_new: int,
                 bucket_bytes, bucket_bytes_new=_SAME, pp: int = 1,
                 tp: int = 1) -> np.ndarray:
    """Re-partition a ``P(("pipe","data","tensor"))``-order global flat
    vector from a ``dp_old`` grid to a ``dp_new`` grid (shrink or grow;
    ``bucket_bytes_new`` additionally re-buckets — the natural-order
    round trip makes a bucket-grid change free here, where the live
    ``bucket_stamp`` guard must refuse it). Element-identical on the
    natural content: ``to_natural(reshard_flat(x)) == to_natural(x)``
    for every column, exactly — the padding tail is the only part
    rebuilt (zeros).
    """
    if bucket_bytes_new is _SAME:
        bucket_bytes_new = bucket_bytes
    arr = np.asarray(arr)
    padded_old, _ = flat_grid(total, dp_old, bucket_bytes)
    padded_new, _ = flat_grid(total, dp_new, bucket_bytes_new)
    if arr.shape != (pp * dp_old * tp * (padded_old // dp_old),):
        raise ValueError(
            f"flat array has shape {arr.shape}, expected "
            f"({pp * tp * padded_old},) for total={total} over "
            f"pp={pp} x dp={dp_old} x tp={tp}")
    # (pp, dp, tp, chunk) mesh order -> (pp, tp) columns of (padded,)
    cols = arr.reshape(pp, dp_old, tp, padded_old // dp_old) \
              .transpose(0, 2, 1, 3).reshape(pp * tp, padded_old)
    # the permutations depend only on (total, dp, bucket_bytes) — build
    # each ONCE, not once per (pp*tp) column (at real model scale the
    # O(padded) index builds dominate the one-shot restore otherwise)
    idx_old = shard_permutation(total, dp_old, bucket_bytes)
    idx_new = shard_permutation(total, dp_new, bucket_bytes_new)

    def recolumn(col):
        nat = np.empty_like(col)
        nat[idx_old] = col                      # inverse of the old grid
        if padded_new > total:
            nat = np.concatenate(
                [nat[:total], np.zeros(padded_new - total, nat.dtype)])
        else:
            nat = nat[:padded_new]
        return nat[idx_new]                     # forward onto the new

    new_cols = np.stack([recolumn(c) for c in cols])
    return new_cols.reshape(pp, tp, dp_new, padded_new // dp_new) \
                   .transpose(0, 2, 1, 3).reshape(-1)


def reshard_zero_state(opt_state: Any, *, total: int, dp_old: int,
                       dp_new: int, bucket_bytes,
                       bucket_bytes_new=_SAME, pp: int = 1,
                       tp: int = 1) -> Any:
    """Reshard every flat-shard leaf of a
    :class:`~apex_tpu.optimizers.distributed_fused.ZeroAdamState`
    (``master``/``exp_avg``/``exp_avg_sq``) from ``dp_old`` to
    ``dp_new``; ``step`` and ``bucket_stamp`` pass through (the bucket
    grid itself is unchanged — the stamp stays valid on the new world and
    the ``check_state`` guard at the jit boundary re-validates it
    there). Leaves come back as numpy; the caller device_puts them onto
    the new mesh's shard spec."""
    kw = dict(total=total, dp_old=dp_old, dp_new=dp_new,
              bucket_bytes=bucket_bytes, bucket_bytes_new=bucket_bytes_new,
              pp=pp, tp=tp)
    return opt_state._replace(
        master=reshard_flat(np.asarray(opt_state.master), **kw),
        exp_avg=reshard_flat(np.asarray(opt_state.exp_avg), **kw),
        exp_avg_sq=reshard_flat(np.asarray(opt_state.exp_avg_sq), **kw))
