"""fp16_utils — the pre-amp manual mixed-precision surface, as a thin
adapter over the modern pieces.

Reference: ``reference:apex/fp16_utils/`` — ``FP16_Optimizer``
(``fp16_optimizer.py:13-554``), ``network_to_half``/``convert_network``
(``fp16util.py:35-80``), ``LossScaler``/``DynamicLossScaler``
(``loss_scaler.py:10,47``). The reference keeps these for backward
compatibility and points users at amp; here the module is a *working*
compatibility shim: every entry point delegates to
:mod:`apex_tpu.amp` / :mod:`apex_tpu.optimizers`, so legacy-style code
runs, while new code should use the policy + scaler API directly.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import cast_floating
from apex_tpu.amp.scaler import (DynamicLossScale, LossScaleState,
                                 StaticLossScale, all_finite)

__all__ = ["FP16_Optimizer", "network_to_half", "convert_network",
           "LossScaler", "DynamicLossScaler", "master_params_to_model_params",
           "prep_param_lists"]

# loss-scaler aliases: the fp16_utils classes are the static/dynamic
# scalers of loss_scaler.py:10,47 — same protocol as the amp ones
LossScaler = StaticLossScale
DynamicLossScaler = DynamicLossScale


def network_to_half(params: Any) -> Any:
    """Cast float leaves to fp16 (``fp16util.py:35-44``). Prefer bf16 via
    ``convert_network(params, jnp.bfloat16)`` on TPU."""
    return cast_floating(params, jnp.float16)


def convert_network(params: Any, dtype) -> Any:
    """``fp16util.py:60-80``: cast float leaves to ``dtype``."""
    return cast_floating(params, dtype)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """``fp16util.py:97-135``: returns ``(model_params, master_params)`` —
    here master = fp32 copy of the tree (flat FP32 buffers are the
    :class:`~apex_tpu.optimizers.FlatOptimizer` tier instead)."""
    return params, cast_floating(params, jnp.float32)


def master_params_to_model_params(model_params: Any, master_params: Any) -> Any:
    """``fp16util.py:150-162``: copy master values into the model dtypes."""
    return jax.tree_util.tree_map(
        lambda mp, ma: ma.astype(mp.dtype) if hasattr(mp, "dtype") else ma,
        model_params, master_params)


class FP16_Optimizer:
    """Legacy wrapper (``fp16_optimizer.py:13-554``): fp32 master params +
    loss scaling around any suite optimizer.

    Functional usage (state is explicit, as everywhere in this library)::

        opt = FP16_Optimizer(FusedAdam(lr=1e-3), dynamic_loss_scale=True)
        state = opt.init(half_params)
        new_half_params, state = opt.step(grads, state, half_params)

    ``state`` carries ``(master_params_fp32, inner_state, LossScaleState)``;
    grads may be half (they are unscaled into fp32 before the update, the
    ``update_master_grads`` path of :436).
    """

    def __init__(self, inner, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False, **scale_kw):
        self.inner = inner
        self.scaler = (DynamicLossScale(**scale_kw) if dynamic_loss_scale
                       else StaticLossScale(static_loss_scale))

    def init(self, params: Any):
        master = cast_floating(params, jnp.float32)
        return (master, self.inner.init(master), self.scaler.init())

    def scale_loss(self, state, loss):
        """The ``optimizer.backward(loss)`` pre-scale (:373)."""
        return self.scaler.scale(state[2], loss)

    def step(self, grads: Any, state, params: Any,
             **kw) -> Tuple[Any, Any]:
        master, inner_state, ls = state
        grads32 = self.scaler.unscale(ls, grads)
        finite = all_finite(grads32)
        new_ls = self.scaler.update(ls, finite)
        new_master, new_inner = self.inner.step(
            grads32, inner_state, master, grads_finite=finite, **kw)
        new_params = master_params_to_model_params(params, new_master)
        return new_params, (new_master, new_inner, new_ls)
