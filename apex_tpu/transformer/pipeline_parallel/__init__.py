"""Pipeline parallelism (``reference:apex/transformer/pipeline_parallel/``)."""

from apex_tpu.transformer.pipeline_parallel.microbatches import (  # noqa: F401
    ConstantNumMicroBatches, NumMicroBatchesCalculator,
    RampupBatchsizeNumMicroBatches, build_num_microbatches_calculator)
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (  # noqa: F401
    rotate_backward, rotate_forward)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func, pipelined_apply)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: F401
    average_losses_across_data_parallel_group, get_kth_microbatch,
    get_ltor_masks_and_position_ids, get_num_microbatches,
    setup_microbatch_calculator, update_num_microbatches)

__all__ = [
    "get_forward_backward_func", "pipelined_apply",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "rotate_forward", "rotate_backward",
    "ConstantNumMicroBatches", "RampupBatchsizeNumMicroBatches",
    "NumMicroBatchesCalculator", "build_num_microbatches_calculator",
    "setup_microbatch_calculator", "get_num_microbatches",
    "update_num_microbatches", "get_kth_microbatch",
    "average_losses_across_data_parallel_group",
    "get_ltor_masks_and_position_ids",
]
