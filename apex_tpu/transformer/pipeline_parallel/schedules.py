"""Pipeline-parallel forward/backward schedules.

Reference: ``reference:apex/transformer/pipeline_parallel/schedules/`` —
``get_forward_backward_func`` (:__init__.py:22) dispatching between
no-pipelining (:fwd_bwd_no_pipelining.py:31-103), 1F1B without interleaving
(:fwd_bwd_pipelining_without_interleaving.py:155-345) and interleaved
virtual-pipeline 1F1B (:fwd_bwd_pipelining_with_interleaving.py:25-375).

TPU redesign. The reference drives each microbatch's fwd/bwd from Python
with explicit NCCL p2p — impossible and unnecessary under jit. Here a
schedule is a *traced program*: a ``lax.scan`` over pipeline ticks inside
``shard_map`` over the ``pipe`` axis, with one ``ppermute`` rotation per
tick. Two backward drivers exist:

* the DEFAULT (``memory_efficient=True``, :func:`_onef1b_fwd_bwd`): one
  scan whose tick runs one forward AND one backward microbatch per global
  stage via explicit ``jax.vjp`` with recompute — the true 1F1B memory
  bound, O(pp·vpp) in-flight activations regardless of microbatch count
  (the role of the reference's interleaved fwd/bwd +
  ``free_output_tensor``, :schedules/common.py:198-249);
* the AD driver (``memory_efficient=False``): differentiating the
  forward tick scan yields the backward pipeline automatically (the
  transpose of ``ppermute`` is the reverse rotation; the reversed scan
  replays the cooldown/steady/warmup structure) — the reference's
  340-line warmup/steady/cooldown bookkeeping as autodiff. Residuals are
  O(ticks) per stage; ``remat=True`` shrinks each tick's residual to the
  carry.

The stage function must be *stage-uniform* (same jaxpr on every device) and
branch on the traced stage index for first/last specifics — the SPMD analog
of ``build_model``'s pre_process/post_process flags
(:schedules/common.py:29-148).

Microbatch m enters stage 0 at tick m and exits stage S-1 (chunk vpp-1) at
tick m + L - 1 (L = S*vpp global stages); total ticks = M + L - 1. Bubble
ticks process zeros and are masked out of the loss.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.observability import ingraph as _metrics
from apex_tpu.remat import RematPolicy
from apex_tpu.transformer.parallel_state import PIPE_AXIS
from apex_tpu.utils.vma import cast_to_vma
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    rotate_backward, rotate_forward)
from apex_tpu.utils.compat import HAS_VMA
from apex_tpu.utils.compat import axis_size as _axis_size


def _record_schedule_metrics(num_microbatches: int, ticks: int,
                             useful_ticks: int) -> None:
    """Static schedule shape telemetry (trace-time Python constants — free
    even when a collector is active, absent when not). ``bubble_fraction``
    is the analytic idle share of stage time slots: each of ``ticks``
    slots per stage runs at most one microbatch unit of useful work, of
    which ``useful_ticks`` are non-bubble — Megatron's (p-1)/(m+p-1) for
    the forward pipe, (2p-1)/(m+2p-1) for the fwd+bwd 1F1B scan. Per-tick
    *wall* times are a trace concern: the ``pipeline_tick`` named_scope
    labels every tick's fusions in a ``profile_trace`` capture."""
    _metrics.record("pipeline/num_microbatches", float(num_microbatches),
                    reduce="mean")
    _metrics.record("pipeline/ticks", float(ticks), reduce="mean")
    _metrics.record("pipeline/bubble_fraction",
                    1.0 - useful_ticks / ticks, reduce="mean")




__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipelined_apply",
]


# ---------------------------------------------------------------------------
# no pipelining: scan over microbatches, grad accumulation
# ---------------------------------------------------------------------------

def forward_backward_no_pipelining(
    forward_step_func: Callable,
    batch: Any,
    params: Any,
    *,
    forward_only: bool = False,
    grad_scale: Any = 1.0,
    loss_fn: Optional[Callable] = None,
    num_model_chunks: Optional[int] = None,
    remat: Any = False,
) -> Tuple[jnp.ndarray, Any]:
    """``fwd_bwd_no_pipelining.py:31-103``: loop microbatches, accumulate.

    ``forward_step_func(params, microbatch) -> loss`` (scalar, already
    averaged over the microbatch). ``batch`` is a pytree whose leaves have a
    leading ``num_microbatches`` axis (see
    :func:`~apex_tpu.transformer.pipeline_parallel.utils.get_kth_microbatch`
    for slicing helpers). Returns ``(mean_loss, grads_or_None)``; grads are
    averaged over microbatches, matching the reference's grad-sync-at-end
    semantics (the no_sync context of :77-85 — accumulation happens locally,
    one sync afterwards by the caller's DDP).

    When ``loss_fn`` is given, the *pipelined* call shape is accepted instead
    so :func:`get_forward_backward_func` call sites are uniform across
    pipeline sizes: ``forward_step_func(params, x, stage_index)`` is the
    whole model (the single stage of a pp=1 run), applied microbatch-wise,
    and ``loss_fn(y, m)`` the head. ``num_model_chunks`` must then be None
    or 1.
    """
    if loss_fn is not None:
        if num_model_chunks not in (None, 1):
            raise ValueError("pp=1 runs have a single model chunk")
        stage_fn = RematPolicy.resolve(remat).wrap(forward_step_func)

        def uniform_step(params, mb_with_index):
            mb, m = mb_with_index
            return loss_fn(stage_fn(params, mb, 0), m)

        n = jax.tree_util.tree_leaves(batch)[0].shape[0]
        batch = (batch, jnp.arange(n))
        forward_step_func = uniform_step

    def one(params, mb):
        if forward_only:
            return forward_step_func(params, mb), None
        loss, grads = jax.value_and_grad(
            lambda p: forward_step_func(p, mb) * grad_scale)(params)
        return loss / grad_scale, grads

    def scan_body(acc, mb):
        loss, grads = one(params, mb)
        acc_loss, acc_grads = acc
        if grads is not None:
            acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, grads)
        return (acc_loss + loss, acc_grads), None

    n_micro = jax.tree_util.tree_leaves(batch)[0].shape[0]
    # pp=1: every tick is useful — reported so the stream's pipeline/*
    # keys exist across schedule choices
    _record_schedule_metrics(n_micro, n_micro, n_micro)
    zero_grads = None if forward_only else jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)
    (total_loss, total_grads), _ = jax.lax.scan(
        scan_body, (jnp.asarray(0.0, jnp.float32), zero_grads), batch)
    mean_loss = total_loss / n_micro
    if forward_only:
        return mean_loss, None
    grads = jax.tree_util.tree_map(
        lambda g: (g / (n_micro * grad_scale)).astype(jnp.float32), total_grads)
    return mean_loss, grads


# ---------------------------------------------------------------------------
# pipelined forward (shared by both pipelined schedules)
# ---------------------------------------------------------------------------

def pipelined_apply(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: jnp.ndarray,
    *,
    num_chunks: int = 1,
    remat: Any = False,
    last_stage_fn: Optional[Callable] = None,
    embed_fn: Optional[Callable] = None,
) -> jnp.ndarray:
    """Run ``microbatches`` through the virtual pipeline; returns the
    per-microbatch outputs of the final global stage, shape ``(M, ...)``.

    Must be called inside ``shard_map`` with the ``pipe`` axis bound.

    - ``stage_fn(chunk_params, x, global_stage) -> y`` — uniform stage body;
      ``global_stage`` is a traced int in ``[0, S*num_chunks)``.
    - ``stage_params``: pytree whose leaves are stacked ``(num_chunks, ...)``
      — this device's chunks (Megatron layout: chunk c on device d is global
      stage ``c*S + d``,
      ``fwd_bwd_pipelining_with_interleaving.py:122-131``).
    - ``microbatches``: ``(M, ...)`` fed to global stage 0; activations keep
      this trailing shape through every stage unless ``embed_fn`` maps them
      first.
    - ``last_stage_fn(y, m_index) -> out`` — applied to the final stage's
      output (e.g. loss head); defaults to identity.
    - ``embed_fn(microbatch) -> activation`` — the first-stage input
      transform (e.g. token embedding), the ``pre_process`` role of
      ``build_model`` (:schedules/common.py:29-148). With it, microbatches
      may have any shape/dtype (e.g. int tokens); the pipelined activation
      is ``embed_fn``'s output. Under SPMD every rank traces the embed (the
      program is stage-uniform) and only stage 0's result is consumed — the
      lookup is negligible next to a transformer stage.

    **Memory profile (measured, see tests/test_pipeline_memory.py).** This
    schedule is *output*-equivalent to the reference's 1F1B, not
    memory-equivalent: AD of the tick scan stores residuals for every tick,
    so backward activation memory is **O((M + L) per-tick residual)** per
    device, while the reference's interleaved fwd/bwd
    (``fwd_bwd_pipelining_without_interleaving.py:155-345``) keeps at most
    O(L) microbatches in flight. What ``remat=True`` guarantees: each
    tick's residual shrinks to the carry (one activation per local chunk) —
    intra-stage activations are recomputed in backward — measured ~4x per-
    microbatch reduction on a 3-matmul stage and exactly the
    carry-per-tick bound asserted in the test. For memory-bound configs
    keep M modest per call (grad-accumulate across calls) or pass
    ``remat=True``.
    """
    S = _axis_size(PIPE_AXIS)
    rank = jax.lax.axis_index(PIPE_AXIS)
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    L = S * num_chunks
    T = M + L - 1
    _record_schedule_metrics(M, T, M)
    # bool | mode string | RematPolicy — "full" (== the legacy True) is
    # plain jax.checkpoint; the name-based policies save/offload the
    # registry-tagged activations the stage_fn emits (apex_tpu/remat.py)
    remat_fn = RematPolicy.resolve(remat).wrap(stage_fn)
    if embed_fn is None:
        if not isinstance(microbatches, jnp.ndarray):
            raise ValueError(
                "pytree microbatches require embed_fn to map them to the "
                "pipelined activation")
        act_shape = microbatches.shape[1:]
        act_dtype = microbatches.dtype
    else:
        mb0 = jax.tree_util.tree_map(
            lambda v: jax.lax.index_in_dim(v, 0, 0, keepdims=False),
            microbatches)
        act_aval = jax.eval_shape(embed_fn, mb0)
        act_shape, act_dtype = act_aval.shape, act_aval.dtype

    def chunk_params_at(c: int):
        return jax.tree_util.tree_map(
            lambda p: jax.lax.index_in_dim(p, c, 0, keepdims=False),
            stage_params)

    @jax.named_scope("pipeline_tick")
    def tick(buf, t):
        # buf: (num_chunks, *act_shape) — input activation per local chunk
        outs = []
        for c in range(num_chunks):
            x = buf[c]
            if c == 0:
                # global stage 0 = device 0 chunk 0 consumes fresh microbatch
                fresh = jax.tree_util.tree_map(
                    lambda v: jax.lax.dynamic_index_in_dim(
                        v, jnp.clip(t, 0, M - 1), 0, keepdims=False),
                    microbatches)
                if embed_fn is not None:
                    fresh = embed_fn(fresh)
                x = jnp.where(rank == 0, fresh.astype(act_dtype), x)
            g_stage = c * S + rank
            y = remat_fn(chunk_params_at(c), x, g_stage)
            outs.append(y.astype(act_dtype))
        stacked = jnp.stack(outs)  # (num_chunks, *act_shape)
        # rotate all chunk outputs to the next device
        received = rotate_forward(stacked)
        # wrap rule: device 0's chunk c>0 consumes last device's chunk c-1
        new_buf = [jnp.zeros(act_shape, act_dtype)] * num_chunks
        for c in range(num_chunks):
            if c == 0:
                new_buf[0] = received[0]  # overwritten by fresh on rank 0
            else:
                new_buf[c] = jnp.where(rank == 0, received[c - 1], received[c])
        # final-stage output this tick (device S-1, chunk num_chunks-1)
        final_out = outs[num_chunks - 1]
        return jnp.stack(new_buf), final_out

    # fixed-point the carry's varying-axes set: the stage body may add axes
    # (e.g. a TP bias makes activations tensor-varying)
    zeros = jnp.zeros((num_chunks,) + act_shape, act_dtype)
    carry_vma = frozenset({PIPE_AXIS})
    for _ in range(4):
        init = cast_to_vma(zeros, carry_vma)
        out_vma = getattr(jax.eval_shape(
            lambda b: tick(b, jnp.asarray(0))[0], init), "vma", frozenset())
        if out_vma <= carry_vma:
            break
        carry_vma = carry_vma | out_vma

    def tick_stable(buf, t):
        new_buf, final_out = tick(buf, t)
        return cast_to_vma(new_buf, carry_vma), final_out

    _, final_outs = jax.lax.scan(tick_stable, init, jnp.arange(T))

    # final stage emits microbatch m at tick m + L - 1; broadcast the last
    # device's outputs over the pipe axis (masked psum) so every stage
    # returns the same — replicated — result
    outs = jax.lax.dynamic_slice_in_dim(final_outs, L - 1, M, axis=0)
    outs = jax.lax.psum(jnp.where(rank == S - 1, outs, jnp.zeros_like(outs)),
                        PIPE_AXIS)
    if last_stage_fn is not None:
        outs = jax.vmap(last_stage_fn)(outs, jnp.arange(M))
    return outs


# ---------------------------------------------------------------------------
# memory-efficient 1F1B: hand-driven vjp inside the tick scan
# ---------------------------------------------------------------------------

from apex_tpu.utils.vma import fixed_point_vma as _fixed_point_vma
from apex_tpu.utils.vma import leaf_vma as _leaf_vma


def _onef1b_fwd_bwd(stage_fn, loss_fn, params, microbatches, remat,
                    grad_scale, shared_params=None, embed_fn=None,
                    num_chunks=1, chunked_params=False):
    """True-1F1B-memory pipelined forward+backward.

    The AD-through-the-tick-scan path (:func:`pipelined_apply`) stores one
    residual per tick — O(M + L) activations per device. The reference's
    1F1B exists precisely to avoid that
    (``reference:apex/transformer/pipeline_parallel/schedules/
    fwd_bwd_pipelining_without_interleaving.py:155-345`` holds at most
    O(pp) microbatches in flight; ``free_output_tensor``,
    ``common.py:198-249``, frees each output the moment its consumer is
    done). This driver reproduces that bound the SPMD way: ONE scan whose
    tick does one forward microbatch AND one backward microbatch per
    global stage, with the backward built from an explicit ``jax.vjp``
    that *recomputes* the stage forward (the reference's
    activation-checkpoint + free trade). The scan itself is never
    differentiated, so its carry — not AD residuals — is the whole
    activation memory:

    - ``saved``: per-chunk input-activation rings of ``2(L - c*S)`` slots
      (chunk c's in-flight window; at global stage g only ``2(L-g)-1``
      are live),
    - one in-transit activation + one in-transit cotangent per chunk,
    - the fp32 grad accumulators.

    With ``num_chunks`` = V > 1 this is the interleaved virtual pipeline
    (Megatron layout: chunk c on device d is global stage ``g = c*S + d``,
    L = S*V global stages,
    ``reference:.../fwd_bwd_pipelining_with_interleaving.py:25-375``);
    V = 1 reduces to plain 1F1B. Microbatch m runs forward at global
    stage g at tick ``m + g`` and backward at tick ``m + 2L - 1 - g``;
    total ticks ``M + 2L - 1``. The cotangent for (m, g) arrives from
    stage g+1's ``dx`` of the previous tick via the reverse rotation
    (wrapping from device 0 chunk c+1 back to device S-1 chunk c — the
    mirror of the forward wrap); the last global stage seeds from the
    loss vjp. Bubble ticks carry exactly-zero cotangents (vjp is linear
    in the seed), so no masking of the grad accumulation is needed beyond
    the loss/seed masks.

    Slot-reuse safety: a forward write at m_f can only collide with a
    pending backward read at m_b if the (even) chunk ring size divides
    m_f - m_b = 2L - 1 - 2g, which is odd — impossible; and the ring
    covers the window since 2(L - c*S) >= 2L - 2g for every device.

    Compiled temp memory is O(1) in M — asserted by
    ``tests/test_pipeline_memory.py``.
    """
    if embed_fn is not None and shared_params is None:
        raise ValueError(
            "embed_fn takes (shared_params, microbatch); pass the embedding "
            "parameters via shared_params so they are differentiated")
    S = _axis_size(PIPE_AXIS)
    rank = jax.lax.axis_index(PIPE_AXIS)
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    V = num_chunks
    L = S * V
    T = M + 2 * L - 1
    _record_schedule_metrics(M, T, M)
    # per-chunk saved-activation window: chunk c's global stages start at
    # c*S, so at most 2(L - c*S) - 1 microbatches are in flight there; an
    # EVEN buffer size keeps the odd-difference collision-safety argument
    # (below) while not over-allocating the uniform 2L for every chunk
    B = [2 * (L - c * S) for c in range(V)]
    # chunked_params: caller passes leaves with a leading (num_chunks, ...)
    # axis (the interleaved API, valid even at num_chunks=1); otherwise raw
    stacked = chunked_params
    p_stack = params if stacked else jax.tree_util.tree_map(
        lambda p: p[None], params)

    def chunk_params(c):
        return jax.tree_util.tree_map(
            lambda p: jax.lax.index_in_dim(p, c, 0, keepdims=False), p_stack)

    f = RematPolicy.resolve(remat).wrap(stage_fn)

    def mb_at(m):
        return jax.tree_util.tree_map(
            lambda v: jax.lax.dynamic_index_in_dim(
                v, jnp.clip(m, 0, M - 1), 0, keepdims=False), microbatches)

    # activation shape/dtype (after embed, if any)
    if embed_fn is None:
        if not isinstance(microbatches, jnp.ndarray):
            raise ValueError("pytree microbatches require embed_fn")
        act_shape, act_dtype = microbatches.shape[1:], microbatches.dtype
    else:
        act_aval = jax.eval_shape(
            lambda sh, mb: embed_fn(sh, mb), shared_params, mb_at(0))
        act_shape, act_dtype = act_aval.shape, act_aval.dtype

    def first_stage_input(shared, mb):
        if embed_fn is not None:
            return embed_fn(shared, mb).astype(act_dtype)
        return mb.astype(act_dtype)

    def stage_and_loss(p, shared, xb, mb, m, c):
        """Uniform composite for chunk ``c``: global stage 0 re-derives its
        input from the microbatch (so embed params are differentiated),
        other stages use the saved input; the loss head runs only on the
        last local chunk (static) and is seeded only on the last device.
        ``mb``/``xb`` must already be chained into the tick's collective
        order (see the barriers in ``tick``)."""
        if c == 0:
            x_in = jnp.where(rank == 0, first_stage_input(shared, mb), xb)
        else:
            x_in = xb
        y = f(p, x_in, c * S + rank)
        if c == V - 1:
            l = loss_fn(y, m) if shared_params is None \
                else loss_fn(shared, y, m)
        else:
            l = jnp.zeros((), jnp.float32)
        return y.astype(act_dtype), l

    f32 = jnp.float32

    def tick(carry, t):
        act_bufs, cot_bufs, saved, acc_g, acc_sg, loss_sum = carry
        # collective-ordering note: the forward rotation, each chunk's
        # stage apply / vjp psums, and the backward rotation are mutually
        # data-independent, and XLA's CPU thunk runtime may run
        # independent collectives concurrently per device — with devices
        # arriving in different orders the rendezvous can cross-match and
        # hit the 40s abort. optimization_barriers thread every chunk's
        # work into one global order. (On TPU the static schedule makes
        # them no-ops.)
        chain = None

        # ---- forward sub-tick: one microbatch enters each global stage
        outs = []
        for c in range(V):
            m_f = t - (c * S + rank)
            x = jax.lax.index_in_dim(act_bufs, c, 0, keepdims=False)
            if chain is not None:
                x, _ = jax.lax.optimization_barrier((x, chain))
            if c == 0:
                # the embed's collectives depend only on loop-invariants;
                # chain the microbatch slice behind the carried activation
                mb_f, x = jax.lax.optimization_barrier((mb_at(m_f), x))
                x = jnp.where(rank == 0,
                              first_stage_input(shared_params, mb_f), x)
            y = f(chunk_params(c), x, c * S + rank)
            saved = (saved[:c]
                     + (saved[c].at[jnp.mod(m_f, B[c])].set(x),)
                     + saved[c + 1:])
            outs.append(y.astype(act_dtype))
            chain = outs[-1]
        received = rotate_forward(jnp.stack(outs))
        new_act = [received[0]]
        for c in range(1, V):
            # wrap: device 0's chunk c consumes last device's chunk c-1
            new_act.append(jnp.where(rank == 0, received[c - 1],
                                     received[c]))
        act_next = jnp.stack(new_act)
        chain, saved = jax.lax.optimization_barrier((act_next, saved))

        # ---- backward sub-tick: one microbatch leaves each global stage
        dxs = []
        for c in range(V):
            g = c * S + rank
            m_b = t - 2 * L + 1 + g
            valid_b = jnp.logical_and(m_b >= 0, m_b < M)
            xb = saved[c][jnp.mod(m_b, B[c])]
            xb, _ = jax.lax.optimization_barrier((xb, chain))
            xb, mb_b = jax.lax.optimization_barrier((xb, mb_at(m_b)))
            (y_b, l_b), vjp_fn = jax.vjp(
                lambda p, sh, x: stage_and_loss(p, sh, x, mb_b, m_b, c),
                chunk_params(c), shared_params, xb)
            dy = jax.lax.index_in_dim(cot_bufs, c, 0, keepdims=False)
            if c == V - 1:
                # global stage L-1 seeds from the loss, not the rotation
                dy = jnp.where(rank == S - 1, jnp.zeros_like(dy), dy)
                dl = jnp.where(
                    jnp.logical_and(rank == S - 1, valid_b),
                    jnp.asarray(grad_scale, f32) / M, jnp.asarray(0.0, f32))
                loss_sum = loss_sum + jnp.where(
                    jnp.logical_and(rank == S - 1, valid_b),
                    l_b.astype(f32), 0.0)
            else:
                dl = jnp.asarray(0.0, f32)
            # seed types must match the primal outputs' varying axes
            # exactly (e.g. data-varying under the DDP pattern)
            dy = cast_to_vma(dy.astype(y_b.dtype), _leaf_vma(y_b))
            dl = cast_to_vma(dl.astype(l_b.dtype), _leaf_vma(l_b))
            dparams, dshared, dxb = vjp_fn((dy, dl))
            acc_g = jax.tree_util.tree_map(
                lambda a, dg: a.at[c].add(dg.astype(f32)), acc_g, dparams)
            if shared_params is not None:
                acc_sg = jax.tree_util.tree_map(
                    lambda a, dg: a + dg.astype(f32), acc_sg, dshared)
            dxs.append(dxb.astype(act_dtype))
            chain = dxs[-1]
        recv_d = rotate_backward(jnp.stack(dxs))
        new_cot = []
        for c in range(V):
            if c < V - 1:
                # wrap mirror: device S-1's chunk c consumes device 0's
                # chunk c+1 (global stage g+1 = (c+1)*S)
                new_cot.append(jnp.where(rank == S - 1, recv_d[c + 1],
                                         recv_d[c]))
            else:
                new_cot.append(recv_d[c])  # rank S-1 re-seeded above
        cot_next = jnp.stack(new_cot)
        # close the chain: the next tick's forward rotation must not start
        # until this tick's backward rotation is issued
        act_next, cot_next = jax.lax.optimization_barrier(
            (act_next, cot_next))

        return (act_next, cot_next, saved, acc_g, acc_sg, loss_sum), None

    zeros_g = jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), f32), p_stack)
    zeros_sg = (None if shared_params is None else jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), f32), shared_params))
    init = (jnp.zeros((V,) + act_shape, act_dtype),
            jnp.zeros((V,) + act_shape, act_dtype),
            tuple(jnp.zeros((B[c],) + act_shape, act_dtype)
                  for c in range(V)),
            zeros_g, zeros_sg, jnp.asarray(0.0, f32))

    # fixed-point each carry leaf's varying-axes set (the stage body may
    # add axes, e.g. TP makes activations tensor-varying, while LN grad
    # accumulators must stay tensor-replicated)
    vma_tree = _fixed_point_vma(tick, init, jnp.asarray(0))

    def tick_stable(carry, t):
        new_carry, _ = tick(carry, t)
        return jax.tree_util.tree_map(cast_to_vma, new_carry, vma_tree), None

    (
        _, _, _, acc_g, acc_sg, loss_sum
    ), _ = jax.lax.scan(
        tick_stable, jax.tree_util.tree_map(cast_to_vma, init, vma_tree),
        jnp.arange(T))

    mean_loss = jax.lax.psum(
        jnp.where(rank == S - 1, loss_sum / M, 0.0), PIPE_AXIS)
    inv_scale = 1.0 / jnp.asarray(grad_scale, f32)
    stage_grads = jax.tree_util.tree_map(lambda g: g * inv_scale, acc_g)
    if not stacked:
        stage_grads = jax.tree_util.tree_map(lambda g: g[0], stage_grads)
    if shared_params is None:
        return mean_loss, stage_grads

    # shared_params enter pipe-INVARIANT, so the vjp's type reconciliation
    # already psums their per-tick cotangent across stages — every rank
    # accumulates the replicated total. If a carry cast left the
    # accumulator pipe-varying-TYPED, psum/S restores the invariant type
    # without double counting the S identical copies.
    #
    # Pre-VMA jax has NO reconciliation (shard_map_unchecked, no
    # replication rewrite): each rank holds only its own DISJOINT partial
    # cotangent — the embedding's on global stage 0, the tied head's on
    # the last stage, zeros between — so the partials must be summed
    # explicitly. Without this every pipe rank Adam-steps the nominally
    # replicated shared params with a DIFFERENT gradient and the replicas
    # silently drift apart ~2*lr/step (caught by the elastic
    # bitwise-resume legs: a checkpoint restore collapses replicas to
    # shard 0, changing the training trajectory).
    def _finalize_shared(g):
        g = g * inv_scale
        if PIPE_AXIS in _leaf_vma(g):
            g = jax.lax.psum(g, PIPE_AXIS) / S
        elif not HAS_VMA:
            g = jax.lax.psum(g, PIPE_AXIS)
        return g

    shared_grads = jax.tree_util.tree_map(_finalize_shared, acc_sg)
    return mean_loss, (stage_grads, shared_grads)


# ---------------------------------------------------------------------------
# pipelined schedules (loss + grads)
# ---------------------------------------------------------------------------

def _pipelined_fwd_bwd(stage_fn, loss_fn, stage_params, microbatches,
                       num_chunks, forward_only, remat, grad_scale,
                       shared_params=None, embed_fn=None):
    """Shared driver: loss = mean over microbatches of
    ``loss_fn(final_stage_output, m)``, computed at the last stage and
    psum-shared over ``pipe``; grads via AD through the scan.

    ``shared_params`` (optional) are pipe-replicated parameters consumed by
    ``embed_fn(shared, microbatch)`` on global stage 0 and by
    ``loss_fn(shared, y, m)`` on the last stage — the pipelined embedding +
    tied output head. Because shared params enter ``shard_map`` replicated
    (device-invariant type), AD itself inserts the cross-stage psum that
    makes their cotangent invariant again — the reference's embedding-group
    allreduce (first + last stage contributions,
    ``reference:apex/transformer/parallel_state.py:215-247``,
    ``schedules/common.py:29-148`` pre/post_process) falls out of the VMA
    type system rather than being an explicit collective here (verified
    against a single-device reference in
    ``tests/test_transformer_parallel.py::test_gpt_pipelined_embedding_and_tied_head``).
    """
    if embed_fn is not None and shared_params is None:
        raise ValueError(
            "embed_fn takes (shared_params, microbatch); pass the embedding "
            "parameters via shared_params so they are differentiated")
    m = jax.tree_util.tree_leaves(microbatches)[0].shape[0]

    def total_loss(params):
        # pipelined_apply already broadcasts the final stage's outputs over
        # the pipe axis, so the loss is replicated by construction
        if shared_params is None:
            outs = pipelined_apply(stage_fn, params, microbatches,
                                   num_chunks=num_chunks, remat=remat)
            losses = jax.vmap(loss_fn)(outs, jnp.arange(m))
        else:
            stages, shared = params
            ef = (lambda mb: embed_fn(shared, mb)) \
                if embed_fn is not None else None
            outs = pipelined_apply(stage_fn, stages, microbatches,
                                   num_chunks=num_chunks, remat=remat,
                                   embed_fn=ef)
            losses = jax.vmap(lambda y, i: loss_fn(shared, y, i))(
                outs, jnp.arange(m))
            # the head runs "for real" only on the last stage (the broadcast
            # outs make every rank compute an identical copy): masking the
            # loss here (a) matches the reference's loss-on-last-stage and
            # (b) routes the head's shared-param cotangent to rank S-1 only,
            # so the psum below counts it exactly once
            rank = jax.lax.axis_index(PIPE_AXIS)
            S = _axis_size(PIPE_AXIS)
            total = jnp.mean(losses)
            return jax.lax.psum(
                jnp.where(rank == S - 1, total, jnp.zeros_like(total)),
                PIPE_AXIS)
        return jnp.mean(losses)

    diff_params = stage_params if shared_params is None \
        else (stage_params, shared_params)
    if forward_only:
        return total_loss(diff_params), None
    loss, grads = jax.value_and_grad(
        lambda p: total_loss(p) * grad_scale)(diff_params)
    grads = jax.tree_util.tree_map(
        lambda g: (g / grad_scale).astype(jnp.float32), grads)
    if shared_params is not None and not HAS_VMA:
        # pre-VMA jax: AD inserts no cross-stage psum for the replicated
        # shared params (no replication rewrite under
        # shard_map_unchecked), so each rank's shared grads are its own
        # disjoint partial (embed on stage 0, masked head on the last) —
        # sum them explicitly, same contract as _finalize_shared above
        sg, shg = grads
        shg = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, PIPE_AXIS), shg)
        grads = (sg, shg)
    return loss / grad_scale, grads


def forward_backward_pipelining_without_interleaving(
    forward_step_func: Callable,
    batch: jnp.ndarray,
    params: Any,
    *,
    loss_fn: Callable,
    forward_only: bool = False,
    remat: Any = False,
    grad_scale: Any = 1.0,
    shared_params: Any = None,
    embed_fn: Optional[Callable] = None,
    memory_efficient: bool = True,
):
    """Pipelined schedule matching 1F1B
    (``fwd_bwd_pipelining_without_interleaving.py:155-345``) in output AND —
    by default — in its O(pp) activation-memory bound (see
    :func:`_onef1b_fwd_bwd`).

    ``forward_step_func(stage_params, x, stage_index) -> y`` is the uniform
    stage body; ``loss_fn(final_output, microbatch_index) -> scalar``.
    ``params`` leaves must NOT carry a chunk axis (single chunk per stage).
    Returns ``(mean_loss, grads)`` — grads for this device's stage params.

    With ``shared_params``/``embed_fn`` (pipelined embedding + tied head, see
    ``_pipelined_fwd_bwd``), ``loss_fn(shared, y, m)`` and grads are
    ``(stage_grads, shared_grads)`` with shared_grads psummed over ``pipe``.

    ``memory_efficient=False`` selects the AD-through-the-tick-scan driver
    (O(M + pp) per-tick residuals; cheaper per step at small M since the
    forward is not recomputed).

    ``remat`` accepts the legacy bool (True == "full"), a mode string, or
    a :class:`~apex_tpu.remat.RematPolicy` — "selective"/"offload" keep
    the registry-tagged activations the stage emits resident/offloaded
    instead of recomputing everything (see ``apex_tpu/remat.py``).
    """
    if memory_efficient and not forward_only:
        return _onef1b_fwd_bwd(
            forward_step_func, loss_fn, params, batch, remat, grad_scale,
            shared_params=shared_params, embed_fn=embed_fn)
    chunked = jax.tree_util.tree_map(lambda p: p[None], params)
    loss, grads = _pipelined_fwd_bwd(
        forward_step_func, loss_fn, chunked, batch, 1, forward_only, remat,
        grad_scale, shared_params=shared_params, embed_fn=embed_fn)
    if grads is not None:
        stage_grads = grads[0] if shared_params is not None else grads
        stage_grads = jax.tree_util.tree_map(lambda g: g[0], stage_grads)
        grads = (stage_grads, grads[1]) if shared_params is not None \
            else stage_grads
    return loss, grads


def forward_backward_pipelining_with_interleaving(
    forward_step_func: Callable,
    batch: jnp.ndarray,
    params: Any,
    *,
    loss_fn: Callable,
    num_model_chunks: int,
    forward_only: bool = False,
    remat: Any = False,
    grad_scale: Any = 1.0,
    shared_params: Any = None,
    embed_fn: Optional[Callable] = None,
    memory_efficient: bool = True,
):
    """Interleaved virtual-pipeline schedule
    (``fwd_bwd_pipelining_with_interleaving.py:25-375``): each device holds
    ``num_model_chunks`` stage chunks, Megatron layout (chunk c on device d =
    global stage ``c*S+d``). ``params`` leaves carry a leading
    ``(num_model_chunks, ...)`` axis.

    ``memory_efficient=True`` (default) runs the vjp-driven 1F1B driver
    with O(L)-in-flight activation memory (see :func:`_onef1b_fwd_bwd`);
    ``False`` selects the AD-through-the-tick-scan driver."""
    if memory_efficient and not forward_only:
        return _onef1b_fwd_bwd(
            forward_step_func, loss_fn, params, batch, remat, grad_scale,
            shared_params=shared_params, embed_fn=embed_fn,
            num_chunks=num_model_chunks, chunked_params=True)
    return _pipelined_fwd_bwd(
        forward_step_func, loss_fn, params, batch, num_model_chunks,
        forward_only, remat, grad_scale, shared_params=shared_params,
        embed_fn=embed_fn)


def get_forward_backward_func(virtual_pipeline_model_parallel_size: Optional[int],
                              pipeline_model_parallel_size: int):
    """Dispatch (``schedules/__init__.py:22``)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining
