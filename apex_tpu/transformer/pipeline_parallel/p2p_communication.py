"""Stage-to-stage activation/grad exchange.

Reference: ``reference:apex/transformer/pipeline_parallel/p2p_communication.py``
— batched NCCL ``isend/irecv`` pairs (:29-67) behind 8 public ops
(:187-408), with an optional scatter-gather transport optimization that
splits tensors 1/tp_size during transit (:120-123,155-182) and a full
``cuda.synchronize`` after each batch (:166).

TPU redesign: under SPMD every stage executes the same program, so a
send/recv pair is one ``ppermute`` rotation over the ``pipe`` axis — XLA
lowers it to ICI neighbor DMA with no host sync. The scatter-gather
transport trick is subsumed by sharding the activation over ``tensor`` in
its sharding spec (GSPMD keeps it split in transit for free). The 8-op
surface collapses to two rotations; the reference names are kept as thin
aliases so schedule code reads the same.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import PIPE_AXIS
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = [
    "rotate_forward", "rotate_backward",
    "send_forward_recv_forward", "send_backward_recv_backward",
]


def _perm_next(pp: int):
    return [(i, (i + 1) % pp) for i in range(pp)]


def _perm_prev(pp: int):
    return [(i, (i - 1) % pp) for i in range(pp)]


def rotate_forward(x: jnp.ndarray) -> jnp.ndarray:
    """Every stage sends ``x`` to the next stage and receives from the
    previous (wrapping; the wrap value is ignored by stage 0's select in the
    schedules). ``send_forward`` + ``recv_forward`` of the reference."""
    pp = _axis_size(PIPE_AXIS)
    return jax.lax.ppermute(x, PIPE_AXIS, _perm_next(pp))


def rotate_backward(g: jnp.ndarray) -> jnp.ndarray:
    """``send_backward`` + ``recv_backward``: grads flow to the previous
    stage."""
    pp = _axis_size(PIPE_AXIS)
    return jax.lax.ppermute(g, PIPE_AXIS, _perm_prev(pp))


# reference-named aliases (p2p_communication.py:187-408)
send_forward_recv_forward = rotate_forward
send_backward_recv_backward = rotate_backward
