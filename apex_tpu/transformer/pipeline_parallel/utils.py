"""Pipeline-parallel utilities.

Reference: ``reference:apex/transformer/pipeline_parallel/utils.py`` —
microbatch-calculator global (:58-121), batch slicing (:122-140), params l2
norm across model-parallel ranks (:213-239), DP loss averaging (:242-250),
memory report (:253-263), ltor masks/position ids (:303+).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import DATA_AXIS
from apex_tpu.transformer.pipeline_parallel.microbatches import (
    build_num_microbatches_calculator)

__all__ = [
    "setup_microbatch_calculator", "get_num_microbatches",
    "get_current_global_batch_size", "update_num_microbatches",
    "get_micro_batch_size", "get_kth_microbatch", "listify_model",
    "average_losses_across_data_parallel_group", "report_memory",
    "get_ltor_masks_and_position_ids", "calc_params_l2_norm",
    "unwrap_model",
]

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_AUTORESUME = None


def setup_microbatch_calculator(rank: int, rampup_batch_size: Optional[List[int]],
                                global_batch_size: int, micro_batch_size: int,
                                data_parallel_size: int) -> None:
    """:58-90 — installs the process-global calculator once."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is not None:
        raise RuntimeError("num microbatches calculator is already initialized.")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _calc():
    if _GLOBAL_NUM_MICROBATCHES_CALCULATOR is None:
        raise RuntimeError("microbatch calculator is not initialized")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches() -> int:
    return _calc().get()


def get_current_global_batch_size() -> int:
    return _calc().get_current_global_batch_size()


def get_micro_batch_size() -> int:
    return _calc().micro_batch_size


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _calc().update(consumed_samples, consistency_check)


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_kth_microbatch(batch: Any, k) -> Any:
    """:122-140 — slice microbatch k out of leaves shaped
    ``(num_micro * micro_bs, ...)``."""
    mbs = get_micro_batch_size()
    return jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(x, k * mbs, mbs, axis=0), batch)


def listify_model(model: Any) -> List[Any]:
    return model if isinstance(model, list) else [model]


def unwrap_model(model, module_instances=()):
    """API-compat: no wrapper modules exist here, returns input."""
    return model


def average_losses_across_data_parallel_group(losses: Sequence[jnp.ndarray]
                                              ) -> jnp.ndarray:
    """:242-250 — pmean of the stacked losses over the data axis (call inside
    shard_map)."""
    stacked = jnp.stack([jnp.asarray(l, jnp.float32) for l in losses])
    return jax.lax.pmean(stacked, DATA_AXIS)


def report_memory(name: str) -> str:
    """:253-263 — device memory report (TPU: per-device allocation stats)."""
    lines = [f"[{name}] memory (MB)"]
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
            used = stats.get("bytes_in_use", 0) / 2**20
            peak = stats.get("peak_bytes_in_use", 0) / 2**20
            lines.append(f"  {d}: in_use {used:.1f} | peak {peak:.1f}")
        except Exception:
            lines.append(f"  {d}: memory_stats unavailable")
    report = "\n".join(lines)
    print(report, flush=True)
    return report


def get_ltor_masks_and_position_ids(
    data: jnp.ndarray,
    eod_token: int,
    reset_position_ids: bool = False,
    reset_attention_mask: bool = False,
    eod_mask_loss: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """:303+ — causal mask, loss mask, position ids for a ``(b, s)`` batch.

    The document-reset variants (splitting attention at EOD tokens) are
    expressed with cumulative EOD counts instead of the reference's Python
    loop over micro-batches — same results, traceable.
    Returns ``attention_mask (b,1,s,s) bool (True = masked)``,
    ``loss_mask (b,s) f32``, ``position_ids (b,s) i32``.
    """
    b, s = data.shape
    row = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
    causal_keep = col <= row  # lower triangular
    keep = jnp.broadcast_to(causal_keep, (b, s, s))

    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)

    position_ids = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))

    if reset_position_ids or reset_attention_mask:
        # document id = number of EODs strictly before this position
        is_eod = (data == eod_token).astype(jnp.int32)
        doc_id = jnp.cumsum(is_eod, axis=1) - is_eod  # eod belongs to its doc
        if reset_attention_mask:
            same_doc = doc_id[:, :, None] == doc_id[:, None, :]
            keep = keep & same_doc
        if reset_position_ids:
            # position within document: index - index of doc start
            idx = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
            # start index of this position's doc = first index with same doc_id
            doc_start = jax.vmap(
                lambda d: jnp.min(
                    jnp.where(d[None, :] == d[:, None],
                              jnp.arange(s, dtype=jnp.int32)[None, :], s),
                    axis=1))(doc_id)
            position_ids = idx - doc_start

    attention_mask = ~keep[:, None, :, :]  # True = masked
    return attention_mask, loss_mask, position_ids


def calc_params_l2_norm(params: Any, axis_names: Sequence[str] = ("tensor",)
                        ) -> jnp.ndarray:
    """:213-239 — L2 norm of all params across model-parallel shards (call
    inside shard_map; psum over the model axes of the local square-sums).
    The reference filters TP-duplicated params; here params are stored
    sharded, so every element is counted exactly once."""
    sq = sum(jnp.sum(jnp.asarray(p).astype(jnp.float32) ** 2)
             for p in jax.tree_util.tree_leaves(params))
    for ax in axis_names:
        sq = jax.lax.psum(sq, ax)
    return jnp.sqrt(sq)
