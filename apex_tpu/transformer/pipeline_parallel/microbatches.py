"""Microbatch calculators (``reference:apex/transformer/microbatches.py``).

Host-side scheduling state — device-independent, so the semantics carry over
directly: ``ConstantNumMicroBatches`` (:93) and
``RampupBatchsizeNumMicroBatches`` (:112, global batch ramped from
``start_batch_size`` by ``batch_size_increment`` every
``rampup_samples/num_increments`` consumed samples).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import List, Optional, Union

__all__ = ["build_num_microbatches_calculator", "NumMicroBatchesCalculator",
           "ConstantNumMicroBatches", "RampupBatchsizeNumMicroBatches"]


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[List[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> "NumMicroBatchesCalculator":
    """``reference:apex/transformer/microbatches.py:34-75``."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(global_batch_size, micro_batch_size,
                                       data_parallel_size)
    if len(rampup_batch_size) != 3:
        raise ValueError("expected the following format: --rampup-batch-size "
                         "<start batch size> <batch size increment> "
                         "<ramp-up samples>")
    start, increment, samples = (int(rampup_batch_size[0]),
                                 int(rampup_batch_size[1]),
                                 int(rampup_batch_size[2]))
    return RampupBatchsizeNumMicroBatches(
        start, increment, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


class NumMicroBatchesCalculator(ABC):
    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples, consistency_check) -> None:
        ...

    # -- checkpointing (host_state sidecar of apex_tpu.checkpoint) --------
    def state_dict(self) -> dict:
        return {"num_micro_batches": self.num_micro_batches,
                "current_global_batch_size": self.current_global_batch_size}

    def load_state_dict(self, state: dict) -> None:
        self.num_micro_batches = state["num_micro_batches"]
        self.current_global_batch_size = state["current_global_batch_size"]


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        mb_times_dp = micro_batch_size * data_parallel_size
        assert global_batch_size % mb_times_dp == 0, (
            f"global batch size ({global_batch_size}) is not divisible by "
            f"micro batch size ({micro_batch_size}) times data parallel size "
            f"({data_parallel_size})")
        self.num_micro_batches = global_batch_size // mb_times_dp
        assert self.num_micro_batches >= 1
        self.current_global_batch_size = global_batch_size
        self.micro_batch_size = micro_batch_size

    def update(self, consumed_samples, consistency_check) -> None:
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        assert self.micro_batch_times_data_parallel_size > 0
        assert start_batch_size > 0
        self.start_batch_size = start_batch_size
        assert global_batch_size > 0
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        assert diff >= 0
        assert batch_size_increment > 0
        self.batch_size_increment = batch_size_increment
        assert diff % batch_size_increment == 0
        num_increments = diff // batch_size_increment
        self.ramup_samples = ramup_samples
        assert self.ramup_samples >= 0
        self.rampup_samples_per_increment = self.ramup_samples / num_increments
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool) -> None:
        if consumed_samples > self.ramup_samples:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples / self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            assert self.current_global_batch_size <= self.global_batch_size
        if consistency_check:
            assert (self.current_global_batch_size
                    % self.micro_batch_times_data_parallel_size == 0), (
                "current global batch size ({}) is not divisible by "
                "micro-batch-size ({}) times data parallel size ({})".format(
                    self.current_global_batch_size, self.micro_batch_size,
                    self.data_parallel_size))
        self.num_micro_batches = (
            self.current_global_batch_size
            // self.micro_batch_times_data_parallel_size)
