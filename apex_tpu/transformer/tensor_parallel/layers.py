"""Tensor-parallel sharded layers.

Reference: ``reference:apex/transformer/tensor_parallel/layers.py`` —
``VocabParallelEmbedding`` (:154-256, vocab-range mask + allreduce),
``ColumnParallelLinear`` (:377-538), ``RowParallelLinear`` (:541-663), and
the fused-wgrad autograd functions
``LinearWithGradAccumulationAndAsyncAllreduce*`` (:259-374) whose backward
overlaps the input-grad allreduce with the weight-grad GEMM.

TPU redesign: layers are param factories whose ``__call__`` runs inside
``shard_map`` with *per-device weight shards* (Column: ``(out/tp, in)``,
Row: ``(out, in/tp)``, Embedding: ``(vocab/tp, h)``). The collectives come
from :mod:`.mappings`; for *independent* collectives the async-allreduce/
wgrad overlap of :285-304 needs no code — XLA's latency-hiding scheduler
overlaps the backward psum with the wgrad dot, which is exactly what the
hand-rolled ``handle = allreduce(async_op=True) ... handle.wait()``
achieved. The sequence-parallel hot path is the exception: its
all-gather→GEMM / GEMM→reduce-scatter pairs are *dependent*, which no
scheduler can overlap without decomposition — ``tp_comm_overlap=True``
swaps them for the ring-decomposed primitives of
:mod:`.collective_matmul` (fwd and bwd overlap, same numerics). The
``gradient_accumulation_fusion`` flag (accumulate wgrad into a persistent
fp32 ``main_grad``, :493-508) is a donation/accumulation concern of the
caller's optimizer loop here, so both flags are accepted and documented
no-ops.

Init matches ``_initialize_affine_weight_*`` (:56-151): the master weight is
materialized at fp32 on host, split along the sharded dim, and each rank
keeps its shard — so TP=N and TP=1 runs are bit-comparable (the property the
reference tests rely on, ``tests/L0/run_transformer/test_layers.py``).
"""

from __future__ import annotations

import contextlib
import math
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.mappings import (
    _vary,
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region,
)
from apex_tpu.transformer.utils import VocabUtility, divide

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "init_method_normal"]


def init_method_normal(sigma: float) -> Callable:
    def init_(key, shape, dtype=jnp.float32):
        return sigma * jax.random.normal(key, shape, dtype)
    return init_


def _default_tp_world_size() -> int:
    """TP size from the installed mesh, or 1 when uninitialized (single-chip
    use without initialize_model_parallel, like torch layers without dist)."""
    if parallel_state.model_parallel_is_initialized():
        return parallel_state.get_tensor_model_parallel_world_size()
    return 1


def _dense(x, w_t):
    """x @ w^T with fp32 MXU accumulation (w stored (out, in) like torch)."""
    return jax.lax.dot_general(x, w_t, (((x.ndim - 1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


@jax.custom_vjp
def _scale_grad(x: jnp.ndarray, scale: float) -> jnp.ndarray:
    """Identity forward; multiplies the cotangent by ``scale`` backward."""
    return x


def _scale_grad_fwd(x, scale):
    return x, scale


def _scale_grad_bwd(scale, g):
    return (g * scale, None)


_scale_grad.defvjp(_scale_grad_fwd, _scale_grad_bwd)


def _local_shard(stacked: jnp.ndarray, world_size: int) -> jnp.ndarray:
    """Resolve this rank's shard of a ``(tp, ...)``-stacked param.

    The intended layout shards axis 0 over the ``tensor`` mesh axis
    (``shard_map`` in_specs ``P('tensor', ...)``), so the local view has
    leading dim 1 and each device holds only its shard — true TP memory
    scaling. A replicated full stack (leading dim == tp) also works, via a
    traced dynamic index, for single-device debugging.
    """
    if stacked.shape[0] == 1:
        return stacked[0]
    if stacked.shape[0] != world_size:
        raise ValueError(
            f"stacked param leading dim {stacked.shape[0]} is neither 1 "
            f"(sharded view) nor tp={world_size} (replicated)")
    rank = jax.lax.axis_index(TENSOR_AXIS)
    return jax.lax.dynamic_index_in_dim(stacked, rank, 0, keepdims=False)


class ColumnParallelLinear:
    """Y = XA + b with A sharded along out-features (:377-538).

    ``__call__(params, x)`` returns ``(out, bias_out)`` like the reference
    forward (bias separate when ``skip_bias_add``). params hold ALL shards
    stacked on axis 0 — shape ``(tp, out/tp, in)`` — and ``__call__`` picks
    its shard by TP rank, so the same pytree works at any point of the mesh
    and checkpoints are layout-independent.
    """

    def __init__(self, input_size: int, output_size: int, bias: bool = True,
                 gather_output: bool = True,
                 init_method: Optional[Callable] = None,
                 skip_bias_add: bool = False, params_dtype=jnp.float32,
                 world_size: Optional[int] = None,
                 no_async_tensor_model_parallel_allreduce: bool = False,
                 gradient_accumulation_fusion: bool = False,
                 sequence_parallel: bool = False, seq_axis: int = 1,
                 tp_comm_overlap: bool = False):
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.gather_output = gather_output
        self.skip_bias_add = skip_bias_add
        self.params_dtype = params_dtype
        self.init_method = init_method or init_method_normal(0.02)
        self.world_size = (world_size if world_size is not None
                           else _default_tp_world_size())
        self.output_size_per_partition = divide(output_size, self.world_size)
        # Megatron-LM sequence parallelism: the input arrives as a sequence
        # shard; forward all-gathers it (AD transpose = reduce-scatter of
        # the input cotangents, the SP backward)
        self.sequence_parallel = sequence_parallel
        self.seq_axis = seq_axis
        # ring-decompose the SP gather under the GEMM (collective_matmul)
        if tp_comm_overlap and not sequence_parallel:
            raise ValueError(
                "tp_comm_overlap requires sequence_parallel=True: only the "
                "SP gather->GEMM pair is a dependent collective")
        self.tp_comm_overlap = tp_comm_overlap

    def init(self, key: jax.Array) -> dict:
        # master weight then split along out dim (:56-151)
        master = self.init_method(key, (self.output_size, self.input_size))
        w = master.reshape(self.world_size, self.output_size_per_partition,
                           self.input_size).astype(self.params_dtype)
        p = {"weight": w}
        if self.use_bias:
            p["bias"] = jnp.zeros(
                (self.world_size, self.output_size_per_partition),
                self.params_dtype)
        return p

    def __call__(self, params: dict, x: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        # pyprof attribution region: the GEMM *and* its dependent TP
        # collectives in one bucket — the unit the overlap-exposure
        # accounting prices (scripts/check_annotations.py contract).
        # tp=1 stays scope-free so single-chip programs attribute to
        # the enclosing model phase (gpt_attention/gpt_mlp) instead.
        scope = (jax.named_scope("tp_column_linear")
                 if self.world_size > 1 else contextlib.nullcontext())
        with scope:
            return self._forward(params, x)

    def _forward(self, params: dict, x: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        w = _local_shard(params["weight"], self.world_size)
        if (self.world_size > 1 and self.sequence_parallel
                and self.tp_comm_overlap):
            # dependent-collective overlap: the sequence all-gather is
            # ring-decomposed under the partial GEMMs (and its backward
            # reduce-scatter under the dW GEMM) — same math, same fp32
            # MXU accumulation, tp-1 ppermutes instead of one fused gather
            from apex_tpu.transformer.tensor_parallel.collective_matmul \
                import all_gather_matmul
            out = all_gather_matmul(x, w, TENSOR_AXIS,
                                    self.seq_axis).astype(x.dtype)
        else:
            if self.world_size > 1:
                if self.sequence_parallel:
                    from apex_tpu.transformer.context_parallel import (
                        gather_from_sequence_parallel_region)
                    x = gather_from_sequence_parallel_region(
                        x, TENSOR_AXIS, self.seq_axis, invariant=True)
                else:
                    x = copy_to_tensor_model_parallel_region(x)
            out = _dense(x, w).astype(x.dtype)
        b = None
        if self.use_bias:
            b = _local_shard(params["bias"], self.world_size)
            if not self.skip_bias_add:
                out = out + b.astype(out.dtype)
                b = None
        if self.gather_output and self.world_size > 1:
            out = gather_from_tensor_model_parallel_region(out)
            if b is not None:
                b = gather_from_tensor_model_parallel_region(b)
        elif self.world_size == 1 and self.gather_output:
            # size-1 axis: restore the invariant type the gather would
            # (a P('tensor')-spec'd weight leaves these tensor-varying)
            from apex_tpu.utils.vma import restore_invariant
            out = restore_invariant(out, TENSOR_AXIS)
            if b is not None:
                b = restore_invariant(b, TENSOR_AXIS)
        return out, b


class RowParallelLinear:
    """Y = XA + b with A sharded along in-features (:541-663); forward ends
    in an allreduce; bias added after the reduce (once)."""

    def __init__(self, input_size: int, output_size: int, bias: bool = True,
                 input_is_parallel: bool = False,
                 init_method: Optional[Callable] = None,
                 skip_bias_add: bool = False, params_dtype=jnp.float32,
                 world_size: Optional[int] = None,
                 sequence_parallel: bool = False, seq_axis: int = 1,
                 tp_comm_overlap: bool = False):
        self.input_size = input_size
        self.output_size = output_size
        self.use_bias = bias
        self.input_is_parallel = input_is_parallel
        self.skip_bias_add = skip_bias_add
        self.params_dtype = params_dtype
        self.init_method = init_method or init_method_normal(0.02)
        self.world_size = (world_size if world_size is not None
                           else _default_tp_world_size())
        self.input_size_per_partition = divide(input_size, self.world_size)
        self.sequence_parallel = sequence_parallel
        self.seq_axis = seq_axis
        # ring-decompose the SP reduce-scatter under the GEMM
        if tp_comm_overlap and not sequence_parallel:
            raise ValueError(
                "tp_comm_overlap requires sequence_parallel=True: only the "
                "SP GEMM->reduce-scatter pair is a dependent collective")
        self.tp_comm_overlap = tp_comm_overlap

    def init(self, key: jax.Array) -> dict:
        master = self.init_method(key, (self.output_size, self.input_size))
        # split along in dim -> (tp, out, in/tp)
        w = master.reshape(self.output_size, self.world_size,
                           self.input_size_per_partition)
        w = jnp.transpose(w, (1, 0, 2)).astype(self.params_dtype)
        p = {"weight": w}
        if self.use_bias:
            # conceptually replicated (:603-612); stored as tp identical
            # copies on axis 0 so one P('tensor') spec covers every leaf
            p["bias"] = jnp.zeros((self.world_size, self.output_size),
                                  self.params_dtype)
        return p

    def __call__(self, params: dict, x: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        # pyprof attribution region, tp>1 only — see ColumnParallelLinear
        scope = (jax.named_scope("tp_row_linear")
                 if self.world_size > 1 else contextlib.nullcontext())
        with scope:
            return self._forward(params, x)

    def _forward(self, params: dict, x: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
        w = _local_shard(params["weight"], self.world_size)
        if not self.input_is_parallel and self.world_size > 1:
            x = scatter_to_tensor_model_parallel_region(x)
        b = _local_shard(params["bias"], self.world_size) if self.use_bias \
            else None
        fold = None
        if b is not None and not self.skip_bias_add:
            # Forward folds b/tp into the pre-psum partial so the psum adds
            # the bias exactly once without up-casting its (replicated)
            # output back to varying — an actual post-reduce add would make
            # AD psum the whole cotangent, inflating the *weight* grads by
            # tp. The naked fold would hand each bias copy cotangent g/tp
            # (starving norm-sensitive optimizers like LARC/SGD vs a TP=1
            # run), so _scale_grad restores the reference semantics
            # (:649-657, bias added after reduce → full grad per copy).
            b_fold = _scale_grad(b.astype(jnp.float32), self.world_size)
            fold = b_fold / self.world_size
            b = None
        if (self.world_size > 1 and self.sequence_parallel
                and self.tp_comm_overlap):
            # dependent-collective overlap: the GEMM is chunked along the
            # sequence and each partial's transfer (ring reduce-scatter)
            # rides under the next chunk's GEMM; the bias fold becomes the
            # primitive's partial_add (same once-per-psum semantics)
            from apex_tpu.transformer.tensor_parallel.collective_matmul \
                import matmul_reduce_scatter
            out = matmul_reduce_scatter(
                x, w, fold, TENSOR_AXIS, self.seq_axis).astype(x.dtype)
            return out, b
        partial = _dense(x, w).astype(x.dtype)
        if fold is not None:
            partial = partial + fold.astype(partial.dtype)
        if self.world_size > 1:
            if self.sequence_parallel:
                # SP: the reduction scatters — each rank keeps its sequence
                # shard of the reduced activations (Megatron-LM SP RowParallel)
                from apex_tpu.transformer.context_parallel import (
                    reduce_scatter_to_sequence_parallel_region)
                out = reduce_scatter_to_sequence_parallel_region(
                    _vary(partial), TENSOR_AXIS, self.seq_axis)
            else:
                out = reduce_from_tensor_model_parallel_region(partial)
        else:
            # a P('tensor')-spec'd weight leaves `partial` typed
            # tensor-varying even on a size-1 axis; restore the invariant
            # type the tp>1 psum would (value identity)
            from apex_tpu.utils.vma import restore_invariant
            out = restore_invariant(partial, TENSOR_AXIS)
            if b is not None:
                b = restore_invariant(b, TENSOR_AXIS)
        return out, b


class VocabParallelEmbedding:
    """Embedding sharded along the vocab dim (:154-256): each rank looks up
    only ids in its range, masks the rest to zero, and the psum reassembles
    full rows."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 init_method: Optional[Callable] = None,
                 params_dtype=jnp.float32, world_size: Optional[int] = None):
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.init_method = init_method or init_method_normal(0.02)
        self.params_dtype = params_dtype
        self.world_size = (world_size if world_size is not None
                           else _default_tp_world_size())
        self.num_embeddings_per_partition = divide(num_embeddings,
                                                   self.world_size)

    def init(self, key: jax.Array) -> dict:
        master = self.init_method(key, (self.num_embeddings,
                                        self.embedding_dim))
        w = master.reshape(self.world_size, self.num_embeddings_per_partition,
                           self.embedding_dim).astype(self.params_dtype)
        return {"weight": w}

    def __call__(self, params: dict, ids: jnp.ndarray) -> jnp.ndarray:
        w = _local_shard(params["weight"], self.world_size)
        if self.world_size == 1:
            # a P('tensor')-spec'd weight is typed tensor-varying even on a
            # size-1 axis; restore the invariant type the tp>1 psum would
            from apex_tpu.utils.vma import restore_invariant
            return jnp.take(restore_invariant(w, TENSOR_AXIS), ids, axis=0)
        per = self.num_embeddings_per_partition
        start = jax.lax.axis_index(TENSOR_AXIS) * per
        # vocab-range mask (:221-239)
        in_range = (ids >= start) & (ids < start + per)
        local_ids = jnp.where(in_range, ids - start, 0)
        rows = jnp.take(w, local_ids, axis=0)
        rows = jnp.where(in_range[..., None], rows, 0)
        return reduce_from_tensor_model_parallel_region(rows)
