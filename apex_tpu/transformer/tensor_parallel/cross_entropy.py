"""Vocab-parallel cross entropy.

Reference: ``reference:apex/transformer/tensor_parallel/cross_entropy.py:23-99``
— with logits sharded along vocab: local max → allreduce(MAX), local
predicted-logit (masked to the owning rank) and local sum-exp → allreduce(SUM),
then ``loss = log(sum_exp) - predicted_logit``; backward scales the local
softmax and subtracts the one-hot on the owning rank only.

Here the three collectives are ``pmax``/``psum`` over the ``tensor`` axis and
the backward falls out of AD with identical communication (the transpose of
psum/pmax touch the same axis).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["vocab_parallel_cross_entropy"]


def vocab_parallel_cross_entropy(vocab_parallel_logits: jnp.ndarray,
                                 target: jnp.ndarray,
                                 label_smoothing: float = 0.0) -> jnp.ndarray:
    """Per-token loss from vocab-sharded logits ``(..., vocab/tp)``.

    ``label_smoothing`` mirrors the reference's smoothing branch (kept 0 in
    the reference tests).
    """
    logits = vocab_parallel_logits.astype(jnp.float32)
    vp = logits.shape[-1]
    rank = jax.lax.axis_index(TENSOR_AXIS)
    start = rank * vp

    # numerically-stable global softmax pieces (:34-56); the max shift
    # cancels analytically in d(loss)/d(logits), so it is detached — which
    # also sidesteps pmax's missing transpose rule (the reference backward
    # :58-99 likewise treats it as a constant)
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1))
    global_max = jax.lax.pmax(local_max, TENSOR_AXIS)
    shifted = logits - global_max[..., None]
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), TENSOR_AXIS)

    # predicted logit: only the owning rank contributes (:40-52)
    in_range = (target >= start) & (target < start + vp)
    local_idx = jnp.where(in_range, target - start, 0)
    picked = jnp.take_along_axis(shifted, local_idx[..., None], axis=-1)[..., 0]
    predicted = jax.lax.psum(jnp.where(in_range, picked, 0.0), TENSOR_AXIS)

    loss = jnp.log(sum_exp) - predicted
    if label_smoothing > 0.0:
        # smoothing term needs mean of all logits: psum of local sums
        vocab_size = vp * _axis_size(TENSOR_AXIS)
        mean_logits = (jax.lax.psum(jnp.sum(shifted, axis=-1), TENSOR_AXIS)
                       / vocab_size)
        # loss = (1-s)*nll + s * (log_sum_exp - mean_logits)
        loss = (1.0 - label_smoothing) * loss + label_smoothing * (
            jnp.log(sum_exp) - mean_logits)
    return loss
