"""Model-parallel RNG streams + activation checkpointing.

Reference: ``reference:apex/transformer/tensor_parallel/random.py`` —
``CudaRNGStatesTracker`` (:120-193) maintains named CUDA RNG states so TP
ranks share a "model-parallel" stream (same dropout inside a TP-sharded
layer) while keeping distinct data-parallel streams;
``model_parallel_cuda_manual_seed`` (:200-230) lays the seeds out as
``tp_seed = seed + 2718 + tp_rank``, ``dp_seed = seed``; and
``CheckpointFunction`` (:233-304) re-forks the RNG in backward so recomputed
dropout masks match the forward.

JAX redesign: RNG is explicit keys, so the tracker stores named ``PRNGKey``
streams and ``fork`` hands out a fresh fold. Recompute-with-same-randomness
is automatic under ``jax.checkpoint`` because keys are *inputs* — the entire
stash/restore dance of :246-290 disappears, which is the point of
re-designing rather than porting. ``get_states``/``set_states`` keep the
checkpointability of :140-151.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "RNGStatesTracker", "get_rng_tracker", "model_parallel_seed",
    "checkpoint", "_MODEL_PARALLEL_RNG_TRACKER_NAME",
]

_MODEL_PARALLEL_RNG_TRACKER_NAME = "model-parallel-rng"
_TENSOR_SEED_OFFSET = 2718  # reference:tensor_parallel/random.py:200-230


class RNGStatesTracker:
    """Named PRNG streams (``random.py:120-193``). ``fork(name)`` yields a
    fresh subkey each call and advances the stream, mirroring how forking
    CUDA RNG state advances it."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self) -> None:
        self.states_ = {}

    def get_states(self) -> Dict[str, jax.Array]:
        return dict(self.states_)

    def set_states(self, states: Dict[str, jax.Array]) -> None:
        self.states_ = dict(states)

    def add(self, name: str, seed) -> None:
        if name in self.states_:
            raise Exception(f"rng state {name} already exists")
        if isinstance(seed, int):
            key = jax.random.PRNGKey(seed)
        else:
            key = seed
        self.states_[name] = key

    def make_key(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME
                 ) -> jax.Array:
        """Split off a subkey and advance the named stream."""
        if name not in self.states_:
            raise Exception(f"rng state {name} is not added")
        key, sub = jax.random.split(self.states_[name])
        self.states_[name] = key
        return sub

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG_TRACKER_NAME):
        """Context-manager API parity with ``random.py:171-193``; yields the
        subkey to thread into dropout/init calls."""
        yield self.make_key(name)


_GLOBAL_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """``get_cuda_rng_tracker`` equivalent."""
    return _GLOBAL_TRACKER


def model_parallel_seed(seed: int, tensor_rank: Optional[int] = None,
                        data_rank: Optional[int] = None) -> None:
    """``model_parallel_cuda_manual_seed`` (:200-230): installs the default
    (data-parallel) stream at ``seed`` and the model-parallel stream at
    ``seed + 2718 + tp_rank``.

    ``tensor_rank``/``data_rank`` may be traced ranks (inside shard_map) —
    keys are built with ``fold_in`` so tracing works. ``data_rank`` is an
    extension over the reference: folding it into the default stream gives
    each DP replica independent dropout masks (the reference reuses ``seed``
    on every rank).
    """
    tracker = get_rng_tracker()
    tracker.reset()
    base = jax.random.PRNGKey(seed)
    if data_rank is not None:
        base = jax.random.fold_in(base, data_rank)
    tracker.add("default", base)
    if tensor_rank is None:
        tp_key = jax.random.PRNGKey(seed + _TENSOR_SEED_OFFSET)
    else:
        tp_key = jax.random.fold_in(
            jax.random.PRNGKey(seed + _TENSOR_SEED_OFFSET), tensor_rank)
    tracker.add(_MODEL_PARALLEL_RNG_TRACKER_NAME, tp_key)


# Activation checkpointing: recompute in backward; RNG correctness is free
# because keys are explicit inputs (vs CheckpointFunction random.py:233-304).
checkpoint = jax.checkpoint
