"""Reusable memory buffers — API-compat layer.

Reference: ``reference:apex/transformer/tensor_parallel/memory.py:35-146`` —
``MemoryBuffer`` hands out zero-copy views of one preallocated flat tensor
(used for checkpointed activations), ``RingMemBuffer`` rotates over N of
them.

On TPU/XLA, buffer reuse is the compiler's job (donation + liveness
analysis); a Python-side preallocated buffer cannot alias XLA temporaries.
These classes keep the API (some Megatron-derived code instantiates them)
with functional semantics: ``get`` returns a correctly-shaped zero view.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

__all__ = ["MemoryBuffer", "RingMemBuffer", "allocate_mem_buff"]


class MemoryBuffer:
    def __init__(self, name: str, numel: int, dtype, track_usage: bool = False):
        self.name = name
        self.numel = numel
        self.dtype = dtype
        self._start = 0
        self.in_use_value = 0
        self.total_value = 0

    def reset(self) -> None:
        self._start = 0

    def is_in_use(self) -> bool:
        return self._start > 0

    def numel_in_use(self) -> int:
        return self._start

    def add(self, shape: Tuple[int, ...]) -> jnp.ndarray:
        numel = 1
        for d in shape:
            numel *= int(d)
        if self._start + numel > self.numel:
            raise RuntimeError(f"memory buffer {self.name} overflow")
        self._start += numel
        return jnp.zeros(shape, self.dtype)

    def get_data(self) -> jnp.ndarray:
        return jnp.zeros((self.numel,), self.dtype)


class RingMemBuffer:
    def __init__(self, name: str, num_buffers: int, numel: int, dtype,
                 track_usage: bool = False):
        self.num_buffers = num_buffers
        self.buffers = [MemoryBuffer(f"{name} {i}", numel, dtype, track_usage)
                        for i in range(num_buffers)]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        buf.reset()
        return buf


def allocate_mem_buff(name: str, numel: int, dtype, track_usage: bool = False
                      ) -> MemoryBuffer:
    return MemoryBuffer(name, numel, dtype, track_usage)
