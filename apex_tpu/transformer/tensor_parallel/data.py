"""Batch broadcast across the tensor-parallel group.

Reference: ``reference:apex/transformer/tensor_parallel/data.py`` —
``broadcast_data`` (:80+) sends the rank-0 batch dict (sizes first, then one
flattened i64 payload) to all TP ranks so every rank of a TP group consumes
identical data.

TPU version: inside ``shard_map``, rank 0's values are distributed with a
masked ``psum`` (contributions from other ranks zeroed) — one collective,
same result. Under GSPMD jit the same guarantee usually comes for free by
replicating the batch over the tensor axis; this explicit form exists for
shard_map code paths and parity tests.
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS

__all__ = ["broadcast_data", "broadcast_from_tensor_parallel_rank0"]


def broadcast_from_tensor_parallel_rank0(x: jnp.ndarray) -> jnp.ndarray:
    """Every TP rank gets rank 0's value (masked-psum broadcast)."""
    rank = jax.lax.axis_index(TENSOR_AXIS)
    contrib = jnp.where(rank == 0, x, jnp.zeros_like(x))
    return jax.lax.psum(contrib, TENSOR_AXIS)


def broadcast_data(keys: Sequence[str], data: Dict[str, jnp.ndarray],
                   datatype=None) -> Dict[str, jnp.ndarray]:
    """``broadcast_data(keys, data, dtype)`` parity: returns a dict where
    every key holds rank-0's tensor. ``datatype`` casts like the reference's
    check_data_types."""
    out = {}
    for k in keys:
        v = data[k]
        if datatype is not None:
            v = v.astype(datatype)
        # ints must ride the psum as numbers; bool promoted
        if v.dtype == jnp.bool_:
            out[k] = broadcast_from_tensor_parallel_rank0(
                v.astype(jnp.int32)).astype(jnp.bool_)
        else:
            out[k] = broadcast_from_tensor_parallel_rank0(v)
    return out
