"""TP collective mappings.

Reference: ``reference:apex/transformer/tensor_parallel/mappings.py`` — four
autograd Functions pairing a forward collective with its transpose:
``_CopyToModelParallelRegion`` (:79, identity fwd / allreduce bwd),
``_ReduceFromModelParallelRegion`` (:95, allreduce fwd / identity bwd),
``_ScatterToModelParallelRegion`` (:111, split fwd / allgather bwd),
``_GatherFromModelParallelRegion`` (:127, allgather fwd / split bwd).

TPU redesign: the reference hand-writes each backward because torch autograd
has no notion of device-variance. JAX's varying-manual-axes (VMA) type system
*is* that notion, and its transposes are exactly the Megatron pairs by
construction: the transpose of marking a value varying (``pcast
to='varying'``) is ``psum``, the transpose of ``psum`` is mark-varying, and
the transpose of a per-rank slice feeding a psum is the all-gather-sum. So
these mappings are thin forward-only wrappers and native AD produces the
reference's backward collectives with no custom_vjp — fewer moving parts and
correct for any input variance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
]


def _vary(x):
    """Mark ``x`` device-varying over the tensor axis (idempotent; on
    pre-VMA jax the cast is an identity and shard_map's own replication
    rewrite supplies the transpose psum)."""
    from apex_tpu.utils.vma import cast_to_vma
    return cast_to_vma(x, frozenset({TENSOR_AXIS}))


def copy_to_tensor_model_parallel_region(x):
    """Identity forward; AD transpose of the vary-cast is the backward
    allreduce (:79-92)."""
    return _vary(x)


def reduce_from_tensor_model_parallel_region(x):
    """Allreduce forward; AD transpose of psum is the identity-as-varying
    backward (:95-108)."""
    return jax.lax.psum(_vary(x), TENSOR_AXIS)


def _split_local(x):
    tp = _axis_size(TENSOR_AXIS)
    if x.shape[-1] % tp:
        # a floor-divide here would silently drop the trailing
        # x.shape[-1] % tp elements on every rank
        raise ValueError(
            f"scatter_to_tensor_model_parallel_region: last dim of size "
            f"{x.shape[-1]} is not divisible by tensor parallel size {tp}")
    rank = jax.lax.axis_index(TENSOR_AXIS)
    chunk = x.shape[-1] // tp
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk, axis=-1)


def scatter_to_tensor_model_parallel_region(x):
    """Keep-own-slice forward; transpose = gather of the slice cotangents
    (:111-124)."""
    return _split_local(_vary(x))


def gather_from_tensor_model_parallel_region(x):
    """All-gather along the last dim forward; transpose = reduce-scatter,
    which for the replicated cotangents of TP training is the reference's
    take-own-slice backward (:127-140)."""
    from apex_tpu.utils.vma import varying_all_gather
    return varying_all_gather(x, TENSOR_AXIS, axis=x.ndim - 1, tiled=True)
