"""Ring-decomposed collective matmuls: overlap TP collectives with the
GEMMs that consume them.

Reference: ``reference:apex/transformer/tensor_parallel/layers.py:259-374``
(``LinearWithGradAccumulationAndAsyncAllreduce``) hides TP communication
behind compute by hand-rolling async NCCL handles. Our layers' docstring
notes XLA's latency-hiding scheduler does that for free — but only for
*independent* collectives. The sequence-parallel hot path is a **dependent**
pair: ColumnParallel all-gathers the sequence and immediately feeds the
GEMM; RowParallel's GEMM immediately feeds a reduce-scatter. A monolithic
``all_gather``/``psum_scatter`` cannot start or finish under the GEMM it is
glued to, so every transformer block exposes one full ICI latency each way.

The fix (Wang et al., "Overlapping Communication with Dependent Computation
via Decomposition in Large Deep Learning Models", ASPLOS 2023; also
Megatron-LM's ``tp_comm_overlap``) is to decompose both ops into ``tp``
ring steps of ``lax.ppermute`` + a partial ``dot_general``:

- :func:`all_gather_matmul` (``AG ⊗ matmul``): each rank starts from its
  own sequence chunk, GEMMs it, and ppermutes it to the next rank — chunk
  *k*'s transfer is independent of chunk *k−1*'s GEMM, so the scheduler
  rides the transfer under the GEMM. After ``tp−1`` hops every rank has
  computed the full-sequence product without ever materializing a fused
  all-gather.
- :func:`matmul_reduce_scatter` (``matmul ⊗ RS``): a partial-sum
  accumulator travels the ring; at each stop the local rank GEMMs the
  sequence chunk the accumulator is destined for and adds it. The incoming
  ``ppermute`` overlaps the local GEMM. The accumulator visits ranks in a
  **fixed ring order**, so the fp32 accumulation order is deterministic
  (``psum_scatter``'s order is backend-defined); at tp=2 a two-term fp32
  sum is commutative, so in fp32 compute the result is bit-identical to
  the fused path. (Under bf16 compute the ring is *better*, not
  bit-equal: it accumulates in fp32 end-to-end where the fused path casts
  each rank's partial to bf16 before the reduction.)

Both carry a ``custom_vjp`` whose backward uses the *transposed*
decomposition — the reduce-scatter of dX rides under the dW GEMM (the
exact win of apex's async-allreduce backward), and the all-gather of dY
rides under its own partial GEMMs:

    all_gather_matmul:    dX = RS(dY @ W)  (ring) ∥ dW = dYᵀ @ AG(X)
    matmul_reduce_scatter: dX = AG(dY) @ W (ring) ∥ dW = AG(dY)ᵀ @ X

so forward AND backward overlap. ``X_full`` (the gathered activations) is
assembled for free from the ring's received chunks and saved as the
residual — tp× the shard's memory, the classic Megatron trade (re-gathering
in backward would re-serialize the dW GEMM behind a collective).

Everything here is plain SPMD code (``ppermute`` + ``dot_general``) — it
runs inside ``shard_map`` on any jax version, pre-VMA 0.4.x included; the
backward rules are written explicitly so no VMA replication rewrite is
needed for correctness.

Telemetry: ``tp/overlap_chunks`` and ``tp/collective_bytes`` are recorded
at the *model* level (``GPTModel.transform``), not here — these functions
are traced by the ``custom_vjp`` machinery (and often inside a layer
``lax.scan``), where an :mod:`apex_tpu.observability.ingraph` record would
capture tracers from the wrong trace level and count one scan-body trace
instead of ``num_layers`` executions.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.compat import axis_size as _axis_size
from apex_tpu.utils.vma import cast_to_vma, reconcile_cotangent

__all__ = ["all_gather_matmul", "matmul_reduce_scatter"]


def _dims_last(a_ndim: int, w_axis: int):
    """Contract ``a``'s last dim with ``w``'s ``w_axis`` dim (no batch)."""
    return (((a_ndim - 1,), (w_axis,)), ((), ()))


def _ring_all_gather_matmul(x: jnp.ndarray, w: jnp.ndarray, axis_name: str,
                            seq_axis: int, w_axis: int
                            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``AG(x, seq_axis) · w`` decomposed into ``tp`` {ppermute, dot} pairs.

    ``x``: this rank's sequence chunk; ``w``: this rank's weight shard,
    contracted ``x[..., -1] × w[w_axis]``. Returns ``(y_full, x_full)``:
    the full-sequence product (fp32, MXU accumulation) and the gathered
    operand (assembled from the received chunks, ``x.dtype``) for use as a
    backward residual. Issues exactly ``tp−1`` ppermutes; each hop is
    independent of the same step's partial GEMM, which is what lets XLA's
    latency-hiding scheduler overlap them.
    """
    tp = _axis_size(axis_name)
    x = cast_to_vma(x, frozenset({axis_name}))
    rank = jax.lax.axis_index(axis_name)
    s_loc = x.shape[seq_axis]
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    dims = _dims_last(x.ndim, w_axis)

    cur = x
    y_full = x_full = None
    for t in range(tp):
        # after t hops this rank holds the chunk that originated on rank-t
        origin = jax.lax.rem(rank - t + tp, tp)
        part = jax.lax.dot_general(cur, w, dims,
                                   preferred_element_type=jnp.float32)
        if y_full is None:
            y_shape = list(part.shape)
            y_shape[seq_axis] = tp * s_loc
            y_full = cast_to_vma(jnp.zeros(y_shape, jnp.float32),
                                 frozenset({axis_name}))
            x_shape = list(cur.shape)
            x_shape[seq_axis] = tp * s_loc
            x_full = cast_to_vma(jnp.zeros(x_shape, cur.dtype),
                                 frozenset({axis_name}))
        start = origin * s_loc
        y_full = jax.lax.dynamic_update_slice_in_dim(y_full, part, start,
                                                     axis=seq_axis)
        x_full = jax.lax.dynamic_update_slice_in_dim(x_full, cur, start,
                                                     axis=seq_axis)
        if t < tp - 1:
            cur = jax.lax.ppermute(cur, axis_name, perm)
    return y_full, x_full


def _ring_matmul_reduce_scatter(x: jnp.ndarray, w: jnp.ndarray,
                                axis_name: str, seq_axis: int, w_axis: int,
                                partial_add: Optional[jnp.ndarray] = None
                                ) -> jnp.ndarray:
    """``RS_seq(x · w [+ partial_add])`` as a ring of partial GEMMs.

    ``x``: full-sequence local operand (each rank a different partial
    product term); returns this rank's sequence shard of the rank-sum
    (fp32). The accumulator for chunk ``c`` starts on rank ``c+1`` and
    visits ranks in ring order, ending at its owner — ``tp−1`` ppermutes,
    each overlapping the next stop's partial GEMM, and a deterministic
    fp32 accumulation order fixed by ring position.
    """
    tp = _axis_size(axis_name)
    x = cast_to_vma(x, frozenset({axis_name}))
    rank = jax.lax.axis_index(axis_name)
    s_full = x.shape[seq_axis]
    if s_full % tp:
        raise ValueError(
            f"matmul_reduce_scatter: dim {seq_axis} of size {s_full} is not "
            f"divisible by {axis_name!r} axis size {tp}")
    s_loc = s_full // tp
    perm = [(i, (i + 1) % tp) for i in range(tp)]
    dims = _dims_last(x.ndim, w_axis)

    acc = None
    for t in range(tp):
        # this rank is stop t of the chunk destined for rank - t - 1
        c = jax.lax.rem(rank - t - 1 + 2 * tp, tp)
        chunk = jax.lax.dynamic_slice_in_dim(x, c * s_loc, s_loc,
                                             axis=seq_axis)
        part = jax.lax.dot_general(chunk, w, dims,
                                   preferred_element_type=jnp.float32)
        if partial_add is not None:
            part = part + partial_add.astype(jnp.float32)
        if acc is None:
            acc = part
        else:
            acc = jax.lax.ppermute(acc, axis_name, perm) + part
    return acc


# ---------------------------------------------------------------------------
# public primitives (custom_vjp: fwd AND bwd are ring-decomposed)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def all_gather_matmul(x: jnp.ndarray, w_t: jnp.ndarray,
                      axis_name: str = TENSOR_AXIS,
                      seq_axis: int = 1) -> jnp.ndarray:
    """``all_gather(x, seq_axis) @ w_t.T`` with the gather ring-decomposed
    under the partial GEMMs — the sequence-parallel ColumnParallel forward.

    ``x``: ``(..., s_local, ..., in)`` sequence shard over ``axis_name``;
    ``w_t``: ``(out, in)`` weight shard (torch layout). Returns the
    full-sequence ``(..., tp*s_local, ..., out)`` product in fp32 (same
    MXU-accumulation contract as the fused path — cast at the call site).
    Backward: ``dX = RS_seq(dY @ W)`` ring-decomposed, overlapping the
    single ``dW = dYᵀ @ AG(X)`` GEMM (the async-allreduce-backward win).
    """
    y, _ = _ring_all_gather_matmul(x, w_t, axis_name, seq_axis, w_axis=1)
    return y


def _ag_mm_fwd(x, w_t, axis_name, seq_axis):
    y, x_full = _ring_all_gather_matmul(x, w_t, axis_name, seq_axis,
                                        w_axis=1)
    return y, (w_t, x_full)


def _ag_mm_bwd(axis_name, seq_axis, res, dy):
    w_t, x_full = res
    # dX: (…, s_full, out)·(out, in) -> shard — ring reduce-scatter of the
    # input cotangents, each hop riding under the next partial GEMM
    dx = _ring_matmul_reduce_scatter(dy, w_t, axis_name, seq_axis, w_axis=0)
    dx = dx.astype(x_full.dtype)
    # dW: one dense GEMM over the saved gathered activations — independent
    # of the dX ring, so the scheduler overlaps the two
    bdims = tuple(range(dy.ndim - 1))
    dw = jax.lax.dot_general(dy, x_full.astype(jnp.float32),
                             ((bdims, bdims), ((), ())),
                             preferred_element_type=jnp.float32)
    # x_full carries x's varying-axes set (built from x via the ring), so it
    # stands in for the primal in the VMA reconciliation (no-op pre-VMA)
    return (reconcile_cotangent(dx, x_full),
            reconcile_cotangent(dw.astype(w_t.dtype), w_t))


all_gather_matmul.defvjp(_ag_mm_fwd, _ag_mm_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def matmul_reduce_scatter(x: jnp.ndarray, w_t: jnp.ndarray,
                          partial_add: Optional[jnp.ndarray] = None,
                          axis_name: str = TENSOR_AXIS,
                          seq_axis: int = 1) -> jnp.ndarray:
    """``reduce_scatter(x @ w_t.T [+ partial_add], seq_axis)`` with the
    reduction ring-decomposed under the partial GEMMs — the
    sequence-parallel RowParallel forward.

    ``x``: ``(..., s_full, ..., in_local)`` full-sequence local operand;
    ``w_t``: ``(out, in_local)`` shard; ``partial_add``: optional
    ``(out,)``-broadcastable term added to every rank's partial *before*
    the reduction (the RowParallel bias fold — each of the ``tp`` partials
    carries ``b/tp`` so the ring sum restores ``b`` exactly once, and its
    cotangent is the full-sequence sum on every rank, matching the fused
    path's semantics on any jax version). Returns this rank's
    ``(..., s_full/tp, ..., out)`` shard of the sum in fp32, accumulation
    order fixed by ring position (in fp32 compute: bit-identical to
    ``psum_scatter`` at tp=2, ≤1-ULP reordering beyond; in bf16 compute
    the fused path reduces in bf16 while this stays fp32 — better, not
    bit-equal).
    Backward: ``dX = AG(dY) @ W`` ring-decomposed; the gathered ``dY``
    falls out of the same ring and feeds the dW GEMM.
    """
    return _ring_matmul_reduce_scatter(x, w_t, axis_name, seq_axis,
                                       w_axis=1, partial_add=partial_add)


def _mm_rs_fwd(x, w_t, partial_add, axis_name, seq_axis):
    y = _ring_matmul_reduce_scatter(x, w_t, axis_name, seq_axis, w_axis=1,
                                    partial_add=partial_add)
    return y, (x, w_t, None if partial_add is None else partial_add)


def _mm_rs_bwd(axis_name, seq_axis, res, dy):
    x, w_t, partial_add = res
    # dX: AG_seq(dY)·(out, in) — ring-decomposed; dy_full assembles from the
    # received chunks for free
    dx, dy_full = _ring_all_gather_matmul(dy, w_t, axis_name, seq_axis,
                                          w_axis=0)
    dx = dx.astype(x.dtype)
    bdims = tuple(range(x.ndim - 1))
    dw = jax.lax.dot_general(dy_full, x.astype(jnp.float32),
                             ((bdims, bdims), ((), ())),
                             preferred_element_type=jnp.float32)
    if partial_add is None:
        d_add = None
    else:
        # every rank's partial carried partial_add at every position, and
        # rank c's output chunk is the cotangent of each rank's partial at
        # that chunk — so the per-rank cotangent is the broadcast-transpose
        # of dY_full (identical on every rank; no collective needed): sum
        # over every axis partial_add was broadcast along, right-aligned
        padded = ((1,) * (dy_full.ndim - jnp.ndim(partial_add))
                  + jnp.shape(partial_add))
        axes = tuple(i for i, n in enumerate(padded) if n == 1)
        d_add = jnp.sum(dy_full, axis=axes).reshape(
            jnp.shape(partial_add)).astype(partial_add.dtype)
        d_add = reconcile_cotangent(d_add, partial_add)
    return (reconcile_cotangent(dx, x),
            reconcile_cotangent(dw.astype(w_t.dtype), w_t), d_add)


matmul_reduce_scatter.defvjp(_mm_rs_fwd, _mm_rs_bwd)
