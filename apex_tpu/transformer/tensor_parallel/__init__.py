"""Tensor-parallel toolkit (``reference:apex/transformer/tensor_parallel/``)."""

from apex_tpu.transformer.tensor_parallel.collective_matmul import (  # noqa: F401,E501
    all_gather_matmul, matmul_reduce_scatter)
from apex_tpu.transformer.tensor_parallel.cross_entropy import (  # noqa: F401
    vocab_parallel_cross_entropy)
from apex_tpu.transformer.tensor_parallel.data import (  # noqa: F401
    broadcast_data, broadcast_from_tensor_parallel_rank0)
from apex_tpu.transformer.tensor_parallel.layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    init_method_normal)
from apex_tpu.transformer.tensor_parallel.mappings import (  # noqa: F401
    copy_to_tensor_model_parallel_region,
    gather_from_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
    scatter_to_tensor_model_parallel_region)
from apex_tpu.transformer.tensor_parallel.memory import (  # noqa: F401
    MemoryBuffer, RingMemBuffer, allocate_mem_buff)
from apex_tpu.transformer.tensor_parallel.random import (  # noqa: F401
    RNGStatesTracker, checkpoint, get_rng_tracker, model_parallel_seed)

__all__ = [
    "all_gather_matmul", "matmul_reduce_scatter",
    "vocab_parallel_cross_entropy",
    "broadcast_data", "broadcast_from_tensor_parallel_rank0",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "init_method_normal",
    "copy_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "MemoryBuffer", "RingMemBuffer", "allocate_mem_buff",
    "RNGStatesTracker", "checkpoint", "get_rng_tracker", "model_parallel_seed",
]
