"""Model-parallel device-mesh state.

Reference: ``reference:apex/transformer/parallel_state.py`` —
``initialize_model_parallel`` (:73-247) carves NCCL process groups for
DP / TP / PP / "model" / embedding from a (tp_size, pp_size, vpp_size)
spec; plus ~30 rank/world accessors (:273-549) and ``destroy_model_parallel``
(:555-580).

TPU-native redesign: the process-group zoo becomes ONE
``jax.sharding.Mesh`` with axes ``("pipe", "data", "tensor")``, reshaped
from the device list in the same rank order the reference uses (tp fastest,
then dp, then pp — ``parallel_state.py:153-247``), so rank arithmetic is
identical. "Process groups" are just axis names (collectives) or
``axis_index_groups``; the embedding group (first+last stage tying,
:215-247) is expressed by the embedding-grad psum in the pipeline schedule.

Accessors come in two flavors:
- static (host Python): sizes, this-process coordinates when running
  multi-process (from ``jax.process_index``), enums of groups;
- traced (inside ``shard_map``): ``get_*_rank()`` uses ``lax.axis_index``
  so the same call sites work under jit, mirroring how reference call sites
  query ranks inside the step.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "initialize_model_parallel", "destroy_model_parallel",
    "model_parallel_is_initialized", "get_mesh",
    "get_tensor_model_parallel_world_size", "get_pipeline_model_parallel_world_size",
    "get_data_parallel_world_size", "get_virtual_pipeline_model_parallel_world_size",
    "get_tensor_model_parallel_rank", "get_pipeline_model_parallel_rank",
    "get_data_parallel_rank",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "is_pipeline_first_stage", "is_pipeline_last_stage",
    "is_rank_in_embedding_group",
    "get_pipeline_model_parallel_next_rank", "get_pipeline_model_parallel_prev_rank",
    "get_pipeline_model_parallel_split_rank",
    "set_pipeline_model_parallel_split_rank",
    "get_context_parallel_world_size", "get_context_parallel_rank",
    "get_context_parallel_groups",
    "get_tensor_model_parallel_groups", "get_data_parallel_groups",
    "get_pipeline_model_parallel_groups", "get_embedding_ranks",
    "get_rank_info",
    "PIPE_AXIS", "DATA_AXIS", "CONTEXT_AXIS", "TENSOR_AXIS",
]

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
CONTEXT_AXIS = "context"
TENSOR_AXIS = "tensor"

_MESH: Optional[Mesh] = None
_VIRTUAL_PP_SIZE: Optional[int] = None
_VIRTUAL_PP_RANK: Optional[int] = None
_PP_SPLIT_RANK: Optional[int] = None


def _dcn_device_grid(devices: Sequence, tp: int, pp: int, cp: int,
                     dp: int) -> np.ndarray:
    """dp-outermost-over-DCN device grid for a multi-process world.

    **The axis-ordering rule** (ROADMAP item 3, documented in
    docs/ROBUSTNESS.md): in a multi-process (multi-host) world, the
    inter-process links (DCN / loopback on the localhost simulation) are
    orders of magnitude slower than intra-process ICI, so the mesh must
    place the axes whose collectives are *latency-tolerant and
    overlappable* across the slow links and keep the *latency-critical*
    axes inside a process:

    - **data** spans processes: its grad reduce-scatter/all-gather is
      once per step and rides under the backward (PR 8's interleaved
      buckets exist to hide exactly this transfer);
    - **tensor / context / pipeline** stay intra-process: tp collectives
      sit on the critical path of every layer (activation
      gather/scatter), and the pipe ppermute latency bounds the bubble.

    Grid construction: group the devices by ``process_index`` (equal
    local counts required), factor ``dp = num_processes x dp_local``, lay
    each process's devices out ``(dp_local, pp, cp, tp)`` locally (tp
    fastest, matching the single-process convention), and make the
    process index the OUTERMOST factor of the data axis — so a
    data-axis collective crosses the DCN exactly once per ring step, and
    no tp/pp/cp neighbor pair ever spans a process boundary.
    """
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(getattr(d, "process_index", 0), []).append(d)
    procs = sorted(by_proc)
    nproc = len(procs)
    counts = {len(by_proc[p]) for p in procs}
    if len(counts) != 1:
        raise RuntimeError(
            f"uneven per-process device counts "
            f"{ {p: len(by_proc[p]) for p in procs} } — the DCN layout "
            f"needs identical local topology on every process")
    per = counts.pop()
    if dp % nproc != 0:
        raise RuntimeError(
            f"data-parallel size {dp} is not divisible by the process "
            f"count {nproc}: dp is the axis that spans the DCN, so every "
            f"process must hold the same number of dp ranks")
    dp_local = dp // nproc
    if per != dp_local * pp * cp * tp:
        raise RuntimeError(
            f"per-process device count {per} != dp_local({dp_local}) x "
            f"pp({pp}) x cp({cp}) x tp({tp}) — tensor/pipeline/context "
            f"axes must fit inside one process (only dp spans the DCN)")
    local = [sorted(by_proc[p], key=lambda d: getattr(d, "id", 0))
             for p in procs]
    natural = np.empty((nproc, per), dtype=object)
    for i, devs in enumerate(local):
        natural[i, :] = devs
    natural = natural.reshape(nproc, dp_local, pp, cp, tp)
    # (proc, dp_local, pp, cp, tp) -> (pp, proc x dp_local = dp, cp, tp)
    return natural.transpose(2, 0, 1, 3, 4).reshape(pp, dp, cp, tp)


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    context_parallel_size: int = 1,
    devices: Optional[Sequence] = None,
    dcn_data_parallel: Optional[bool] = None,
) -> Mesh:
    """Build and install the global mesh (``parallel_state.py:73-247``).

    ``devices`` defaults to ``jax.devices()``; data-parallel size is derived
    as ``len(devices) / (tp*pp*cp)`` exactly like the reference derives it
    from world size. ``context_parallel_size`` carves a ``context`` axis
    (for ring/Ulysses attention, :mod:`apex_tpu.transformer.
    context_parallel`) out of the data dimension — the reference has no CP
    groups at all (SURVEY §2.3); the layout follows Megatron-LM's later
    convention: tp fastest, then cp, then dp, then pp.

    ``dcn_data_parallel`` selects the multi-host layout rule
    (:func:`_dcn_device_grid`): the data axis is laid out outermost over
    the process (DCN) dimension while tp/pp/cp stay strictly
    intra-process. Default ``None`` auto-enables it exactly when the
    device set spans more than one process — a single-process mesh keeps
    the legacy ``(pp, dp, cp, tp)`` reshape bit-for-bit (every existing
    single-host checkpoint/test layout is unchanged).
    """
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK, _PP_SPLIT_RANK
    if devices is None:
        devices = jax.devices()
    world = len(devices)
    tp, pp = tensor_model_parallel_size, pipeline_model_parallel_size
    cp = context_parallel_size
    if world % (tp * pp * cp) != 0:
        raise RuntimeError(
            f"world size ({world}) is not divisible by tensor ({tp}) x "
            f"pipeline ({pp}) x context ({cp}) parallel sizes")
    dp = world // (tp * pp * cp)
    if virtual_pipeline_model_parallel_size is not None and pp < 2:
        raise RuntimeError(
            "pipeline-model-parallel size must be at least 2 with the "
            "interleaved schedule")
    if dcn_data_parallel is None:
        dcn_data_parallel = len(
            {getattr(d, "process_index", 0) for d in devices}) > 1
    if dcn_data_parallel:
        grid = _dcn_device_grid(devices, tp, pp, cp, dp)
    else:
        # single-host rank layout: tp fastest, then cp, then dp, then pp
        grid = np.asarray(devices).reshape(pp, dp, cp, tp)
    _MESH = Mesh(grid, (PIPE_AXIS, DATA_AXIS, CONTEXT_AXIS, TENSOR_AXIS))
    _VIRTUAL_PP_SIZE = virtual_pipeline_model_parallel_size
    _VIRTUAL_PP_RANK = 0 if virtual_pipeline_model_parallel_size else None
    _PP_SPLIT_RANK = pipeline_model_parallel_split_rank
    return _MESH


def model_parallel_is_initialized() -> bool:
    return _MESH is not None


def get_mesh() -> Mesh:
    if _MESH is None:
        raise RuntimeError("model parallel is not initialized — call "
                           "initialize_model_parallel() first")
    return _MESH


def destroy_model_parallel() -> None:
    """``parallel_state.py:555-580``."""
    global _MESH, _VIRTUAL_PP_SIZE, _VIRTUAL_PP_RANK, _PP_SPLIT_RANK
    _MESH = None
    _VIRTUAL_PP_SIZE = None
    _VIRTUAL_PP_RANK = None
    _PP_SPLIT_RANK = None


# -- world sizes (static) ----------------------------------------------------

def get_tensor_model_parallel_world_size() -> int:
    return get_mesh().shape[TENSOR_AXIS]


def get_pipeline_model_parallel_world_size() -> int:
    return get_mesh().shape[PIPE_AXIS]


def get_data_parallel_world_size() -> int:
    return get_mesh().shape[DATA_AXIS]


def get_context_parallel_world_size() -> int:
    return get_mesh().shape[CONTEXT_AXIS]


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _VIRTUAL_PP_SIZE


# -- ranks (traced inside shard_map, static int otherwise impossible) --------

def get_tensor_model_parallel_rank():
    """Traced rank — valid inside ``shard_map`` over the mesh."""
    return jax.lax.axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PIPE_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DATA_AXIS)


def get_context_parallel_rank():
    return jax.lax.axis_index(CONTEXT_AXIS)


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    """Host-side scheduling state (``parallel_state.py:475-490``) — the
    interleaved schedule sets this while building each model chunk."""
    return _VIRTUAL_PP_RANK


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    global _VIRTUAL_PP_RANK
    _VIRTUAL_PP_RANK = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _PP_SPLIT_RANK


def set_pipeline_model_parallel_split_rank(rank: Optional[int]) -> None:
    global _PP_SPLIT_RANK
    _PP_SPLIT_RANK = rank


# -- stage predicates --------------------------------------------------------

def is_pipeline_first_stage(ignore_virtual: bool = False,
                            virtual_rank=None):
    """Traced bool inside shard_map (``parallel_state.py:449-460``).

    The virtual (interleaved-chunk) index is NOT device state in this
    framework: the scan-based schedules hand the stage body its global
    stage index explicitly, so pass ``virtual_rank`` (host int or traced)
    when querying per-chunk. The module-global set via
    ``set_virtual_pipeline_model_parallel_rank`` exists for reference API
    compatibility and is read at *trace* time — nothing traced observes
    later host mutation (the inconsistency VERDICT r1 flagged)."""
    first = get_pipeline_model_parallel_rank() == 0
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        vr = _VIRTUAL_PP_RANK if virtual_rank is None else virtual_rank
        first = jnp.logical_and(vr == 0, first)
    return first


def is_pipeline_last_stage(ignore_virtual: bool = False,
                           virtual_rank=None):
    """See :func:`is_pipeline_first_stage` for ``virtual_rank``."""
    last = (get_pipeline_model_parallel_rank()
            == get_pipeline_model_parallel_world_size() - 1)
    if not ignore_virtual and _VIRTUAL_PP_SIZE is not None:
        vr = _VIRTUAL_PP_RANK if virtual_rank is None else virtual_rank
        last = jnp.logical_and(vr == _VIRTUAL_PP_SIZE - 1, last)
    return last


def is_rank_in_embedding_group(pipeline_rank) -> bool:
    """First/last stage tie their embedding grads (``parallel_state.py:215-247``).
    Takes an explicit (host) pipeline rank."""
    return pipeline_rank in (0, get_pipeline_model_parallel_world_size() - 1)


def get_pipeline_model_parallel_next_rank():
    """(traced) ``parallel_state.py:524-531``."""
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() + 1) % pp


def get_pipeline_model_parallel_prev_rank():
    pp = get_pipeline_model_parallel_world_size()
    return (get_pipeline_model_parallel_rank() - 1) % pp


# -- group enumerations (host-side; for axis_index_groups / debugging) -------

def _global_rank(pp_r: int, dp_r: int, tp_r: int, cp_r: int = 0) -> int:
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    return tp_r + tp * (cp_r + cp * (dp_r + dp * pp_r))


def get_tensor_model_parallel_groups() -> List[List[int]]:
    """Flat-rank groups, same membership as the reference's TP groups
    (``parallel_state.py:153-247``); usable as ``axis_index_groups`` over a
    flattened device list."""
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    pp = get_pipeline_model_parallel_world_size()
    return [[_global_rank(p, d, t, c) for t in range(tp)]
            for p in range(pp) for d in range(dp) for c in range(cp)]


def get_data_parallel_groups() -> List[List[int]]:
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    pp = get_pipeline_model_parallel_world_size()
    return [[_global_rank(p, d, t, c) for d in range(dp)]
            for p in range(pp) for c in range(cp) for t in range(tp)]


def get_context_parallel_groups() -> List[List[int]]:
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    pp = get_pipeline_model_parallel_world_size()
    return [[_global_rank(p, d, t, c) for c in range(cp)]
            for p in range(pp) for d in range(dp) for t in range(tp)]


def get_pipeline_model_parallel_groups() -> List[List[int]]:
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    pp = get_pipeline_model_parallel_world_size()
    return [[_global_rank(p, d, t, c) for p in range(pp)]
            for d in range(dp) for c in range(cp) for t in range(tp)]


def get_embedding_ranks() -> List[List[int]]:
    """First+last stage per (dp, cp, tp) column
    (``parallel_state.py:215-247``)."""
    tp = get_tensor_model_parallel_world_size()
    cp = get_context_parallel_world_size()
    dp = get_data_parallel_world_size()
    pp = get_pipeline_model_parallel_world_size()
    cols = [(d, c, t) for d in range(dp) for c in range(cp)
            for t in range(tp)]
    if pp == 1:
        return [[_global_rank(0, d, t, c)] for d, c, t in cols]
    return [[_global_rank(0, d, t, c), _global_rank(pp - 1, d, t, c)]
            for d, c, t in cols]


def get_rank_info() -> Tuple[int, int, int, Optional[int]]:
    """(dp, tp, pp, vpp) sizes for log prefixes
    (``parallel_state.py:250-259`` returns ranks; sizes here since host code
    has no single rank under SPMD)."""
    if not model_parallel_is_initialized():
        return (1, 1, 1, None)
    return (get_data_parallel_world_size(),
            get_tensor_model_parallel_world_size(),
            get_pipeline_model_parallel_world_size(),
            _VIRTUAL_PP_SIZE)
