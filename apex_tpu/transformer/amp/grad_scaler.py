"""TP/PP-aware dynamic loss scaling.

Reference: ``reference:apex/transformer/amp/grad_scaler.py:38-49`` — a
``torch.cuda.amp.GradScaler`` subclass whose ``_maybe_opt_step``/``update``
allreduce ``found_inf`` with MAX over the **model-parallel group**, so every
TP/PP shard skips (or keeps) the step together even when only one shard
overflowed.

Here the same contract wraps :class:`apex_tpu.amp.DynamicLossScale`: the
finite flag is reduced (min of "is finite" == max of "found inf") over the
model axes before the scale update and the select-skip.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import (
    DynamicLossScale, LossScaleState, all_finite, select_tree)
from apex_tpu.transformer.parallel_state import PIPE_AXIS, TENSOR_AXIS

__all__ = ["GradScaler"]


class GradScaler:
    """Functional grad scaler synchronized over model-parallel axes.

    Usage inside a shard_mapped step::

        scaler = GradScaler(init_scale=2**16)
        state = scaler.init()
        finite = scaler.all_finite_synced(grads)      # reduced over tp+pp
        new_state = scaler.update(state, finite)
        params, opt_state = opt.step(grads, opt_state, params,
                                     grads_finite=finite)
    """

    def __init__(self, init_scale: float = 2.0 ** 16, growth_factor: float = 2.0,
                 backoff_factor: float = 0.5, growth_interval: int = 2000,
                 model_parallel_axes: Sequence[str] = (TENSOR_AXIS, PIPE_AXIS)):
        self._inner = DynamicLossScale(
            init_scale=init_scale, growth_factor=growth_factor,
            backoff_factor=backoff_factor, growth_interval=growth_interval)
        self.model_parallel_axes = tuple(model_parallel_axes)

    def init(self) -> LossScaleState:
        return self._inner.init()

    def scale(self, state: LossScaleState, tree: Any) -> Any:
        return self._inner.scale(state, tree)

    def unscale(self, state: LossScaleState, grads: Any,
                cast_to=jnp.float32) -> Any:
        return self._inner.unscale(state, grads, cast_to)

    def all_finite_synced(self, grads: Any) -> jnp.ndarray:
        """found_inf MAX-allreduce over the model-parallel group
        (``grad_scaler.py:38-49``), as a min-reduce of the finite flag."""
        return all_finite(grads, axis_names=self.model_parallel_axes)

    def update(self, state: LossScaleState, grads_finite: jnp.ndarray
               ) -> LossScaleState:
        return self._inner.update(state, grads_finite)
