"""Model-parallel-aware loss scaling (``reference:apex/transformer/amp/``)."""

from apex_tpu.transformer.amp.grad_scaler import GradScaler  # noqa: F401

__all__ = ["GradScaler"]
