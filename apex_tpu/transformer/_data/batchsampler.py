"""Megatron pretraining batch samplers.

Reference: ``reference:apex/transformer/_data/_batchsampler.py:38,102`` —
DP-sharded sequential and shuffled index samplers that (a) resume exactly
from ``consumed_samples`` and (b) yield each DP rank its disjoint slice of
the global batch. Framework-agnostic index arithmetic, so the port is
semantic, not mechanical: torch's generator is replaced by numpy's (the
permutation differs numerically from torch's for the same epoch seed, but
every invariant — disjointness across ranks, epoch-determinism, exact
resume — is preserved and tested).

Feeding JAX: each yielded list indexes the host dataset; pack the rows
with :func:`apex_tpu._native.gather_rows` (native memcpy batch assembly,
the host-side analog of apex_C) and ``jax.device_put`` (or feed through
``tensor_parallel.data.broadcast_data`` under TP).
"""

from __future__ import annotations

import abc
from typing import Iterator, List

import numpy as np

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]


class _Base(abc.ABC):
    """Base class (``_batchsampler.py:16-35``)."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    @abc.abstractmethod
    def __iter__(self) -> Iterator[List[int]]:
        ...

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new: int) -> None:
        self._local_minibatch_size = new
        self.local_minibatch_times_data_parallel_size = (
            new * self.data_parallel_size)


def _check(total_samples, consumed_samples, local_minibatch_size,
           data_parallel_rank, data_parallel_size, sequential: bool):
    if total_samples <= 0:
        raise RuntimeError(f"no sample to consume: {total_samples}")
    if sequential and consumed_samples >= total_samples:
        raise RuntimeError(
            f"no samples left to consume: {consumed_samples}, "
            f"{total_samples}")
    if local_minibatch_size <= 0:
        raise RuntimeError(
            f"local minibatch size must be greater than 0: "
            f"{local_minibatch_size}")
    if data_parallel_size <= 0:
        raise RuntimeError(
            f"data parallel size must be greater than 0: "
            f"{data_parallel_size}")
    if data_parallel_rank >= data_parallel_size:
        raise RuntimeError(
            f"data_parallel_rank should be smaller than data size: "
            f"{data_parallel_rank}, {data_parallel_size}")


class MegatronPretrainingSampler(_Base):
    """Sequential DP-sharded sampler (``_batchsampler.py:38-100``).

    Walks indices ``consumed_samples..total_samples``; every
    ``local_minibatch_size * dp`` indices form one global batch, of which
    this rank yields its contiguous slice.
    """

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        _check(total_samples, consumed_samples, local_minibatch_size,
               data_parallel_rank, data_parallel_size, sequential=True)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last

    def __len__(self) -> int:
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def __iter__(self):
        batch = []
        # accumulate one GLOBAL batch (lmb * dp indices) then slice this
        # rank's piece — upstream Megatron-LM's behavior. The reference fork
        # accumulates only local_minibatch_size before slicing
        # (``_batchsampler.py:88-96``), which hands every rank > 0 an empty
        # list; that is a POC bug, not semantics worth preserving.
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            tail = batch[start:end]
            if tail:
                yield tail


class MegatronPretrainingRandomSampler(_Base):
    """Shuffled DP-sharded sampler (``_batchsampler.py:102-182``).

    Each rank owns a contiguous ``bucket`` of the dataset; per epoch the
    bucket is permuted with the epoch number as seed (determinism =
    resumability), and ``consumed_samples`` positions into the permutation.
    """

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        _check(total_samples, consumed_samples, local_minibatch_size,
               data_parallel_rank, data_parallel_size, sequential=False)
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.last_batch_size = (
            self.total_samples % self.local_minibatch_times_data_parallel_size)

    def __len__(self) -> int:
        return self.total_samples

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples

        bucket_size = (self.total_samples
                       // self.local_minibatch_times_data_parallel_size
                       ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        g = np.random.RandomState(self.epoch)
        random_idx = g.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        # last incomplete batch dropped, as in the reference
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size)
                yield batch
                batch = []
