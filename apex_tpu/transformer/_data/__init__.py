"""Megatron-style data samplers (``reference:apex/transformer/_data/``)."""

from apex_tpu.transformer._data.batchsampler import (  # noqa: F401
    MegatronPretrainingRandomSampler, MegatronPretrainingSampler)

__all__ = ["MegatronPretrainingSampler", "MegatronPretrainingRandomSampler"]
