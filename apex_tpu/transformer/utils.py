"""Shared transformer utilities (``reference:apex/transformer/utils.py``)."""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["ensure_divisibility", "divide", "split_tensor_along_last_dim",
           "VocabUtility"]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    assert numerator % denominator == 0, (
        f"{numerator} is not divisible by {denominator}")


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(x: jnp.ndarray, num_partitions: int
                                ) -> Tuple[jnp.ndarray, ...]:
    """``reference:apex/transformer/utils.py`` — equal chunks of the last dim."""
    last = divide(x.shape[-1], num_partitions)
    return tuple(jnp.split(x, num_partitions, axis=-1))


class VocabUtility:
    """Vocab shard index ranges (``reference:apex/transformer/tensor_parallel/utils.py``)."""

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size: int, rank, world_size: int):
        first = rank * per_partition_vocab_size
        return first, first + per_partition_vocab_size

    @staticmethod
    def vocab_range_from_global_vocab_size(global_vocab_size: int, rank,
                                           world_size: int):
        per = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per, rank, world_size)
