"""Expert parallelism (MoE) — EP over a mesh axis.

The reference has **no** MoE (SURVEY §2.3: "EP — not required for parity;
note for roadmap"); on TPU expert parallelism is a first-class mesh axis,
so the roadmap item ships: a Switch-style top-1 routed MLP whose experts
shard over a mesh axis, with the canonical GShard dispatch/combine
einsums and one ``all_to_all`` each way (the collective EP exists for —
tokens travel to their expert's device and back over ICI).

Design (single SPMD program, static shapes):

1. router: ``gates = softmax(x @ Wg)``; top-1 expert per token, with the
   Switch load-balancing auxiliary loss;
2. capacity ``C = ceil(tokens_local * capacity_factor / E)``; per-expert
   positions via cumsum; tokens beyond capacity are dropped (their output
   is 0 and the residual path carries them, as in Switch);
3. dispatch einsum builds ``(E, C, d)`` slots; ``all_to_all`` re-shards
   from token-sharded to expert-sharded: each device receives the slots
   bound for ITS local experts from every peer;
4. local expert FFNs (dense -> gelu -> dense), vmapped over local experts;
5. reverse ``all_to_all``; combine einsum scatters expert outputs back to
   token positions, scaled by the gate.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.layers import init_method_normal
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["ExpertParallelMLP"]


class ExpertParallelMLP:
    """Switch-style top-1 MoE MLP with experts sharded over ``axis_name``.

    ``num_experts`` must divide by the axis size; parameters come back from
    :meth:`init` stacked ``(num_experts, ...)`` — shard axis 0 over the
    expert axis (``P(axis_name)``); the router is replicated.

    ``__call__(params, x)`` with ``x`` ``(tokens_local, hidden)`` (flatten
    batch x seq first) returns ``(out, aux_loss)`` — ``aux_loss`` is the
    Switch load-balancing term (mean over devices is up to the caller).
    """

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, capacity_factor: float = 1.25,
                 axis_name: str = TENSOR_AXIS,
                 init_method=None, params_dtype=jnp.float32):
        self.hidden_size = hidden_size
        self.ffn = ffn_hidden_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.axis_name = axis_name
        self.init_method = init_method or init_method_normal(0.02)
        self.params_dtype = params_dtype

    def init(self, key: jax.Array) -> dict:
        E, h, f = self.num_experts, self.hidden_size, self.ffn
        kr, k1, k2 = jax.random.split(key, 3)
        return {
            "router": {"weight": self.init_method(kr, (E, h)).astype(
                self.params_dtype)},
            "experts": {
                "wi": self.init_method(k1, (E, f, h)).astype(
                    self.params_dtype),
                "bi": jnp.zeros((E, f), self.params_dtype),
                "wo": self.init_method(k2, (E, h, f)).astype(
                    self.params_dtype),
                "bo": jnp.zeros((E, h), self.params_dtype),
            },
        }

    # -- pieces -----------------------------------------------------------
    def _route(self, params, x):
        """Top-1 gates + dispatch/combine tensors (GShard einsum form)."""
        E = self.num_experts
        n = x.shape[0]
        C = max(1, math.ceil(n * self.capacity_factor / E))
        logits = (x.astype(jnp.float32)
                  @ params["router"]["weight"].astype(jnp.float32).T)
        gates = jax.nn.softmax(logits, axis=-1)           # (n, E)
        expert = jnp.argmax(gates, axis=-1)               # (n,)
        gate = jnp.max(gates, axis=-1)                    # (n,)
        onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)
        # position of each token within its expert's queue
        pos = jnp.cumsum(onehot, axis=0) * onehot         # 1-based
        pos = jnp.sum(pos, axis=-1) - 1.0                 # (n,), -1 if none
        keep = pos < C
        gate = gate * keep
        # dispatch (n, E, C): token -> expert slot
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), C,
                                dtype=jnp.float32)        # (n, C)
        dispatch = onehot[:, :, None] * pos_oh[:, None, :] \
            * keep[:, None, None]
        combine = dispatch * gate[:, None, None]
        # Switch aux loss: E * sum_e fraction_e * mean_prob_e
        frac = jnp.mean(onehot, axis=0)
        prob = jnp.mean(gates, axis=0)
        aux = E * jnp.sum(frac * prob)
        return dispatch, combine, aux, C

    def _expert_ffn(self, ep_params, slots):
        """slots: (E_local, S, h) -> (E_local, S, h), vmapped experts."""
        def one(wi, bi, wo, bo, xs):
            dt = xs.dtype
            h1 = jax.nn.gelu(xs @ wi.astype(dt).T + bi.astype(dt),
                             approximate=True)
            return h1 @ wo.astype(dt).T + bo.astype(dt)
        return jax.vmap(one)(ep_params["wi"], ep_params["bi"],
                             ep_params["wo"], ep_params["bo"], slots)

    # -- forward ----------------------------------------------------------
    def __call__(self, params: dict, x: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        E = self.num_experts
        ep = _axis_size(self.axis_name)
        if E % ep:
            raise ValueError(f"num_experts {E} not divisible by ep={ep}")
        e_loc = E // ep
        dispatch, combine, aux, C = self._route(params, x)

        dt = x.dtype
        # (n, E, C) x (n, h) -> (E, C, h) slots on the source device
        slots = jnp.einsum("nec,nh->ech", dispatch.astype(jnp.float32),
                           x.astype(jnp.float32)).astype(dt)
        # token-sharded -> expert-sharded: split the E axis, gather peers'
        # slots for my local experts along the capacity axis
        slots = jax.lax.all_to_all(slots, self.axis_name, split_axis=0,
                                   concat_axis=1, tiled=True)
        # (e_loc, ep*C, h) through the local experts
        out_slots = self._expert_ffn(params["experts"], slots)
        out_slots = jax.lax.all_to_all(out_slots, self.axis_name,
                                       split_axis=1, concat_axis=0,
                                       tiled=True)
        # combine back to token positions, gate-scaled
        out = jnp.einsum("nec,ech->nh", combine.astype(jnp.float32),
                         out_slots.astype(jnp.float32))
        return out.astype(dt), aux
