"""Context parallelism: ring attention + sequence-parallel mappings.

The reference has **no** long-sequence distribution (SURVEY §5: no ring
attention, no context-parallel group, no Ulysses all-to-all; its fused
attention caps at seqlen 2048/512). On TPU long context is first-class,
so this module goes beyond parity:

- :func:`ring_attention` — blockwise-softmax attention with the sequence
  sharded over a mesh axis: each device holds its (b, h, s/cp, d) shard,
  k/v chunks rotate around the ring via ``ppermute`` (ICI
  neighbor-to-neighbor traffic, the ideal TPU collective), and the online
  (m, l, acc) running softmax merges chunks exactly — the Ring Attention
  construction. Causality is handled per chunk-origin: earlier chunks
  attend fully, the diagonal chunk causally, later chunks not at all
  (their work is skipped numerically via masking; the rotation itself is
  uniform, keeping the program SPMD). Backward falls out of AD through the
  scan — the transpose of ``ppermute`` is the reverse rotation, so
  gradients ride the same ring. ``remat=True`` (default) recomputes each
  chunk's scores in backward: residuals stay O(s_local·d), never
  O(s_local·s_global).
- sequence-parallel scatter/gather (Megatron-LM SP): norms/dropout run on
  a 1/tp sequence shard between the TP collectives. On TPU these are thin
  ``ppermute``-free wrappers over all_gather/psum_scatter along the
  sequence dim of the TENSOR axis.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import NEG_INF
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["ring_attention", "ulysses_attention",
           "scatter_to_sequence_parallel_region",
           "gather_from_sequence_parallel_region",
           "reduce_scatter_to_sequence_parallel_region"]


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   softmax_scale: Optional[float] = None,
                   remat: bool = True) -> jnp.ndarray:
    """Attention over a sequence sharded on ``axis_name``.

    ``q``/``k``/``v``: this device's shard, ``(b, h, s_local, d)``, where
    the global sequence is the rank-order concatenation of shards. Must be
    called inside ``shard_map`` with ``axis_name`` bound. Returns the
    output shard ``(b, h, s_local, d)``.

    Chunk math runs in fp32 (scores + running stats), inputs may be bf16.
    """
    b, h, s_loc, d = q.shape
    if softmax_scale is None:
        softmax_scale = 1.0 / math.sqrt(d)
    cp = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    qf = q.astype(jnp.float32)

    def chunk_update(carry, kv_and_t):
        m, l, acc = carry
        k_c, v_c, t = kv_and_t
        # after t rotations this device holds the chunk that originated on
        # rank (rank - t) mod cp
        kv_rank = jax.lax.rem(rank - t + cp, cp)
        s = jax.lax.dot_general(
            qf, k_c.astype(jnp.float32),
            (((3,), (3,)), ((0, 1), (0, 1)))) * softmax_scale
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 1)
            in_chunk = col <= row                      # diagonal chunk
            allowed = jnp.where(
                kv_rank < rank, True,
                jnp.where(kv_rank > rank, False, in_chunk))
            s = jnp.where(allowed, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            # fully-masked chunks drive m_new to NEG_INF -> exp == 1 garbage
            p = jnp.where(allowed, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jax.lax.dot_general(
            p, v_c.astype(jnp.float32), (((3,), (2,)), ((0, 1), (0, 1))))
        return (m_new, l, acc)

    if remat:
        chunk_update = jax.checkpoint(chunk_update)

    def body(carry, t):
        m, l, acc, k_c, v_c = carry
        m, l, acc = chunk_update((m, l, acc), (k_c, v_c, t))
        # rotate kv to the next device for the following step (uniform —
        # also on the last step, keeping the scan body SPMD-identical;
        # the final rotation returns each chunk home)
        k_c = jax.lax.ppermute(k_c, axis_name, perm)
        v_c = jax.lax.ppermute(v_c, axis_name, perm)
        return (m, l, acc, k_c, v_c), None

    from apex_tpu.utils.vma import cast_to_vma
    vma = frozenset({axis_name})
    init = (cast_to_vma(jnp.full((b, h, s_loc, 1), NEG_INF, jnp.float32),
                        vma),
            cast_to_vma(jnp.zeros((b, h, s_loc, 1), jnp.float32), vma),
            cast_to_vma(jnp.zeros((b, h, s_loc, d), jnp.float32), vma),
            k, v)
    (m, l, acc, _, _), _ = jax.lax.scan(body, init, jnp.arange(cp))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      axis_name: str, causal: bool = False,
                      softmax_scale: Optional[float] = None,
                      attention_fn=None) -> jnp.ndarray:
    """DeepSpeed-Ulysses context parallelism: two ``all_to_all``s instead
    of a ring.

    Input/output layout matches :func:`ring_attention` — ``(b, h, s/cp,
    d)`` sequence shards. Internally the first ``all_to_all`` re-shards
    from sequence-split to *head*-split (each device gets ``h/cp`` full-
    sequence heads), runs ordinary full-sequence attention per local head
    (``attention_fn``, default the fused flash/XLA dispatcher — so the
    Pallas kernel runs on full sequences), and the second ``all_to_all``
    restores sequence sharding. Requires ``h % cp == 0``; for more devices
    than heads use :func:`ring_attention`. Ulysses moves O(b·s·d·h/cp) per
    all_to_all but keeps the attention kernel monolithic; the ring keeps
    traffic neighbor-to-neighbor but chunks the kernel — the standard
    trade, both offered here.
    """
    b, h_loc_in, s_loc, d = q.shape
    cp = _axis_size(axis_name)
    # note: h here is the LOCAL head count of the sequence-sharded layout,
    # which equals the global head count (heads are replicated across cp)
    if h_loc_in % cp:
        raise ValueError(f"num heads {h_loc_in} not divisible by cp={cp}")
    if attention_fn is None:
        from apex_tpu.ops.flash_attention import flash_attention
        attention_fn = flash_attention

    def seq_to_heads(x):
        # (b, h, s/cp, d) -> (b, h/cp, s, d): each device keeps its head
        # slice, receives the full sequence (tiled all_to_all splits axis 1
        # by cp and concatenates received chunks along axis 2 in device —
        # i.e. sequence — order)
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_fn(qh, kh, vh, causal=causal,
                       softmax_scale=softmax_scale)
    return heads_to_seq(out)


# ---------------------------------------------------------------------------
# Megatron-LM sequence parallelism (norms/dropout on sequence shards)
# ---------------------------------------------------------------------------

def scatter_to_sequence_parallel_region(x: jnp.ndarray,
                                        axis_name: str = TENSOR_AXIS,
                                        seq_axis: int = 0) -> jnp.ndarray:
    """Split the sequence dim across the TP axis (fwd); gather in bwd.
    Entering an SP region (Megatron-LM ``scatter_to_sequence_parallel``;
    the reference layout is (s, b, h) so ``seq_axis`` defaults to 0 —
    pass 1 for (b, s, h) models)."""
    tp = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    if x.shape[seq_axis] % tp:
        raise ValueError(f"sequence dim {x.shape[seq_axis]} not divisible "
                         f"by tp={tp}")
    chunk = x.shape[seq_axis] // tp
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk,
                                        axis=seq_axis)


def gather_from_sequence_parallel_region(x: jnp.ndarray,
                                         axis_name: str = TENSOR_AXIS,
                                         seq_axis: int = 0,
                                         invariant: bool = False
                                         ) -> jnp.ndarray:
    """all_gather the sequence shards (fwd); reduce-scatter in bwd. Leaving
    an SP region into a TP matmul.

    ``invariant=True`` types the gathered result device-invariant (every
    rank provably holds the same full sequence). Inside a TP model this
    matters for AD bookkeeping: plain-TP activations are invariant, so the
    SP gather must restore that type or replicated-parameter cotangents
    get attributed per-rank and differ from the TP=1 semantics (see
    tests/test_models.py::test_gpt_sequence_parallel_matches_tp)."""
    if not invariant:
        from apex_tpu.utils.vma import varying_all_gather
        return varying_all_gather(x, axis_name, axis=seq_axis, tiled=True)
    from apex_tpu.utils.vma import invariant_all_gather
    return invariant_all_gather(x, axis_name, axis=seq_axis)


def reduce_scatter_to_sequence_parallel_region(x: jnp.ndarray,
                                               axis_name: str = TENSOR_AXIS,
                                               seq_axis: int = 0
                                               ) -> jnp.ndarray:
    """psum_scatter along the sequence dim — the RowParallel output path
    under SP (replaces the plain psum: each rank keeps only its sequence
    shard of the reduced activations)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=seq_axis,
                                tiled=True)
