"""Megatron-style model-parallel toolkit on a TPU mesh.

Reference export list: ``reference:apex/transformer/__init__.py:1-23``.
"""

from apex_tpu.transformer import amp  # noqa: F401
from apex_tpu.transformer import context_parallel  # noqa: F401
from apex_tpu.transformer import expert_parallel  # noqa: F401
from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import pipeline_parallel  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType, AttnType, LayerType, ModelType)
from apex_tpu.ops.fused_softmax import FusedScaleMaskSoftmax  # noqa: F401

# `functional` namespace parity (reference:apex/transformer/functional)
from apex_tpu.ops import fused_softmax as functional  # noqa: F401

__all__ = [
    "amp", "context_parallel", "expert_parallel", "functional",
    "parallel_state", "pipeline_parallel",
    "tensor_parallel", "AttnMaskType", "AttnType", "LayerType", "ModelType",
    "FusedScaleMaskSoftmax",
]
