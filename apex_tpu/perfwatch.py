"""``python -m apex_tpu.perfwatch`` — the performance-observatory CLI.

Thin executable shim over :mod:`apex_tpu.observability.perfwatch` (the
library lives with the other observability layers; the CLI rides at
package level like ``python -m apex_tpu.analysis``). Exit status: 0
clean, 1 regressions / drift shifts / dead selfcheck, 2 usage error.
"""

from apex_tpu.observability.perfwatch import main

if __name__ == "__main__":
    raise SystemExit(main())
