"""Data-parallel layer (``reference:apex/parallel/__init__.py``).

- :class:`DistributedDataParallel` / :func:`allreduce_grads` — grad psum with
  apex DDP's numeric options (predivide, fp32-allreduce, averaging).
- :class:`SyncBatchNorm` / :func:`sync_batch_norm` — cross-device BN.
- :func:`convert_syncbn_model` — BN→SyncBN conversion for this package's
  module objects (the reference's recursive torch-module surgery,
  ``reference:apex/parallel/__init__.py:21-56``).
- :func:`create_syncbn_process_group` — BN groups of size N as psum
  ``axis_index_groups`` (``reference:apex/parallel/__init__.py:58+``).
- :class:`LARC` re-export (lives with the optimizers;
  ``reference:apex/parallel/LARC.py``).
"""

from typing import List, Optional, Sequence

from apex_tpu.optimizers.larc import LARC  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel, Reducer, allreduce_grads)
from apex_tpu.parallel.spatial import (  # noqa: F401
    halo_exchange, spatial_conv2d)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    BatchNormState, SyncBatchNorm, sync_batch_norm)

__all__ = [
    "DistributedDataParallel", "Reducer", "allreduce_grads",
    "SyncBatchNorm", "BatchNormState", "sync_batch_norm",
    "convert_syncbn_model", "create_syncbn_process_group", "LARC",
]


def convert_syncbn_model(module, axis_name: str = "data",
                         axis_index_groups=None):
    """Recursively rebuild a module tree, replacing any
    :class:`~apex_tpu.parallel.sync_batchnorm.SyncBatchNorm` configured
    without a mesh axis (i.e. plain local BN) with a synced one
    (``reference:apex/parallel/__init__.py:21-56``). Works on this package's
    module objects and plain containers of them; other objects pass through.
    """
    if isinstance(module, SyncBatchNorm):
        if module.axis_name is not None:
            return module  # already synced; keep its axis/groups config
        return SyncBatchNorm(
            module.num_features, eps=module.eps, momentum=module.momentum,
            affine=module.affine,
            track_running_stats=module.track_running_stats,
            axis_name=axis_name, axis_index_groups=axis_index_groups,
            channel_axis=module.channel_axis, fuse_relu=module.fuse_relu,
            param_dtype=module.param_dtype)
    if isinstance(module, (list, tuple)):
        return type(module)(
            convert_syncbn_model(m, axis_name, axis_index_groups)
            for m in module)
    if isinstance(module, dict):
        return {k: convert_syncbn_model(v, axis_name, axis_index_groups)
                for k, v in module.items()}
    # generic object: rewrite attributes that are BN/containers in place
    if hasattr(module, "__dict__"):
        for k, v in vars(module).items():
            if isinstance(v, (SyncBatchNorm, list, tuple, dict)):
                setattr(module, k,
                        convert_syncbn_model(v, axis_name, axis_index_groups))
    return module


def create_syncbn_process_group(group_size: int,
                                world_size: Optional[int] = None
                                ) -> List[List[int]]:
    """Partition ``world_size`` devices into BN groups of ``group_size`` —
    returned as ``axis_index_groups`` for psum. ``group_size=0`` means one
    global group (None semantics)."""
    import jax

    if world_size is None:
        world_size = jax.device_count()
    if group_size == 0:
        return [list(range(world_size))]
    if world_size % group_size != 0:
        raise ValueError(
            f"world_size {world_size} not divisible by group_size {group_size}")
    return [list(range(i, i + group_size))
            for i in range(0, world_size, group_size)]
