"""Data-parallel gradient synchronization.

Reference: ``reference:apex/parallel/distributed.py:129-639`` — a
gradient-hook-driven bucketed NCCL allreduce with comm/compute overlap,
flatten/unflatten copies, predivide factors, and optional fp32 allreduce.

On TPU the *mechanism* disappears: grads live in a jitted step function, the
sync is one ``psum`` per grad tree over the ``data`` mesh axis, and XLA's
latency-hiding scheduler overlaps the collectives with the backward pass
(the hand-built bucket/stream machinery of ``distributed.py:319-556`` is the
compiler's job). What remains semantic — and is kept here — is the numeric
policy: ``gradient_predivide_factor`` (``distributed.py:445-454``: grads are
scaled by ``1/predivide`` before the reduce and ``predivide/world_size``
after, trading overflow headroom in half precision),
``allreduce_always_fp32`` (:168, cast half grads up for the reduce), and
``gradient_average`` (divide by world size or not).

Use inside ``shard_map``/``pmap`` with a named axis, or under jit with
sharding constraints where XLA inserts the psum itself.

**Bucketing** (``bucket_bytes=...``): one psum per grad leaf is the right
default for a handful of large tensors, but a transformer's ~10²–10³ leaves
become that many small latency-bound collectives, while one monolithic
flat psum serializes the whole window behind a single full-tree transfer.
The bucketed path is the reference's bucketed allreduce
(``distributed.py:319-556``; Li et al., VLDB 2021) restated for XLA: grads
are raveled into one flat fp32 vector (the
:mod:`apex_tpu.optimizers._flatten` layout) and reduced in B fixed-size
buckets — B *independent* collectives whose transfers XLA's latency-hiding
scheduler can overlap with each other's scale/unravel epilogues and with
any step work that doesn't consume the synced grads (the loss-scale
update, the local finite-check). The ZeRO optimizers
(:mod:`apex_tpu.optimizers.distributed_fused`) reduce-scatter and
all-gather over the same bucket grid, so bucket k's gather rides under
bucket k+1's update math. This module is also the package's raw
``lax.psum_scatter`` chokepoint (:func:`reduce_scatter_grads`) —
``scripts/check_collectives.py`` flags grad-sync collectives anywhere
else, so future code cannot bypass the bucketing engine.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.observability import health as _health
from apex_tpu.observability import ingraph as _metrics
from apex_tpu.utils.vma import cast_to_vma
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["allreduce_grads", "DistributedDataParallel", "Reducer",
           "grouped_psum", "reduce_scatter_grads", "DEFAULT_BUCKET_BYTES"]

# ~4 MiB per bucket: large enough that per-collective latency amortizes,
# small enough that several buckets are in flight per window (torch-DDP's
# default is 25 MB for NCCL ring allreduce; ICI latencies are lower, so a
# smaller default keeps more overlap opportunity — see docs/PERF.md
# "DP overlap + ZeRO" for the sizing methodology)
DEFAULT_BUCKET_BYTES = 4 << 20


def reduce_scatter_grads(flat: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Tiled fp32 reduce-scatter of a flat grad (bucket) over ``axis_name``
    — each rank receives the *summed* ``1/axis_size`` slice it owns. The
    package's single raw ``lax.psum_scatter`` grad-sync site: ZeRO's
    :meth:`~apex_tpu.optimizers.distributed_fused._DistributedFusedBase.
    _shard_grads` routes here per bucket (``reference:apex/contrib/
    optimizers/distributed_fused_adam.py:409``), and
    ``scripts/check_collectives.py`` flags raw ``psum_scatter`` call sites
    anywhere outside this module (sequence-dim *activation* scatters are
    separately allowlisted there)."""
    return jax.lax.psum_scatter(
        cast_to_vma(flat, frozenset({axis_name})), axis_name,
        scatter_dimension=0, tiled=True)


def grouped_psum(x: jnp.ndarray, axis_name: str,
                 axis_index_groups: Optional[Sequence[Sequence[int]]] = None
                 ) -> jnp.ndarray:
    """``psum`` restricted to device subgroups — the analog of NCCL
    subgroup ``new_group`` communicators
    (``reference:apex/parallel/__init__.py:58+``).

    Resolution order (all paths differentiable, so BN/DDP backward through
    groups works):

    1. native ``psum(axis_index_groups=...)`` — currently raises
       ``NotImplementedError`` inside ``shard_map``; tried first so a
       future JAX picks it up for free;
    2. contiguous equal-size groups (how ``create_syncbn_process_group``
       carves them): ``all_gather`` + a dynamic slice of this rank's group
       + sum — O(world) traffic, O(group) compute;
    3. arbitrary groups: ``all_gather`` + membership-mask contraction —
       O(world) traffic, O(world²·|x|/world) compute; fine for the small
       stat vectors this is used on, wasteful for large tensors at large
       world sizes (documented limitation).
    """
    if axis_index_groups is None:
        return jax.lax.psum(x, axis_name)
    groups = [list(g) for g in axis_index_groups]
    try:
        return jax.lax.psum(x, axis_name, axis_index_groups=groups)
    except NotImplementedError:
        pass
    world = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    from apex_tpu.utils.vma import varying_all_gather
    gathered = varying_all_gather(x, axis_name, tiled=False)  # (world, ...)

    sizes = {len(g) for g in groups}
    contiguous_equal = (
        len(sizes) == 1
        and sorted(i for g in groups for i in g) == list(range(world))
        and all(g == list(range(g[0], g[0] + len(g))) for g in groups))
    if contiguous_equal:
        gsize = sizes.pop()
        start = (rank // gsize) * gsize
        mine = jax.lax.dynamic_slice_in_dim(gathered, start, gsize, axis=0)
        return jnp.sum(mine.astype(jnp.float32), axis=0).astype(x.dtype)

    mask = np.zeros((world, world), np.float32)
    for g in groups:
        for i in g:
            for j in g:
                mask[i, j] = 1.0
    row = jnp.asarray(mask)[rank]
    return jnp.tensordot(row, gathered.astype(jnp.float32),
                         axes=1).astype(x.dtype)


def _group_size_for_rank(axis_name: str, groups) -> jnp.ndarray:
    """Traced size of the group containing this rank — groups may be uneven,
    so averaging must use each rank's own group size."""
    world = _axis_size(axis_name)
    sizes = np.zeros((world,), np.float32)
    for g in groups:
        for i in g:
            sizes[i] = len(g)
    return jnp.asarray(sizes)[jax.lax.axis_index(axis_name)]


def _bucketed_allreduce(grads: Any, axis_name: str,
                        gradient_predivide_factor: float,
                        gradient_average: bool, bucket_bytes: int) -> Any:
    """The bucketing engine: ravel the grad tree into B fixed-size flat
    fp32 buckets and psum each (independent collectives XLA can overlap),
    scale per bucket, unravel. Always reduces in fp32 — the ravel *is*
    the fp32 master-grad copy, so ``allreduce_always_fp32`` is implied on
    this path (same numeric contract as the ZeRO reduce-scatter).

    Span-local assembly (``_flatten.ravel_span``/``unravel_parts``): each
    bucket's psum consumes only the grad leaves in its span — not a
    full-tree concatenate — so the scheduler can issue bucket k's
    transfer while the backward is still producing later buckets' grads,
    and each synced leaf is rebuilt from only the buckets covering it.
    The full padded flat vector never materializes (asserted on the
    jaxpr in tests)."""
    from apex_tpu.optimizers._flatten import (bucket_bounds, build_layout,
                                              ravel_span, unravel_parts)
    lay = build_layout(grads, chunks=1)
    bounds = bucket_bounds(lay, bucket_bytes)
    world = _axis_size(axis_name)
    pre = gradient_predivide_factor

    if _metrics.recording():
        _metrics.record("ddp/allreduce_bytes", float(4 * lay.total),
                        reduce="sum")
        _metrics.record("ddp/num_buckets", float(len(bounds)), reduce="mean")
        _metrics.record("ddp/bucket_bytes",
                        float(4 * max(n for _, n in bounds)), reduce="mean")

    if gradient_average:
        post = pre / world
    else:
        post = pre if pre != 1.0 else None

    with jax.named_scope("apex_ddp_bucketed_allreduce"):
        pieces = []
        for off, n in bounds:
            # one psum per bucket, assembled span-locally: this bucket's
            # transfer depends only on the grads in its span, and the
            # pre/post scales are per-bucket epilogue work the scheduler
            # can run under the next bucket's transfer
            b = ravel_span(grads, lay, off, n)
            if pre != 1.0:
                b = b / pre
            b = jax.lax.psum(
                cast_to_vma(b, frozenset({axis_name})), axis_name)
            if post is not None:
                b = b * post
            pieces.append(b)
    synced = unravel_parts(pieces, bounds, lay)
    _health.observe_replica_agreement(synced, axis_name, name="ddp_grads")
    return synced


def allreduce_grads(grads: Any, axis_name: str = "data",
                    gradient_predivide_factor: float = 1.0,
                    allreduce_always_fp32: bool = False,
                    gradient_average: bool = True,
                    axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
                    bucket_bytes: Optional[int] = None
                    ) -> Any:
    """psum a grad pytree over ``axis_name`` with apex DDP's numeric options.

    Must be called inside a context where ``axis_name`` is bound
    (``shard_map``, ``pmap``, ...). ``axis_index_groups`` restricts the
    reduction to subgroups — the analog of passing a ``process_group``
    (``reference:apex/parallel/__init__.py:58+``).

    ``bucket_bytes`` switches to the bucketed engine (module docstring):
    the tree is reduced as B flat fp32 buckets instead of one psum per
    leaf — identical numerics to ``allreduce_always_fp32=True`` up to the
    reduction's reassociation, with B independent collectives for XLA's
    scheduler to overlap. ``None`` (default) keeps the per-leaf path
    byte-identical to the pre-bucketing library. Incompatible with
    ``axis_index_groups`` (subgroup reduces stay per-leaf).
    """
    if bucket_bytes is not None:
        if axis_index_groups is not None:
            raise ValueError(
                "bucket_bytes and axis_index_groups are mutually exclusive: "
                "the bucketed engine reduces over the full axis")
        return _bucketed_allreduce(grads, axis_name,
                                   gradient_predivide_factor,
                                   gradient_average, bucket_bytes)
    if axis_index_groups is not None:
        world = _group_size_for_rank(axis_name, axis_index_groups)
    else:
        world = _axis_size(axis_name)
    pre = gradient_predivide_factor

    if _metrics.recording():
        # shapes/dtypes are static, so the reduced traffic is a trace-time
        # constant: this rank's contribution per sync (DDP "bucket" = one
        # leaf = one psum; XLA may coalesce, this counts the semantic view)
        leaves = [jnp.asarray(g) for g in jax.tree_util.tree_leaves(grads)]
        nbytes = sum(
            l.size * (4 if allreduce_always_fp32 else l.dtype.itemsize)
            for l in leaves)
        _metrics.record("ddp/allreduce_bytes", float(nbytes), reduce="sum")
        _metrics.record("ddp/buckets", float(len(leaves)), reduce="mean")

    @jax.named_scope("apex_ddp_allreduce")
    def _sync(g):
        g = jnp.asarray(g)
        orig_dtype = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if pre != 1.0:
            g = g / pre
        g = grouped_psum(g, axis_name, axis_index_groups)
        if gradient_average:
            g = g * (pre / world)
        elif pre != 1.0:
            g = g * pre
        return g.astype(orig_dtype)

    synced = jax.tree_util.tree_map(_sync, grads)
    if axis_index_groups is None:
        # full-level watchdog: post-allreduce grads are replicated by
        # construction, so any cross-replica divergence here is silent
        # corruption (bad collective, bitflip, nondeterministic op) — a
        # trace-time-gated no-op below level="full". Subgroup reduces are
        # exempt: their results legitimately differ across groups.
        _health.observe_replica_agreement(synced, axis_name,
                                          name="ddp_grads")
    return synced


class DistributedDataParallel:
    """Functional DDP: holds the sync policy, applies it to grad trees.

    The ctor keeps the reference's argument names (``distributed.py:162-175``)
    where they still mean something; stream arguments
    (``num_allreduce_streams``, ...) are accepted and ignored — stream
    scheduling is XLA's concern. Bucketing, however, is *real* again:
    ``bucket_bytes`` (the role of the reference's ``message_size``,
    ``distributed.py:165``, restated in bytes) routes :meth:`sync_gradients`
    through the bucketed flat-fp32 engine (module docstring) so the window's
    sync is B overlappable collectives instead of one psum per leaf.

    ``delay_allreduce=True`` is real (torch-DDP ``no_sync`` semantics, the
    closest analog of the reference flag at ``distributed.py:162``):
    :meth:`value_and_grad` then returns *unsynced* per-replica grads so a
    gradient-accumulation loop can sum K microbatches locally and fire
    :meth:`sync_gradients` once per window — see
    :func:`apex_tpu.training.accumulate_gradients`, which packages that
    loop (and whose jaxpr carries exactly one psum per window, asserted in
    tests).
    """

    def __init__(self, axis_name: str = "data",
                 gradient_predivide_factor: float = 1.0,
                 allreduce_always_fp32: bool = False,
                 gradient_average: bool = True,
                 axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
                 delay_allreduce: bool = False,
                 bucket_bytes: Optional[int] = None,
                 **_ignored_stream_args):
        if axis_index_groups is not None and bucket_bytes is not None:
            # same contract as allreduce_grads/Reducer, failed at the
            # misconfiguration site instead of deep inside a later trace
            raise ValueError(
                "bucket_bytes and axis_index_groups are mutually exclusive: "
                "the bucketed engine reduces over the full axis")
        self.axis_name = axis_name
        self.gradient_predivide_factor = gradient_predivide_factor
        self.allreduce_always_fp32 = allreduce_always_fp32
        self.gradient_average = gradient_average
        self.axis_index_groups = axis_index_groups
        self.delay_allreduce = delay_allreduce
        self.bucket_bytes = bucket_bytes

    def sync_gradients(self, grads: Any) -> Any:
        return allreduce_grads(
            grads, self.axis_name, self.gradient_predivide_factor,
            self.allreduce_always_fp32, self.gradient_average,
            self.axis_index_groups, bucket_bytes=self.bucket_bytes)

    def value_and_grad(self, loss_fn, **vag_kwargs):
        """``jax.value_and_grad`` whose grads come back already synced —
        the "wrap your model and backward just works" usage shape of apex DDP.

        The first argument (params) is marked device-varying
        (``lax.pcast(..., to='varying')``) before differentiation: each device differentiates
        its own replica and the sync is this class's explicit allreduce —
        exactly torch-DDP's model. (Without this, shard_map's AD would
        auto-``psum`` cotangents of replicated params and an explicit sync
        would double-count.)

        With ``delay_allreduce=True`` the grads come back UNSYNCED (still
        per-replica, ``no_sync`` semantics) — the caller owns firing
        :meth:`sync_gradients` once per accumulation window.
        """
        def wrapped(params, *args, **kwargs):
            params = jax.tree_util.tree_map(
                lambda p: cast_to_vma(p, frozenset({self.axis_name})), params)
            value, grads = jax.value_and_grad(loss_fn, **vag_kwargs)(
                params, *args, **kwargs)
            if self.delay_allreduce:
                return value, grads
            return value, self.sync_gradients(grads)

        return wrapped


class Reducer:
    """Manual full-reduction helper (``reference:apex/parallel/distributed.py:89-126``):
    no hooks, user calls ``reduce`` explicitly on params or grads; values are
    allreduce-averaged. ``bucket_bytes`` runs the mean through the bucketed
    flat-fp32 engine (B overlappable psums) instead of one pmean per leaf —
    mutually exclusive with ``axis_index_groups`` (the ctor raises, same
    contract as :func:`allreduce_grads`)."""

    def __init__(self, axis_name: str = "data",
                 axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
                 bucket_bytes: Optional[int] = None):
        if axis_index_groups is not None and bucket_bytes is not None:
            raise ValueError(
                "bucket_bytes and axis_index_groups are mutually exclusive: "
                "the bucketed engine reduces over the full axis")
        self.axis_name = axis_name
        self.axis_index_groups = axis_index_groups
        self.bucket_bytes = bucket_bytes

    def reduce(self, tree: Any) -> Any:
        if self.axis_index_groups is not None:
            world = _group_size_for_rank(self.axis_name,
                                         self.axis_index_groups)
            return jax.tree_util.tree_map(
                lambda x: grouped_psum(x, self.axis_name,
                                       self.axis_index_groups) / world,
                tree)
        if self.bucket_bytes is not None:
            return _bucketed_allreduce(tree, self.axis_name, 1.0, True,
                                       self.bucket_bytes)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.pmean(x, self.axis_name), tree)
