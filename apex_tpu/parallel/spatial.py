"""Spatial parallelism: halo exchange + spatially-sharded convolution.

Reference: ``reference:apex/contrib/bottleneck/bottleneck.py`` —
``SpatialBottleneck`` shards the image height across GPUs and exchanges
1-row halos over NCCL so the 3x3 convs see their neighbors' boundary rows
(the ``halo_exchange`` modes in ``bottleneck.py``; peer memory fast paths
in ``apex/contrib/csrc/peer_memory``).

TPU redesign: the halo transfer is a pair of ``ppermute`` neighbor shifts
(the ideal ICI pattern — exactly what the reference emulates with CUDA
peer-to-peer copies), and the boundary ranks substitute zero padding so
the sharded convolution reproduces a dense SAME conv bit-for-bit. Works
under AD: the transpose of a shift is the opposite shift, so halo
gradients flow back to their owners.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["halo_exchange", "spatial_conv2d"]


def halo_exchange(x: jnp.ndarray, axis_name: str, halo: int = 1,
                  spatial_axis: int = 1,
                  halo_top: Optional[int] = None,
                  halo_bottom: Optional[int] = None) -> jnp.ndarray:
    """Concatenate halo rows from the previous/next rank around this
    rank's shard (NHWC, height sharded by default). Boundary ranks get
    zeros — the SAME-padding rows of the equivalent dense conv.

    ``halo`` sets both sides; ``halo_top``/``halo_bottom`` override
    individually (strided SAME convs pad asymmetrically)."""
    ht = halo if halo_top is None else halo_top
    hb = halo if halo_bottom is None else halo_bottom
    cp = _axis_size(axis_name)
    rank = jax.lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % cp) for i in range(cp)]
    bwd = [(i, (i - 1) % cp) for i in range(cp)]

    parts = []
    if ht:
        bottom = jax.lax.slice_in_dim(
            x, x.shape[spatial_axis] - ht, x.shape[spatial_axis],
            axis=spatial_axis)
        from_prev = jax.lax.ppermute(bottom, axis_name, fwd)
        parts.append(jnp.where(rank == 0, jnp.zeros_like(from_prev),
                               from_prev))
    parts.append(x)
    if hb:
        top = jax.lax.slice_in_dim(x, 0, hb, axis=spatial_axis)
        from_next = jax.lax.ppermute(top, axis_name, bwd)
        parts.append(jnp.where(rank == cp - 1, jnp.zeros_like(from_next),
                               from_next))
    if len(parts) == 1:
        return x
    return jnp.concatenate(parts, axis=spatial_axis)


def spatial_conv2d(x: jnp.ndarray, w: jnp.ndarray, axis_name: str,
                   stride: int = 1) -> jnp.ndarray:
    """SAME 2D conv over an NHWC input whose HEIGHT is sharded on
    ``axis_name`` — each rank convolves its shard plus exchanged halos and
    the result equals the dense conv's corresponding height slice.

    Odd kernel sizes, ``kh > stride``, and ``stride`` must divide the
    local shard height (the reference's spatial bottleneck has the same
    alignment requirements for its strided convs). SAME with stride pads
    ``k - stride`` rows total when the size divides the stride, split
    low-first like XLA: top halo ``(k - stride) // 2``, bottom the rest.
    """
    kh, kw = w.shape[0], w.shape[1]
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError("spatial_conv2d requires odd kernel sizes")
    if x.shape[1] % stride:
        raise ValueError("stride must divide the local shard height")
    if kh <= stride:
        raise ValueError("kernel height must exceed stride")
    pad_h = kh - stride
    ht, hb = pad_h // 2, pad_h - pad_h // 2
    x = halo_exchange(x, axis_name, spatial_axis=1, halo_top=ht,
                      halo_bottom=hb)
    # height carries the SAME padding via halos/zeros; width pads locally
    # with the SAME formula (asymmetric under stride, low-first like XLA)
    W = x.shape[2]
    out_w = -(-W // stride)
    pad_w = max((out_w - 1) * stride + kw - W, 0)
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride),
        [(0, 0), (pad_w // 2, pad_w - pad_w // 2)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
