"""SyncBatchNorm — cross-device batch normalization via ``psum``.

Reference: two implementations with identical semantics —
``reference:apex/parallel/optimized_sync_batchnorm_kernel.py:10-119`` (CUDA
Welford local stats → allgather → ``welford_parallel`` count-weighted merge →
normalize; backward allreduces ``(sum_dy, sum_dy_xmu)``) and the pure-Python
fallback ``reference:apex/parallel/sync_batchnorm_kernel.py:7-119``.

TPU version: local ``(sum, sum_sq, count)`` + one ``psum`` gives the same
count-weighted global mean/var (mathematically identical to the parallel
Welford merge of ``welford.cu:569``, including uneven per-rank batches —
``tests/distributed/synced_batchnorm/two_gpu_test_different_batch_size.py``);
the backward collective falls out of AD: the transpose of ``psum`` reproduces
exactly the ``allreduce(sum_dy, sum_dy_xmu)`` of the reference backward.
The fused ReLU + residual-add options of the optimized kernel
(``syncbn.welford_mean_var`` + ``relu_backward_c_last``,
``optimized_sync_batchnorm.py:9``'s ``fuse_relu``/``z``) are the ``fuse_relu``
and ``z`` arguments; channels-last layouts are an XLA concern and need no API.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["BatchNormState", "SyncBatchNorm", "sync_batch_norm"]


class BatchNormState(NamedTuple):
    """Running stats (fp32), updated functionally each training call."""
    running_mean: jnp.ndarray
    running_var: jnp.ndarray
    num_batches_tracked: jnp.ndarray


def _reduce_axes(x: jnp.ndarray, channel_axis: int) -> Tuple[int, ...]:
    return tuple(i for i in range(x.ndim) if i != channel_axis % x.ndim)


def _prod(xs) -> int:
    p = 1
    for v in xs:
        p *= int(v)
    return p


def sync_batch_norm(
    x: jnp.ndarray,
    weight: Optional[jnp.ndarray],
    bias: Optional[jnp.ndarray],
    state: BatchNormState,
    *,
    training: bool = True,
    momentum: float = 0.1,
    eps: float = 1e-5,
    channel_axis: int = 1,
    axis_name: Optional[str] = None,
    axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
    z: Optional[jnp.ndarray] = None,
    fuse_relu: bool = False,
    apply_dtype: Optional[Any] = None,
) -> Tuple[jnp.ndarray, BatchNormState]:
    """Returns ``(out, new_state)``.

    ``channel_axis=1`` matches torch NCHW; pass ``-1`` for NHWC. When
    ``axis_name`` is None this is ordinary BN (the single-process fallback of
    ``optimized_sync_batchnorm.py:70``). ``z`` is the pre-activation residual
    added before the optional fused ReLU.

    ``apply_dtype``: dtype of the per-element normalize/scale/shift (and its
    backward). Default ``None`` = fp32, the reference ``keep_batchnorm_fp32``
    semantics. Pass the input dtype (e.g. bf16) to fold the normalization
    into a per-channel ``x * a + b`` computed from fp32 statistics — the
    statistics reductions stay fp32, only the O(N*H*W) apply runs at input
    precision. On an HBM-bound step this halves the BN apply/backward
    traffic; bf16 shares fp32's exponent range, so the fp16-era divergence
    risk ``keep_batchnorm_fp32`` guards against does not apply.
    """
    c_ax = channel_axis % x.ndim
    red = _reduce_axes(x, c_ax)
    xf = x.astype(jnp.float32)
    stat_shape = [1] * x.ndim
    stat_shape[c_ax] = x.shape[c_ax]

    if training:
        # local partial sums; one psum merges count-weighted across devices
        with jax.named_scope("sync_bn_stats"):
            local_count = jnp.asarray(
                _prod(x.shape[i] for i in red), jnp.float32)
            s1 = jnp.sum(xf, axis=red)
            s2 = jnp.sum(xf * xf, axis=red)
            if axis_name is not None:
                from apex_tpu.parallel.distributed import grouped_psum
                s1 = grouped_psum(s1, axis_name, axis_index_groups)
                s2 = grouped_psum(s2, axis_name, axis_index_groups)
                count = grouped_psum(local_count, axis_name,
                                     axis_index_groups)
            else:
                count = local_count
        mean = s1 / count
        var = s2 / count - mean * mean  # biased, used for normalization
        unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
        new_state = BatchNormState(
            running_mean=(1 - momentum) * state.running_mean + momentum * mean,
            running_var=(1 - momentum) * state.running_var + momentum * unbiased,
            num_batches_tracked=state.num_batches_tracked + 1)
    else:
        mean, var = state.running_mean, state.running_var
        new_state = state

    inv = jax.lax.rsqrt(var + eps)
    if apply_dtype is not None and jnp.dtype(apply_dtype) != jnp.float32:
        # per-channel affine folded in fp32, applied at input precision
        a = inv if weight is None else inv * weight.astype(jnp.float32)
        b = -mean * a
        if bias is not None:
            b = b + bias.astype(jnp.float32)
        a = a.astype(apply_dtype).reshape(stat_shape)
        b = b.astype(apply_dtype).reshape(stat_shape)
        out = x.astype(apply_dtype) * a + b
        if z is not None:
            out = out + z.astype(apply_dtype)
        if fuse_relu:
            out = jax.nn.relu(out)
        return out.astype(x.dtype), new_state
    out = (xf - mean.reshape(stat_shape)) * inv.reshape(stat_shape)
    if weight is not None:
        out = out * weight.astype(jnp.float32).reshape(stat_shape)
    if bias is not None:
        out = out + bias.astype(jnp.float32).reshape(stat_shape)
    if z is not None:
        out = out + z.astype(jnp.float32)
    if fuse_relu:
        out = jax.nn.relu(out)
    return out.astype(x.dtype), new_state


class SyncBatchNorm:
    """``apex.parallel.SyncBatchNorm`` (``optimized_sync_batchnorm.py:9``) as a
    param/state factory. ``process_group`` becomes ``axis_index_groups``."""

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 axis_name: Optional[str] = None,
                 axis_index_groups: Optional[Sequence[Sequence[int]]] = None,
                 channel_axis: int = 1, fuse_relu: bool = False,
                 param_dtype=jnp.float32):
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.axis_name = axis_name
        self.axis_index_groups = axis_index_groups
        self.channel_axis = channel_axis
        self.fuse_relu = fuse_relu
        self.param_dtype = param_dtype

    def init(self) -> Tuple[dict, BatchNormState]:
        params = {}
        if self.affine:
            params = {"weight": jnp.ones(self.num_features, self.param_dtype),
                      "bias": jnp.zeros(self.num_features, self.param_dtype)}
        state = BatchNormState(
            running_mean=jnp.zeros(self.num_features, jnp.float32),
            running_var=jnp.ones(self.num_features, jnp.float32),
            num_batches_tracked=jnp.asarray(0, jnp.int32))
        return params, state

    def __call__(self, params: dict, state: BatchNormState, x: jnp.ndarray,
                 training: bool = True, z: Optional[jnp.ndarray] = None
                 ) -> Tuple[jnp.ndarray, BatchNormState]:
        # track_running_stats=False: always normalize with batch stats and
        # never touch running state (torch/reference semantics,
        # optimized_sync_batchnorm.py:58-74)
        use_batch_stats = training or not self.track_running_stats
        out, new_state = sync_batch_norm(
            x, params.get("weight"), params.get("bias"), state,
            training=use_batch_stats,
            momentum=self.momentum, eps=self.eps,
            channel_axis=self.channel_axis, axis_name=self.axis_name,
            axis_index_groups=self.axis_index_groups, z=z,
            fuse_relu=self.fuse_relu)
        if not self.track_running_stats:
            new_state = state
        return out, new_state
