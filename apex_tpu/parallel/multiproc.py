"""Multi-process (multi-host) runtime bootstrap — the real launcher.

The reference's ``multiproc.py`` (``reference:apex/parallel/multiproc.py:
5-35``) spawns ``world_size`` local processes with ``--rank i`` args — the
pre-``torchrun`` convenience launcher. This module is its TPU-shaped
graduation from documented stub to real implementation (ROADMAP item 3):
the **worker half** of multi-host bootstrap. One Python process per host
drives all of that host's devices; processes rendezvous through
``jax.distributed.initialize(coordinator_address, num_processes,
process_id)``, after which ``jax.devices()`` is the *global* device list
and one SPMD program spans every host. The **supervisor half** (process
spawning, heartbeats, restart/shrink policy) lives in
:mod:`apex_tpu.elastic.launch`; this module owns the per-process
environment protocol the two halves speak:

======================  =====================================================
env var                 meaning
======================  =====================================================
APEX_TPU_COORDINATOR    ``host:port`` of the rendezvous coordinator
                        (process 0 starts the service on it)
APEX_TPU_NUM_PROCESSES  world size (process count)
APEX_TPU_PROCESS_ID     this process's rank in ``[0, num_processes)``
APEX_TPU_LOCAL_DEVICES  virtual CPU devices to force per process (localhost
                        simulation; unset/0 = use the real local devices)
APEX_TPU_RUN_DIR        scratch dir shared with the supervisor (heartbeats)
======================  =====================================================

On CPU the cross-process collectives run over the **gloo** transport
(``jax_cpu_collectives_implementation``) — a localhost 2-process x
4-virtual-device mesh exercises the exact multi-controller code paths
(global meshes, collective checkpointing, cross-host psums) a TPU pod
runs, with DCN replaced by loopback TCP. On real Cloud TPU slices the
coordinator/rank values come from the platform and
``jax.distributed.initialize()`` discovers them; the env protocol here is
only needed when a supervisor owns placement.

Order matters: :func:`initialize` (or :func:`initialize_from_env`) must
run before *any* JAX backend use in the process — it forces the virtual
device count and the collectives transport, both of which are sealed at
backend initialization.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional

__all__ = ["ProcessInfo", "initialize", "initialize_from_env",
           "process_env", "process_id", "process_count", "any_process",
           "main",
           "ENV_COORDINATOR", "ENV_NUM_PROCESSES", "ENV_PROCESS_ID",
           "ENV_LOCAL_DEVICES", "ENV_RUN_DIR"]

ENV_COORDINATOR = "APEX_TPU_COORDINATOR"
ENV_NUM_PROCESSES = "APEX_TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "APEX_TPU_PROCESS_ID"
ENV_LOCAL_DEVICES = "APEX_TPU_LOCAL_DEVICES"
ENV_RUN_DIR = "APEX_TPU_RUN_DIR"

_INFO: Optional["ProcessInfo"] = None


@dataclasses.dataclass(frozen=True)
class ProcessInfo:
    """What :func:`initialize` established for this process."""

    process_id: int
    num_processes: int
    coordinator_address: Optional[str]
    local_devices: Optional[int]
    run_dir: Optional[str]


def process_env(process_id: int, num_processes: int,
                coordinator_address: str, *,
                local_devices: Optional[int] = None,
                run_dir: Optional[str] = None) -> Dict[str, str]:
    """The env-var block a supervisor hands worker ``process_id`` — the
    other half of :func:`initialize_from_env`."""
    if not 0 <= process_id < num_processes:
        raise ValueError(f"bad rank {process_id}/{num_processes}")
    env = {ENV_COORDINATOR: str(coordinator_address),
           ENV_NUM_PROCESSES: str(int(num_processes)),
           ENV_PROCESS_ID: str(int(process_id))}
    if local_devices:
        env[ENV_LOCAL_DEVICES] = str(int(local_devices))
    if run_dir:
        env[ENV_RUN_DIR] = str(run_dir)
    return env


def initialize(coordinator_address: Optional[str], num_processes: int,
               process_id: int, *, local_devices: Optional[int] = None,
               run_dir: Optional[str] = None) -> ProcessInfo:
    """Bootstrap this process into an ``num_processes``-wide world.

    Must run before any JAX backend use. Steps, in the only order that
    works:

    1. ``local_devices`` set → force that many virtual CPU devices
       (:func:`~apex_tpu.utils.hostmesh.force_virtual_cpu_devices` —
       XLA_FLAGS must be written before the backend exists);
    2. select the ``gloo`` CPU collectives transport (cross-process CPU
       collectives are disabled by default; sealed at backend init);
    3. ``jax.distributed.initialize(...)`` — skipped at
       ``num_processes == 1`` (a single-process world needs no
       coordinator; ``jax.process_count()`` is already 1).

    Returns (and caches) a :class:`ProcessInfo`; :func:`process_id` /
    :func:`process_count` read the cache without touching the backend.
    """
    global _INFO
    if not 0 <= process_id < num_processes:
        raise ValueError(f"bad rank {process_id}/{num_processes}")
    if num_processes > 1 and not coordinator_address:
        raise ValueError(
            "a multi-process world needs a coordinator_address "
            "(host:port; process 0 starts the service on it)")
    if local_devices:
        from apex_tpu.utils.hostmesh import force_virtual_cpu_devices
        # verify=False: the count check initializes the backend, and
        # jax.distributed.initialize refuses to run after that
        force_virtual_cpu_devices(int(local_devices), verify=False)
    import jax
    if num_processes > 1:
        try:
            # cross-process CPU collectives ride the gloo transport; the
            # flag does not exist on every jax line — leave those to the
            # backend default rather than failing the bootstrap
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=int(num_processes),
            process_id=int(process_id))
    if local_devices and jax.local_device_count() < int(local_devices):
        raise RuntimeError(
            f"asked for {local_devices} local virtual CPU devices but "
            f"the backend initialized with {jax.local_device_count()} — "
            f"the JAX backend was touched before initialize()")
    _INFO = ProcessInfo(process_id=int(process_id),
                        num_processes=int(num_processes),
                        coordinator_address=coordinator_address,
                        local_devices=(int(local_devices)
                                       if local_devices else None),
                        run_dir=run_dir)
    return _INFO


def initialize_from_env() -> Optional[ProcessInfo]:
    """Worker-side bootstrap from the supervisor's env block. Returns
    ``None`` (and does nothing) when ``APEX_TPU_COORDINATOR`` is unset —
    safe to call unconditionally at the top of a training script."""
    coord = os.environ.get(ENV_COORDINATOR)
    if not coord:
        return None
    return initialize(
        coord,
        int(os.environ.get(ENV_NUM_PROCESSES, "1")),
        int(os.environ.get(ENV_PROCESS_ID, "0")),
        local_devices=int(os.environ.get(ENV_LOCAL_DEVICES, "0")) or None,
        run_dir=os.environ.get(ENV_RUN_DIR) or None)


def process_id() -> int:
    """This process's rank: the :func:`initialize` cache, else the env
    protocol, else 0. Never touches the JAX backend (callable from fault
    hooks before jax is imported)."""
    if _INFO is not None:
        return _INFO.process_id
    return int(os.environ.get(ENV_PROCESS_ID, "0"))


def process_count() -> int:
    """World size, same resolution order as :func:`process_id`."""
    if _INFO is not None:
        return _INFO.num_processes
    return int(os.environ.get(ENV_NUM_PROCESSES, "1"))


def any_process(flag: bool) -> bool:
    """Cross-process OR of a host-side bool — the collective decision
    primitive the elastic runner's termination poll uses: if ANY process
    saw the preemption signal, every process must take the drain path at
    the SAME step, or the survivors deadlock in the next step's
    collectives while the drained rank waits in the checkpoint barrier.
    Free (no collective) in a single-process world."""
    import jax
    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils
    got = multihost_utils.process_allgather(
        np.asarray(bool(flag), np.bool_))
    return bool(np.any(got))


def main(argv=None) -> int:
    """CLI launcher: ``python -m apex_tpu.parallel.multiproc -n 2 --
    worker.py args...`` — the reference module's launcher role, now a
    strict alias of the elastic supervisor CLI
    (:func:`apex_tpu.elastic.launch.main`: heartbeats, bounded
    restart-with-backoff, world-size shrink; one argparse surface, so
    the two advertised entry points cannot drift)."""
    from apex_tpu.elastic.launch import main as _launch_main

    return _launch_main(argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
