"""Deprecated stub (SURVEY §7.7): the pre-torchrun process launcher.

The reference (``reference:apex/parallel/multiproc.py:5-35``) spawns
``world_size`` local processes with ``--rank i`` args — a pre-``torchrun``
convenience that NVIDIA itself deprecated.

On TPU the launcher role is subsumed by SPMD: one Python process per host
drives all local devices, and multi-host initialization is
``jax.distributed.initialize()`` (automatic on Cloud TPU). Parallelism is
expressed in the program (``jax.sharding.Mesh`` +
``apex_tpu.transformer.parallel_state``), not by spawning ranked
processes. Running this module prints that guidance and exits non-zero.
"""

import sys

_MSG = (
    "apex_tpu.parallel.multiproc is a documented stub: on TPU there is no "
    "per-rank process launcher. One process per host drives all local "
    "devices; call jax.distributed.initialize() for multi-host, and "
    "express DP/TP/PP over a jax.sharding.Mesh "
    "(apex_tpu.transformer.parallel_state.initialize_model_parallel)."
)


def main() -> int:
    print(_MSG, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
