"""Family-B rules: the repo's AST/text contract lints on one walker core.

Each rule here is the port of one historical ``scripts/check_*.py``
(those scripts remain as thin shims over this module), plus the
metric-family meta-lint that closes the "new family silently
undocumented" gap. Policy tables (allowlists, contract tables) live next
to their rule; the walk/report boilerplate lives once in
:mod:`apex_tpu.analysis.astlint`.

Rule -> encoded bug class (details + allowlisting in docs/ANALYSIS.md):

- ``ast-annotations`` — a refactor dropping a documented ``named_scope``
  silently rots the pyprof attribution-region vocabulary.
- ``ast-collectives`` — a raw ``lax.all_gather``/``psum_scatter``/grad
  ``psum`` bypasses the VMA shims / bucketing chokepoints.
- ``ast-metrics-doc`` — a ``record()``/``gauge()``/``counter()``/
  ``histogram()`` name in a checked family with no docs row.
- ``ast-metric-families`` — a metric name under a ``<prefix>/`` that is
  not a known family at all (the list in ``METRIC_PREFIXES`` used to be
  grown by hand per PR; this meta-lint makes forgetting it loud).
- ``ast-remat-names`` — a checkpoint-name tag literal outside the
  ``remat.CHECKPOINT_NAMES`` registry (no policy can save it).
- ``ast-elastic-exits`` — a process exit under ``apex_tpu/elastic/``
  outside the two blessed chokepoints: ``AutoResume.request_resume``
  (the runner's preemption exit) and ``launch.py::_supervisor_exit``
  (the supervisor CLI's exit-code propagation).
- ``ast-bench-configs`` — a bench-config key that no longer names a real
  config dataclass field (the leg silently falls back to defaults).
- ``ast-bench-history`` — the perfwatch JSONL schema keys drift from the
  writer's literal ``HISTORY_FIELDS`` table (a renamed key silently
  forks every future history file from every past one).
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import List, Tuple

from apex_tpu.analysis.core import Finding, Rule, register
from apex_tpu.analysis.astlint import (PACKAGE, callee_name,
                                       iter_package_trees, iter_py_files,
                                       literal_str, parse_file,
                                       tuple_literal)

__all__ = ["ANNOTATIONS", "ALLOWED_GATHER", "ALLOWED_SCATTER",
           "GRAD_SYNC_PREFIXES", "METRIC_PREFIXES", "EXEMPT_PREFIXES",
           "METRIC_CALLEES", "TAG_CALLEES", "REGISTRY_FILE", "ELASTIC_DIR",
           "CHOKEPOINT_FILE", "CHOKEPOINT_FUNC", "LAUNCH_FILE",
           "LAUNCH_CHOKEPOINT_FUNC", "CONFIG_CLASSES",
           "SECTIONS", "SLO_METRICS", "DOC", "PERFWATCH_FILE",
           "HISTORY_TABLE", "HISTORY_WRITER", "HISTORY_JSONL",
           "rule_annotations", "rule_collectives",
           "rule_metrics_doc", "rule_metric_families", "rule_remat_names",
           "rule_elastic_exits", "rule_bench_configs",
           "rule_bench_history"]

Findings = Tuple[List[Finding], List[str]]


def _p(*parts: str) -> str:
    return os.path.join(*parts)


# ---------------------------------------------------------------------------
# ast-annotations: the named_scope contract table
# ---------------------------------------------------------------------------

# annotation -> source files allowed to carry it (repo-relative). The
# contract is "exists in at least one of its owning files": moving an
# annotation to an unrelated module is a docs-breaking change and should
# fail here until the table (and docs) are updated. The table doubles as
# the pyprof attribution-region vocabulary: apex_tpu/pyprof/model.py's
# DEFAULT_REGIONS must stay a subset of these keys (asserted in
# tests/test_pyprof.py), so every region a step-time attribution report
# names is guaranteed to exist as a named_scope in source.
ANNOTATIONS = {
    "apex_ddp_allreduce": ["apex_tpu/parallel/distributed.py"],
    "apex_ddp_bucketed_allreduce": ["apex_tpu/parallel/distributed.py"],
    "sync_bn_stats": ["apex_tpu/parallel/sync_batchnorm.py"],
    "pipeline_tick": [
        "apex_tpu/transformer/pipeline_parallel/schedules.py"],
    "flash_attention": ["apex_tpu/ops/flash_attention.py"],
    "optimizer_step": ["apex_tpu/optimizers/_base.py"],
    # model phases (pyprof attribution regions)
    "gpt_embed": ["apex_tpu/models/gpt.py"],
    "gpt_ln": ["apex_tpu/models/gpt.py"],
    "gpt_attention": ["apex_tpu/models/gpt.py"],
    "gpt_mlp": ["apex_tpu/models/gpt.py"],
    "gpt_head_loss": ["apex_tpu/models/gpt.py"],
    "rn50_stem": ["apex_tpu/models/resnet.py"],
    "rn50_body": ["apex_tpu/models/resnet.py"],
    "rn50_head": ["apex_tpu/models/resnet.py"],
    # tensor-parallel layers (GEMM + dependent collective, tp > 1 only)
    "tp_column_linear": [
        "apex_tpu/transformer/tensor_parallel/layers.py"],
    "tp_row_linear": [
        "apex_tpu/transformer/tensor_parallel/layers.py"],
    # serving fast path: the decode kernel plus the AOT step bodies,
    # so pyprof attributes prefill vs decode vs speculative verify
    # (docs/SERVING.md)
    "decode_attention": ["apex_tpu/ops/flash_attention.py"],
    "serve_prefill": ["apex_tpu/serving/engine.py"],
    "serve_decode": ["apex_tpu/serving/engine.py"],
    "serve_verify": ["apex_tpu/serving/engine.py"],
}


def rule_annotations(repo: str) -> Findings:
    findings, notes = [], []
    for name, files in sorted(ANNOTATIONS.items()):
        needle = f'named_scope("{name}")'
        found_in = []
        for rel in files:
            try:
                with open(os.path.join(repo, rel)) as f:
                    if needle in f.read():
                        found_in.append(rel)
            except OSError:
                pass
        if found_in:
            notes.append(f"ok       {name}: {', '.join(found_in)}")
        else:
            findings.append(Finding(
                "ast-annotations", "MISSING", name,
                f"expected {needle} in {' or '.join(files)} — update the "
                f"source or the contract table (ANNOTATIONS in "
                f"apex_tpu/analysis/rules_ast.py + docs/OBSERVABILITY.md)"))
    return findings, notes


# ---------------------------------------------------------------------------
# ast-collectives: gathers/grad-syncs stay behind their chokepoints
# ---------------------------------------------------------------------------

# the only modules allowed to touch lax.all_gather directly: the VMA shims
# themselves and the version-compat layer
ALLOWED_GATHER = {
    _p("apex_tpu", "utils", "vma.py"),
    _p("apex_tpu", "utils", "compat.py"),
}

# lax.psum_scatter: the grad-sync chokepoint (reduce_scatter_grads), plus
# the context-parallel sequence-dim scatter — an ACTIVATION collective
# (RowParallel output path along the sequence axis), not a gradient sync,
# so it does not belong behind the bucketing engine
ALLOWED_SCATTER = {
    _p("apex_tpu", "parallel", "distributed.py"),
    _p("apex_tpu", "transformer", "context_parallel.py"),
    # the jaxpr-collectives rule's own planted-violation selfcheck — a
    # deliberately-unrouted scatter inside a tiny fixture program, the
    # very thing the program-level lint exists to catch
    _p("apex_tpu", "analysis", "program.py"),
}

# modules whose psums are gradient-path reductions by construction: any
# raw lax.psum / lax.psum_scatter here must route through the
# parallel/distributed.py chokepoints (allreduce_grads / grouped_psum /
# reduce_scatter_grads) so bucketing policy cannot be bypassed
GRAD_SYNC_PREFIXES = (
    _p("apex_tpu", "training.py"),
    _p("apex_tpu", "optimizers") + os.sep,
)

_GATHER = re.compile(r"lax\.all_gather\s*\(")
_SCATTER = re.compile(r"lax\.psum_scatter\s*\(")
_PSUM = re.compile(r"lax\.psum\s*\(")


def _line_hits(pattern: re.Pattern, source: str):
    return [i + 1 for i, line in enumerate(source.splitlines())
            if pattern.search(line)]


def rule_collectives(repo: str) -> Findings:
    findings, notes = [], []
    for path in iter_py_files(os.path.join(repo, PACKAGE)):
        rel = os.path.relpath(path, repo)
        with open(path) as f:
            source = f.read()

        hits = _line_hits(_GATHER, source)
        if hits:
            if rel in ALLOWED_GATHER:
                notes.append(f"ok       {rel}: gather wrapper module "
                             f"(lines {', '.join(map(str, hits))})")
            else:
                findings.extend(Finding(
                    "ast-collectives", "RAW", f"{rel}:{ln}",
                    "lax.all_gather outside the VMA-safe wrappers — use "
                    "apex_tpu.utils.vma.varying_all_gather (or "
                    "invariant_all_gather)") for ln in hits)

        hits = _line_hits(_SCATTER, source)
        if hits:
            if rel in ALLOWED_SCATTER:
                notes.append(f"ok       {rel}: psum_scatter chokepoint/"
                             f"allowlisted "
                             f"(lines {', '.join(map(str, hits))})")
            else:
                findings.extend(Finding(
                    "ast-collectives", "RAW", f"{rel}:{ln}",
                    "lax.psum_scatter outside the grad-sync chokepoint — "
                    "use apex_tpu.parallel.distributed."
                    "reduce_scatter_grads (bucketing/telemetry ride on "
                    "it)") for ln in hits)

        if rel.startswith(GRAD_SYNC_PREFIXES):
            findings.extend(Finding(
                "ast-collectives", "RAW", f"{rel}:{ln}",
                "raw lax.psum in a grad-sync module — route through "
                "apex_tpu.parallel.distributed (allreduce_grads / "
                "grouped_psum) so bucketing policy and ddp/* telemetry "
                "cannot be bypassed") for ln in _line_hits(_PSUM, source))
    return findings, notes


# ---------------------------------------------------------------------------
# ast-metrics-doc + ast-metric-families: the metric-name contracts
# ---------------------------------------------------------------------------

DOC = os.path.join("docs", "OBSERVABILITY.md")

# metric families under the documentation contract; names outside these
# prefixes (host registry internals, ad-hoc example metrics) are exempt
# from the PER-NAME doc check — but see EXEMPT_PREFIXES: the family
# meta-lint requires every slash-prefixed name to belong somewhere.
METRIC_PREFIXES = ("health/", "tp/", "amp/", "ddp/", "pipeline/",
                   "optim/", "zero/", "mem/", "perf/", "ckpt/", "resume/",
                   "serve/", "slo/", "elastic/", "fleet/", "train/")

# slash-prefixed families that are deliberately OUTSIDE the doc-table
# contract: jax/* (the compile-storm counters install_compile_listeners
# owns) and memory/* (raw allocator passthrough from sample_memory_stats)
# are runtime internals documented in prose, not per-name table rows
EXEMPT_PREFIXES = ("jax/", "memory/")

# callees whose literal first argument is a metric name: in-graph
# ``ingraph.record(...)`` and the host-registry accessors — ``gauge``
# (the mem/* family is static per compile, so it rides gauges, not
# records) plus ``counter``/``histogram``, which the elastic runtime's
# ckpt/* and resume/* families ride
METRIC_CALLEES = ("record", "gauge", "counter", "histogram")

_PLACEHOLDER = re.compile(r"<[^<>`]*>")


def _norm(name: str) -> str:
    """Collapse every ``<...>`` placeholder spelling to ``<>`` so the
    source's ``f"health/{name}/l2"`` matches the doc's
    ``health/<tree>/l2``."""
    return _PLACEHOLDER.sub("<>", name)


def _metric_names(repo: str):
    """Yield ``(relpath, lineno, name)`` for every statically-known
    metric name at a record/gauge/counter/histogram call site."""
    for rel, tree in iter_package_trees(repo):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if callee_name(node) not in METRIC_CALLEES:
                continue
            name = literal_str(node.args[0])
            if name is not None:
                yield rel, node.lineno, name


def _documented_names(repo: str) -> set:
    """Every backticked token in the observability doc, normalized."""
    with open(os.path.join(repo, DOC)) as f:
        text = f.read()
    return {_norm(tok) for tok in re.findall(r"`([^`\n]+)`", text)}


def rule_metrics_doc(repo: str) -> Findings:
    try:
        documented = _documented_names(repo)
    except OSError:
        return [Finding("ast-metrics-doc", "MISSING", DOC,
                        "cannot read the metric table")], []
    findings, notes = [], []
    for rel, lineno, name in _metric_names(repo):
        if not _norm(name).startswith(METRIC_PREFIXES):
            continue
        if _norm(name) in documented:
            notes.append(f"ok       {name} ({rel}:{lineno})")
        else:
            findings.append(Finding(
                "ast-metrics-doc", "UNDOC", f"{rel}:{lineno}",
                f"{name} recorded but absent from {DOC} — add a table "
                f"row (placeholders like <tree> match f-string fields)"))
    return findings, notes


def rule_metric_families(repo: str) -> Findings:
    """The meta-lint: every slash-prefixed metric name must open with a
    KNOWN family — either a documented ``METRIC_PREFIXES`` family or an
    explicitly exempt runtime-internal one. The family list used to be
    maintained by hand per PR; a brand-new ``<prefix>/`` family now
    fails here with its call site instead of shipping undocumented."""
    findings, notes = [], []
    known = METRIC_PREFIXES + EXEMPT_PREFIXES
    seen_families = set()
    for rel, lineno, name in _metric_names(repo):
        norm = _norm(name)
        if "/" not in norm or norm.startswith("<"):
            continue  # unprefixed ad-hoc names are outside the contract
        family = norm.split("/", 1)[0] + "/"
        if family in known:
            seen_families.add(family)
        else:
            findings.append(Finding(
                "ast-metric-families", "ROGUE", f"{rel}:{lineno}",
                f"{name} opens a metric family {family!r} that is in "
                f"neither METRIC_PREFIXES (documented families) nor "
                f"EXEMPT_PREFIXES — register it in "
                f"apex_tpu/analysis/rules_ast.py and document it in "
                f"{DOC}"))
    notes.append("ok       families in use: "
                 + ", ".join(sorted(seen_families)))
    return findings, notes


# ---------------------------------------------------------------------------
# ast-remat-names: checkpoint-name tags come from the registry
# ---------------------------------------------------------------------------

REGISTRY_FILE = _p(PACKAGE, "remat.py")

# callee spellings that denote a checkpoint-name tag. ``_tag`` is the
# models' policy-gated bound tagger (identity under none/full); ``tag``
# the remat-module chokepoint; ``checkpoint_name`` the raw jax call.
TAG_CALLEES = ("checkpoint_name", "tag", "_tag", "_remat_tag")


def _remat_registry(repo: str):
    """``(CHECKPOINT_NAMES, SELECTIVE_SAVE)`` parsed from the registry
    module's AST — raises OSError/ValueError when the module or the
    assignments are missing (a moved registry must move this scan too)."""
    with open(os.path.join(repo, REGISTRY_FILE)) as f:
        tree = ast.parse(f.read(), filename=REGISTRY_FILE)
    names = save = None
    for node in ast.walk(tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = (node.target,)
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "CHECKPOINT_NAMES":
                names = tuple_literal(node.value)
            if isinstance(t, ast.Name) and t.id == "SELECTIVE_SAVE":
                save = tuple_literal(node.value)
    if not names:
        raise ValueError(
            f"{REGISTRY_FILE} defines no CHECKPOINT_NAMES tuple literal")
    return tuple(names), tuple(save or ())


def _tag_sites(repo: str):
    """Yield ``(relpath, lineno, name)`` for every statically-known tag
    literal in the package (registry module excluded — its docstrings and
    error messages mention names by design)."""
    for rel, tree in iter_package_trees(repo):
        if rel == REGISTRY_FILE:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if callee_name(node) not in TAG_CALLEES:
                continue
            # the name rides as the positional second argument or as
            # the name= keyword (raw checkpoint_name accepts both)
            name = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if isinstance(name, ast.Constant) and isinstance(
                    name.value, str):
                yield rel, node.lineno, name.value


def rule_remat_names(repo: str) -> Findings:
    try:
        names, save = _remat_registry(repo)
    except (OSError, ValueError) as e:
        return [Finding("ast-remat-names", "MISSING", "registry",
                        str(e))], []
    findings, notes = [], []
    for extra in [n for n in save if n not in names]:
        findings.append(Finding(
            "ast-remat-names", "ORPHAN", "SELECTIVE_SAVE",
            f"SELECTIVE_SAVE entry {extra!r} is not in CHECKPOINT_NAMES"))
    for rel, lineno, name in _tag_sites(repo):
        if name in names:
            notes.append(f"ok       {name} ({rel}:{lineno})")
        else:
            findings.append(Finding(
                "ast-remat-names", "ORPHAN", f"{rel}:{lineno}",
                f"{name} tagged but absent from remat.CHECKPOINT_NAMES — "
                f"no policy can save it"))
    return findings, notes


# ---------------------------------------------------------------------------
# ast-elastic-exits: the process-exit discipline
# ---------------------------------------------------------------------------

ELASTIC_DIR = _p(PACKAGE, "elastic")
CHOKEPOINT_FILE = _p(PACKAGE, "utils", "autoresume.py")
CHOKEPOINT_FUNC = "request_resume"
# the supervisor CLI (elastic/launch.py) needs a SECOND blessed exit —
# it must propagate the gang's success as a process exit code — pinned,
# exactly like the runner's, to one named chokepoint function
LAUNCH_FILE = _p(PACKAGE, "elastic", "launch.py")
LAUNCH_CHOKEPOINT_FUNC = "_supervisor_exit"


def _exit_spelling(node):
    """The process-exit spelling of an AST node, or None."""
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if (f.value.id, f.attr) in (("sys", "exit"), ("os", "_exit"),
                                        ("os", "abort")):
                return f"{f.value.id}.{f.attr}"
        if isinstance(f, ast.Name) and f.id in ("exit", "quit"):
            return f.id
    if isinstance(node, ast.Raise) and node.exc is not None:
        exc = node.exc
        name = (exc.func if isinstance(exc, ast.Call) else exc)
        if isinstance(name, ast.Name) and name.id == "SystemExit":
            return "raise SystemExit"
    return None


def _launch_chokepoint_nodes(tree) -> set:
    """ids of every AST node inside ``LAUNCH_CHOKEPOINT_FUNC`` defs."""
    inside = set()
    for func in ast.walk(tree):
        if isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and func.name == LAUNCH_CHOKEPOINT_FUNC:
            inside.update(id(n) for n in ast.walk(func))
    return inside


def rule_elastic_exits(repo: str) -> Findings:
    findings, notes = [], []
    pkg = os.path.join(repo, ELASTIC_DIR)
    if not os.path.isdir(pkg):
        return [Finding("ast-elastic-exits", "MISSING", ELASTIC_DIR,
                        "elastic package absent")], []
    for path in iter_py_files(pkg):
        rel = os.path.relpath(path, repo)
        tree = parse_file(path, rel)
        if tree is None:
            continue
        is_launch = rel == LAUNCH_FILE
        blessed = _launch_chokepoint_nodes(tree) if is_launch else set()
        clean = True
        blessed_exits = 0
        for node in ast.walk(tree):
            spelling = _exit_spelling(node)
            if spelling is None:
                continue
            if id(node) in blessed:
                # the supervisor CLI's one sanctioned exit; counted and
                # shape-checked below, never reported as a raw EXIT
                if spelling == "sys.exit":
                    blessed_exits += 1
                    continue
            clean = False
            findings.append(Finding(
                "ast-elastic-exits", "EXIT", f"{rel}:{node.lineno}",
                f"{spelling}: elastic code must exit only through "
                f"AutoResume.{CHOKEPOINT_FUNC}"
                + (f" or {LAUNCH_CHOKEPOINT_FUNC} (the supervisor CLI "
                   f"chokepoint)" if is_launch else "")
                + " — raise instead, so failures stay distinguishable "
                  "from clean preemptions"))
        if is_launch:
            # chokepoint-rot check, mirroring the AutoResume one: the
            # blessed function must hold EXACTLY one sys.exit
            if blessed_exits != 1:
                clean = False
                findings.append(Finding(
                    "ast-elastic-exits", "CHOKE", rel,
                    f"expected exactly one sys.exit inside "
                    f"{LAUNCH_CHOKEPOINT_FUNC}, found {blessed_exits}"))
            else:
                notes.append(f"ok       {rel}::{LAUNCH_CHOKEPOINT_FUNC} "
                             f"is the supervisor exit chokepoint")
        if clean:
            notes.append(f"ok       {rel}")

    # the chokepoint itself: exactly one sys.exit, inside request_resume
    choke = os.path.join(repo, CHOKEPOINT_FILE)
    try:
        with open(choke) as f:
            tree = ast.parse(f.read(), filename=CHOKEPOINT_FILE)
    except OSError:
        findings.append(Finding(
            "ast-elastic-exits", "MISSING", CHOKEPOINT_FILE,
            "the AutoResume chokepoint the contract is anchored on "
            "cannot be read"))
        return findings, notes
    exits = []
    for func in [n for n in ast.walk(tree)
                 if isinstance(n, ast.FunctionDef)]:
        for node in ast.walk(func):
            if _exit_spelling(node) == "sys.exit":
                exits.append(func.name)
    if exits != [CHOKEPOINT_FUNC]:
        findings.append(Finding(
            "ast-elastic-exits", "CHOKE", CHOKEPOINT_FILE,
            f"expected exactly one sys.exit inside {CHOKEPOINT_FUNC}, "
            f"found {exits or 'none'}"))
    else:
        notes.append(f"ok       {CHOKEPOINT_FILE}::{CHOKEPOINT_FUNC} is "
                     f"the sole exit chokepoint")
    return findings, notes


# ---------------------------------------------------------------------------
# ast-bench-configs: declarative bench legs name real config fields
# ---------------------------------------------------------------------------

CONFIG_CLASSES = ("TrainConfig", "ModelConfig", "ParallelConfig",
                  "BatchConfig", "OptimizerConfig")
SECTIONS = {"model": "ModelConfig", "parallel": "ParallelConfig",
            "batch": "BatchConfig", "optimizer": "OptimizerConfig"}

# the request-latency vocabulary bench.py's stated DECODE_SLO may target
# (mirrors apex_tpu.observability.slo.LATENCY_METRICS — duplicated here
# because the AST family must not import the jax-backed package; the
# mirror is pinned equal in tests/test_analysis.py)
SLO_METRICS = ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms")


def _dataclass_fields(path: str, class_names) -> dict:
    """``{class_name: {field, ...}}`` from annotated class-body
    assignments (the dataclass field syntax), no import needed."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in class_names:
            fields = set()
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
            out[node.name] = fields
    return out


def bench_field_tables(repo: str) -> dict:
    tables = _dataclass_fields(
        os.path.join(repo, PACKAGE, "config.py"), CONFIG_CLASSES)
    tables.update(_dataclass_fields(
        os.path.join(repo, PACKAGE, "models", "gpt.py"), ("GPTConfig",)))
    missing = [c for c in (*CONFIG_CLASSES, "GPTConfig")
               if not tables.get(c)]
    if missing:
        raise ValueError(f"could not extract fields for {missing}")
    return tables


def _check_spec(spec: dict, tables: dict, where: str,
                findings: list) -> bool:
    """One TrainConfig-shaped nested dict against the field tables."""
    ok = True
    for key, value in spec.items():
        if key not in tables["TrainConfig"]:
            ok = False
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"{key!r} is not a TrainConfig field"))
            continue
        section = SECTIONS.get(key)
        if section and isinstance(value, dict):
            for sub in value:
                if sub not in tables[section]:
                    ok = False
                    findings.append(Finding(
                        "ast-bench-configs", "UNKNOWN", where,
                        f"{key}.{sub!r} is not a {section} field"))
    return ok


def _literal_assign(path: str, name: str):
    """The literal value of module-level ``name = <literal>``, or None."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for target in node.targets:
                if isinstance(target, ast.Name) and target.id == name:
                    return ast.literal_eval(node.value)
    return None


def _bench_table(bench_path: str):
    """The literal ``BENCH_TRAIN_CONFIGS`` dict from bench.py, or None."""
    return _literal_assign(bench_path, "BENCH_TRAIN_CONFIGS")


def _decode_slo_table(bench_path: str):
    """The literal ``DECODE_SLO`` tuple from bench.py, or None."""
    return _literal_assign(bench_path, "DECODE_SLO")


def _class_init_params(path: str, class_name: str):
    """Parameter names of ``class_name.__init__`` (AST, no import), or
    None when the class or its ``__init__`` is absent."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            for stmt in node.body:
                if isinstance(stmt, ast.FunctionDef) \
                        and stmt.name == "__init__":
                    a = stmt.args
                    return {p.arg for p in (*a.posonlyargs, *a.args,
                                            *a.kwonlyargs)} - {"self"}
    return None


def _check_decode_configs(repo: str, bench_path: str, findings: list,
                          notes: list):
    """The serving legs: ``BENCH_DECODE_CONFIGS`` keys must be real
    engine-constructor parameters — bench.py builds the engine by
    ``**spec``, so an unknown key would TypeError only at bench runtime
    (and a renamed engine knob would silently strand the leg). Legs
    carrying block-pool keys validate against
    ``PagedServingEngine.__init__``; dense legs (the speculative A/B)
    against ``ServingEngine.__init__``. A leg that states
    ``speculate_k`` must state it >= 1 — ``speculate_k=0`` would
    silently bench the non-speculative path against itself."""
    engine_path = os.path.join(repo, PACKAGE, "serving", "engine.py")
    try:
        paged_allowed = _class_init_params(engine_path,
                                           "PagedServingEngine")
        dense_allowed = _class_init_params(engine_path, "ServingEngine")
        table = _literal_assign(bench_path, "BENCH_DECODE_CONFIGS")
    except (OSError, SyntaxError, ValueError) as e:
        findings.append(Finding("ast-bench-configs", "MISSING",
                                "bench.py BENCH_DECODE_CONFIGS", str(e)))
        return
    if paged_allowed is None or dense_allowed is None:
        findings.append(Finding(
            "ast-bench-configs", "MISSING", "serving/engine.py",
            "no PagedServingEngine/ServingEngine.__init__ to validate "
            "BENCH_DECODE_CONFIGS against"))
        return
    if table is None:
        findings.append(Finding(
            "ast-bench-configs", "MISSING", "bench.py",
            "no literal BENCH_DECODE_CONFIGS table (the serving decode "
            "legs must state their engine config declaratively)"))
        return
    for leg, spec in table.items():
        where = f"bench.py BENCH_DECODE_CONFIGS[{leg!r}]"
        if not isinstance(spec, dict):
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"expected a dict of engine kwargs, got "
                f"{type(spec).__name__}"))
            continue
        paged = bool(set(spec) - dense_allowed)
        allowed = paged_allowed if paged else dense_allowed
        engine = "PagedServingEngine" if paged else "ServingEngine"
        bad = [k for k in spec if k not in allowed]
        if bad:
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"{bad} are not {engine}.__init__ parameters"))
            continue
        sk = spec.get("speculate_k")
        if sk is not None and (not isinstance(sk, int) or sk < 1):
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"speculate_k={sk!r}: a speculative leg must state a "
                "static draft window >= 1 (0 benches the "
                "non-speculative path against itself)"))
            continue
        notes.append(f"ok       {where}: {len(spec)} keys ({engine})")


def _check_decode_slo(bench_path: str, findings: list, notes: list):
    """The stated-SLO contract: ``DECODE_SLO`` entries are
    ``(metric, quantile, threshold_ms)`` triples over the request-record
    latency vocabulary — a typo'd metric would score ``goodput`` against
    a field ``SLOTarget`` rejects only at bench runtime."""
    try:
        table = _decode_slo_table(bench_path)
    except (OSError, SyntaxError, ValueError) as e:
        findings.append(Finding("ast-bench-configs", "MISSING",
                                "bench.py DECODE_SLO", str(e)))
        return
    if table is None:
        findings.append(Finding(
            "ast-bench-configs", "MISSING", "bench.py",
            "no literal DECODE_SLO table (the gpt_decode_goodput line "
            "must state its SLO declaratively)"))
        return
    if not isinstance(table, (list, tuple)):
        # a malformed literal must be a FINDING, not a TypeError that
        # aborts the whole analysis run
        findings.append(Finding(
            "ast-bench-configs", "UNKNOWN", "bench.py DECODE_SLO",
            f"expected a tuple of (metric, quantile, threshold_ms) "
            f"triples, got {type(table).__name__}"))
        return
    ok = True
    for entry in table:
        where = f"bench.py DECODE_SLO[{entry!r}]"
        if not (isinstance(entry, tuple) and len(entry) == 3):
            ok = False
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                "expected a (metric, quantile, threshold_ms) triple"))
            continue
        metric, quantile, threshold = entry
        if metric not in SLO_METRICS:
            ok = False
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"{metric!r} is not a request-latency metric "
                f"{SLO_METRICS}"))
        if not (isinstance(quantile, (int, float))
                and 0 < quantile < 100):
            ok = False
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"quantile {quantile!r} outside (0, 100)"))
        if not (isinstance(threshold, (int, float)) and threshold > 0):
            ok = False
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", where,
                f"threshold_ms {threshold!r} must be positive"))
    if ok:
        notes.append(f"ok       bench.py DECODE_SLO: {len(table)} "
                     f"target(s)")


def _gpt_step_calls(bench_path: str):
    """``(own_params, [(lineno, kw_names)])`` of every
    ``_gpt_train_step(...)`` call plus the def's own parameters."""
    with open(bench_path) as f:
        tree = ast.parse(f.read(), filename=bench_path)
    own_params = set()
    calls = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "_gpt_train_step":
            a = node.args
            own_params = {p.arg for p in
                          (*a.posonlyargs, *a.args, *a.kwonlyargs)}
        if isinstance(node, ast.Call):
            if callee_name(node) == "_gpt_train_step":
                kws = [k.arg for k in node.keywords if k.arg is not None]
                calls.append((node.lineno, kws))
    return own_params, calls


def rule_bench_configs(repo: str) -> Findings:
    findings, notes = [], []
    try:
        tables = bench_field_tables(repo)
    except (OSError, ValueError) as e:
        return [Finding("ast-bench-configs", "MISSING",
                        "config field tables", str(e))], []

    bench_path = os.path.join(repo, "bench.py")
    try:
        table = _bench_table(bench_path)
        own_params, calls = _gpt_step_calls(bench_path)
    except (OSError, SyntaxError, ValueError) as e:
        return [Finding("ast-bench-configs", "MISSING", "bench.py",
                        str(e))], []
    if table is None:
        findings.append(Finding(
            "ast-bench-configs", "MISSING", "bench.py",
            "no literal BENCH_TRAIN_CONFIGS table"))
    else:
        for leg, spec in table.items():
            where = f"bench.py BENCH_TRAIN_CONFIGS[{leg!r}]"
            if _check_spec(spec, tables, where, findings):
                nkeys = sum(len(v) if isinstance(v, dict) else 1
                            for v in spec.values())
                notes.append(f"ok       {where}: {nkeys} keys")

    _check_decode_slo(bench_path, findings, notes)
    _check_decode_configs(repo, bench_path, findings, notes)

    allowed = own_params | tables["GPTConfig"]
    for lineno, kws in calls:
        bad = [k for k in kws if k not in allowed]
        if bad:
            findings.append(Finding(
                "ast-bench-configs", "UNKNOWN", f"bench.py:{lineno}",
                f"_gpt_train_step keyword(s) {bad} match neither its "
                f"parameters nor a GPTConfig field"))
        else:
            notes.append(f"ok       bench.py:{lineno} _gpt_train_step "
                         f"call")

    results_path = os.path.join(repo, "BENCH_CONFIGS.json")
    if os.path.exists(results_path):
        try:
            with open(results_path) as f:
                entries = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(Finding(
                "ast-bench-configs", "MISSING", "BENCH_CONFIGS.json",
                str(e)))
            return findings, notes
        for entry in entries if isinstance(entries, list) else []:
            cfg = entry.get("config") if isinstance(entry, dict) else None
            if isinstance(cfg, dict):
                where = (f"BENCH_CONFIGS.json "
                         f"[{entry.get('metric', '?')}].config")
                if _check_spec(cfg, tables, where, findings):
                    notes.append(f"ok       {where}")
    return findings, notes


# ---------------------------------------------------------------------------
# ast-bench-history: the perfwatch JSONL schema stays pinned to its writer
# ---------------------------------------------------------------------------

PERFWATCH_FILE = _p(PACKAGE, "observability", "perfwatch.py")
HISTORY_TABLE = "HISTORY_FIELDS"
HISTORY_WRITER = "make_record"
HISTORY_JSONL = "BENCH_HISTORY.jsonl"


def _history_writer_keys(path: str):
    """``(base_keys, promoted_keys)`` of the history writer: the literal
    keys of ``make_record``'s record dict (the always-present set) and
    every literal ``rec["..."] = ...`` subscript it assigns (the
    conditionally-promoted set). None when the function is absent."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.FunctionDef)
                and node.name == HISTORY_WRITER):
            continue
        base, promoted = [], []
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                keys = [k.value for k in sub.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                if "metric" in keys:
                    base = keys
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        promoted.append(t.slice.value)
        return base, promoted
    return None


def rule_bench_history(repo: str) -> Findings:
    """The longitudinal twin of ``ast-bench-configs``: perfwatch's
    ``HISTORY_FIELDS`` literal is the one schema every
    ``BENCH_HISTORY.jsonl`` record obeys — the writer's always-present
    dict keys must equal the table's ``required`` set, its promoted
    keys must come from the table, and any on-disk history at the repo
    root must match both (a key outside the table means a reader and a
    writer already disagree)."""
    findings, notes = [], []
    path = os.path.join(repo, PERFWATCH_FILE)
    try:
        table = _literal_assign(path, HISTORY_TABLE)
        writer = _history_writer_keys(path)
    except (OSError, SyntaxError, ValueError) as e:
        return [Finding("ast-bench-history", "MISSING", PERFWATCH_FILE,
                        str(e))], []
    if table is None:
        return [Finding(
            "ast-bench-history", "MISSING", PERFWATCH_FILE,
            f"no literal {HISTORY_TABLE} table (the JSONL schema must "
            f"be stated declaratively)")], []

    fields, required, ok_shape = {}, set(), True
    for entry in table:
        if not (isinstance(entry, tuple) and len(entry) == 2
                and isinstance(entry[0], str)
                and entry[1] in ("required", "optional")):
            ok_shape = False
            findings.append(Finding(
                "ast-bench-history", "UNKNOWN",
                f"{HISTORY_TABLE}[{entry!r}]",
                "expected a (field, 'required'|'optional') pair"))
            continue
        fields[entry[0]] = entry[1]
        if entry[1] == "required":
            required.add(entry[0])
    if ok_shape:
        notes.append(f"ok       {HISTORY_TABLE}: {len(fields)} field(s), "
                     f"{len(required)} required")

    if writer is None:
        findings.append(Finding(
            "ast-bench-history", "MISSING", PERFWATCH_FILE,
            f"no {HISTORY_WRITER}() writer to validate "
            f"{HISTORY_TABLE} against"))
    else:
        base, promoted = writer
        for key in sorted(required - set(base)):
            findings.append(Finding(
                "ast-bench-history", "MISSING",
                f"{PERFWATCH_FILE}::{HISTORY_WRITER}",
                f"required field {key!r} absent from the writer's "
                f"record literal"))
        for key in sorted(set(base) - required):
            findings.append(Finding(
                "ast-bench-history", "ROGUE",
                f"{PERFWATCH_FILE}::{HISTORY_WRITER}",
                f"writer always emits {key!r}, which {HISTORY_TABLE} "
                f"does not list as required"))
        for key in sorted(set(promoted) - set(fields)):
            findings.append(Finding(
                "ast-bench-history", "ROGUE",
                f"{PERFWATCH_FILE}::{HISTORY_WRITER}",
                f"writer promotes {key!r}, which is not in "
                f"{HISTORY_TABLE} at all"))
        if set(base) == required and set(promoted) <= set(fields):
            notes.append(f"ok       {HISTORY_WRITER}: {len(base)} base + "
                         f"{len(set(promoted))} promoted key(s) match")

    jsonl = os.path.join(repo, HISTORY_JSONL)
    if os.path.exists(jsonl):
        checked = 0
        with open(jsonl) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                where = f"{HISTORY_JSONL}:{lineno}"
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    findings.append(Finding(
                        "ast-bench-history", "UNKNOWN", where, str(e)))
                    continue
                keys = set(rec) if isinstance(rec, dict) else set()
                for key in sorted(required - keys):
                    findings.append(Finding(
                        "ast-bench-history", "MISSING", where,
                        f"record lacks required field {key!r}"))
                for key in sorted(keys - set(fields)):
                    findings.append(Finding(
                        "ast-bench-history", "UNKNOWN", where,
                        f"record key {key!r} is not in {HISTORY_TABLE}"))
                checked += 1
        notes.append(f"ok       {HISTORY_JSONL}: {checked} record(s) "
                     f"checked")
    return findings, notes


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------

register(Rule("ast-annotations", "ast",
              "documented hot-path named_scope annotations exist in "
              "source (pyprof region vocabulary)", run=rule_annotations))
register(Rule("ast-collectives", "ast",
              "collective call sites stay behind the VMA/bucketing "
              "chokepoints (text-level; jaxpr-collectives is the "
              "program-level twin)", run=rule_collectives))
register(Rule("ast-metrics-doc", "ast",
              "every recorded metric name in a checked family has a "
              "docs/OBSERVABILITY.md row", run=rule_metrics_doc))
register(Rule("ast-metric-families", "ast",
              "every slash-prefixed metric name belongs to a registered "
              "family (meta-lint over the family list itself)",
              run=rule_metric_families))
register(Rule("ast-remat-names", "ast",
              "checkpoint-name tag literals come from "
              "remat.CHECKPOINT_NAMES; SELECTIVE_SAVE is a registry "
              "subset", run=rule_remat_names))
register(Rule("ast-elastic-exits", "ast",
              "elastic code exits only through AutoResume.request_resume "
              "or launch.py::_supervisor_exit", run=rule_elastic_exits))
register(Rule("ast-bench-configs", "ast",
              "bench-config keys name real config dataclass fields",
              run=rule_bench_configs))
register(Rule("ast-bench-history", "ast",
              "the perfwatch JSONL schema (writer keys + on-disk "
              "records) matches the literal HISTORY_FIELDS table",
              run=rule_bench_history))
