"""Family-A rules: jaxpr/program lints for the bug classes found the
hard way.

Every rule here encodes a production bug this repo actually shipped and
caught late with a hand-written one-off check (pointers in
docs/ANALYSIS.md):

- :func:`check_donation` — PR 9's double-donated shared int8 scale
  buffer: a pytree leaf appearing twice in a donated argument hands the
  SAME buffer to XLA twice (use-after-free class), and a donated leaf
  that never shows up in ``input_output_alias`` silently wastes the
  in-place-update HBM saving the donation was for.
- :func:`check_collective_placement` — the program-level twin of
  ``scripts/check_collectives.py``: a helper that *calls* ``lax.psum``
  indirectly escapes the AST check, but its equation still lands in the
  jaxpr outside the blessed chokepoint ``named_scope``\\ s.
- :func:`check_flat_materialization` — PR 8's flat-gradient barrier: a
  1-D padded-size fp32 value anywhere in a bucketed ZeRO program is the
  full-tree ravel barrier back in disguise.
- :func:`check_shared_grad_reduction` — PR 7's silent shared-param
  cotangent drift: under ``shard_map_unchecked`` on pre-VMA jax there is
  no replication rewrite, so a replicated param's cotangent with no
  reducing collective over the mesh axis in its dependency cone is a
  per-rank partial — every rank steps with a different gradient.
- :class:`recompile_guard` — PR 1's compile-storm counters as a scoped
  assertion: the serving/elastic driver loops adopt it so a shape or
  static-arg leak that retraces the steady-state step fails loudly.

Rules return :class:`~apex_tpu.analysis.core.Finding` lists; the
``verify_*`` convenience wrappers raise :class:`AnalysisError` instead
(construction-time self-checks). Each rule registers a CLI ``selfcheck``
proving itself on a built-in clean/planted program pair.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from apex_tpu.analysis.core import (AnalysisError, Finding, Rule,
                                    format_finding, register)
from apex_tpu.analysis import jaxpr as jx

__all__ = ["DEFAULT_BLESSED_SCOPES", "GRAD_SYNC_COLLECTIVES",
           "check_donation", "check_collective_placement",
           "check_flat_materialization", "check_shared_grad_reduction",
           "verify_findings", "lint_program", "recompile_guard",
           "lint_trainer_step", "lint_serving_engine"]


# ---------------------------------------------------------------------------
# jaxpr-donation
# ---------------------------------------------------------------------------

import re as _re

# HLO header: input_output_alias={ {0}: (1, {}, may-alias), ... } — the
# parenthesized first field is the parameter number
_HLO_ALIAS_KEY = "input_output_alias={"
_HLO_PARAM = _re.compile(r"\(\s*(\d+)\s*,")
# StableHLO (lowered, pre-XLA): each aliased parameter carries a
# tf.aliasing_output attr; a requested-but-unpaired donation shows up as
# jax.buffer_donor (or the parameter is dropped entirely when unused)
_SH_ALIAS = "tf.aliasing_output"
_SH_DONOR = "jax.buffer_donor"


def _alias_param_numbers(text: str) -> Optional[List[int]]:
    """Parameter numbers aliased in an HLO module header, or None when
    the text is not HLO (StableHLO lowered text has no header map). The
    map nests braces (output/param tuple indices), so the span is found
    by balance, not regex."""
    start = text.find(_HLO_ALIAS_KEY)
    if start < 0:
        return None
    i = start + len(_HLO_ALIAS_KEY)
    depth = 1
    j = i
    while j < len(text) and depth:
        depth += {"{": 1, "}": -1}.get(text[j], 0)
        j += 1
    return [int(p) for p in _HLO_PARAM.findall(text[i:j])]


def _buffer_key(leaf) -> tuple:
    """An identity key for a device buffer: the array object itself,
    plus the raw buffer pointer when the backend exposes one (two
    distinct jax.Array wrappers can share a buffer)."""
    try:
        return ("ptr", leaf.unsafe_buffer_pointer())
    except Exception:
        return ("id", id(leaf))


def check_donation(program: Any = None, *,
                   donated_args: Any = None,
                   expected_donated: Optional[int] = None,
                   min_alias_bytes: Optional[int] = None,
                   label: str = "program") -> List[Finding]:
    """Donation-safety lint.

    ``program``: a lowered or compiled stage (anything with
    ``.as_text()``); HLO headers are parsed for ``input_output_alias``
    entries, StableHLO for ``tf.aliasing_output`` parameter attributes.
    ``expected_donated``: the number of donated *leaves* the caller
    annotated (e.g. ``len(tree_leaves(cache))``) — fewer aliased
    parameters than that means a donated buffer is NOT updated in place.
    ``donated_args``: the actual argument pytree(s) that will be donated
    — flagged when two leaves are the same underlying buffer (the PR 9
    double-donation class; XLA cannot see this statically).
    ``min_alias_bytes``: floor on ``memory_analysis().alias_size_in_bytes``
    for compiled programs (skipped where the backend reports none).
    """
    findings: List[Finding] = []

    if donated_args is not None:
        import jax
        seen = {}
        for args in (donated_args if isinstance(donated_args, tuple)
                     else (donated_args,)):
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    args)[0]:
                if not hasattr(leaf, "dtype"):
                    continue
                key = _buffer_key(leaf)
                pretty = jax.tree_util.keystr(path)
                if key in seen:
                    findings.append(Finding(
                        "jaxpr-donation", "DOUBLE", label,
                        f"leaves {seen[key]} and {pretty} are the SAME "
                        f"buffer donated twice — XLA will alias one "
                        f"buffer to two outputs (the PR 9 shared-scale "
                        f"class); allocate distinct buffers"))
                else:
                    seen[key] = pretty

    if program is not None:
        text = program.as_text() if hasattr(program, "as_text") else \
            str(program)
        params = _alias_param_numbers(text)
        if params is None:
            n_aliased = text.count(_SH_ALIAS)
            n_unpaired = text.count(_SH_DONOR)
            if n_unpaired:
                findings.append(Finding(
                    "jaxpr-donation", "UNALIASED", label,
                    f"{n_unpaired} donated parameter(s) carry "
                    f"{_SH_DONOR} but no {_SH_ALIAS} — the donation "
                    f"could not be paired with an output and buys "
                    f"nothing"))
        else:
            n_aliased = len(params)
            dup = sorted({p for p in params if params.count(p) > 1})
            if dup:
                findings.append(Finding(
                    "jaxpr-donation", "DOUBLE", label,
                    f"parameter(s) {dup} appear in more than one "
                    f"input_output_alias entry — one donated buffer "
                    f"feeds two outputs"))
        if expected_donated is not None and n_aliased < expected_donated:
            findings.append(Finding(
                "jaxpr-donation", "UNALIASED", label,
                f"only {n_aliased} of {expected_donated} donated leaves "
                f"appear in the program's input/output aliasing — the "
                f"rest are copied, not updated in place (an unused "
                f"donated arg is dropped from the program entirely)"))
        ma = getattr(program, "memory_analysis", None)
        if min_alias_bytes is not None and callable(ma):
            try:
                analysis = ma()
            except Exception:
                analysis = None
            if analysis is not None:
                got = int(getattr(analysis, "alias_size_in_bytes", 0))
                # an executable deserialized from the PERSISTENT
                # compilation cache carries NO memory_analysis —
                # alias_size reads 0 while the HLO header's
                # input_output_alias map (parsed above) is intact and
                # complete. The map is the authority there; a 0 next to
                # a complete map is missing metadata, not a missing
                # alias (reproduced: fresh compile 4096 bytes, cache
                # hit 0 bytes, identical alias map — this hard-failed
                # the dryrun serving leg on every warm-cache retry).
                # A genuinely partial alias (0 < got < floor) still
                # fires.
                map_complete = n_aliased > 0 and (
                    expected_donated is None
                    or n_aliased >= expected_donated)
                if got < min_alias_bytes and not (
                        got == 0 and map_complete):
                    findings.append(Finding(
                        "jaxpr-donation", "UNALIASED", label,
                        f"alias_size_in_bytes {got} < expected "
                        f"{min_alias_bytes} — part of the donated state "
                        f"is still copied each step"))
    return findings


# ---------------------------------------------------------------------------
# jaxpr-collectives
# ---------------------------------------------------------------------------

# chokepoint named_scopes a grad-sync collective may live under: the DDP
# engine's own scopes plus optimizer_step (ZeRO's per-bucket RS/AG issue
# from inside the optimizer; scripts/check_annotations.py pins all three
# scopes to their owning modules)
DEFAULT_BLESSED_SCOPES = ("apex_ddp_allreduce",
                          "apex_ddp_bucketed_allreduce", "optimizer_step")

# the grad-sync collective class the placement lint polices by default;
# bare psum is NOT here (loss means / metrics / health psums are
# legitimate everywhere) — pass collectives=(..., "psum") to tighten a
# specific program
GRAD_SYNC_COLLECTIVES = ("psum_scatter", "reduce_scatter", "all_gather",
                         "all_gather_invariant")


def check_collective_placement(
        program: Any, *, blessed: Sequence[str] = DEFAULT_BLESSED_SCOPES,
        collectives: Sequence[str] = GRAD_SYNC_COLLECTIVES,
        axes: Optional[Sequence[str]] = None,
        label: str = "program") -> List[Finding]:
    """Flag ``collectives``-class equations (optionally restricted to
    mesh ``axes``) whose accumulated ``named_scope`` stack contains none
    of the ``blessed`` chokepoint scopes. Catches what the AST check
    cannot: a helper that reaches ``lax.psum_scatter`` through any number
    of indirections still traces to an equation outside the scope."""
    findings = []
    jaxpr = jx.jaxpr_of(program)
    for eqn, stack in jx.iter_eqns_scoped(jaxpr):
        name = eqn.primitive.name
        if name not in collectives:
            continue
        eq_axes = jx.eqn_axes(eqn)
        if axes is not None and not set(eq_axes) & set(axes):
            continue
        if not jx.scope_matches(stack, blessed):
            findings.append(Finding(
                "jaxpr-collectives", "RAW", label,
                f"{name} over axes {tuple(eq_axes)} outside the blessed "
                f"chokepoint scopes {tuple(blessed)} (scope stack: "
                f"{stack or '<none>'}) — route it through the "
                f"parallel/distributed chokepoints or extend the "
                f"blessed list with justification"))
    return findings


# ---------------------------------------------------------------------------
# jaxpr-flat-grad
# ---------------------------------------------------------------------------

def check_flat_materialization(program: Any, sizes, *,
                               dtype: str = "float32",
                               label: str = "program") -> List[Finding]:
    """No equation in a bucketed/ZeRO program may output a 1-D ``dtype``
    array of a padded flat-gradient ``size`` — that value IS the
    full-tree ravel barrier the backward-interleaved apply removed
    (PR 8); its presence serializes every bucket behind the slowest."""
    jaxpr = jx.jaxpr_of(program)
    if isinstance(sizes, int):
        sizes = (sizes,)
    findings = []
    for size in sizes:
        prims = jx.flat_materializations(jaxpr, size, dtype)
        if prims:
            findings.append(Finding(
                "jaxpr-flat-grad", "BARRIER", label,
                f"padded-size ({size},) {dtype} value(s) materialize "
                f"via {sorted(set(prims))} — the full flat gradient "
                f"barrier is back; ravel span-locally per bucket"))
    return findings


# ---------------------------------------------------------------------------
# jaxpr-shared-grad
# ---------------------------------------------------------------------------

def check_shared_grad_reduction(
        program: Any, outputs: Sequence[Tuple[int, str]], axis: str, *,
        label: str = "program") -> List[Finding]:
    """Each listed output (``(flat_output_index, human_name)``) must have
    a reducing collective over mesh ``axis`` in its dependency cone.

    This is PR 7's drift bug as a lint: under ``shard_map_unchecked`` on
    pre-VMA jax nothing reconciles a replicated param's cotangent, so a
    shared-grad (or updated-shared-param) output whose cone contains no
    ``psum``-class reduction over the axis is a per-rank partial — the
    nominally replicated leaves drift apart silently (~2·lr/step for
    tied embeddings)."""
    jaxpr = jx.jaxpr_of(program)
    findings = []
    for idx, name in outputs:
        if not jx.cone_has_reduction(jaxpr, idx, axis):
            findings.append(Finding(
                "jaxpr-shared-grad", "PARTIAL", label,
                f"output [{idx}] ({name}) has no reducing collective "
                f"over mesh axis {axis!r} in its dependency cone — under "
                f"shard_map_unchecked its value is a per-rank partial "
                f"cotangent and replicas will drift (psum the shared "
                f"grads over {axis!r}, see schedules._finalize_shared)"))
    return findings


# ---------------------------------------------------------------------------
# composition + verification helpers
# ---------------------------------------------------------------------------

def verify_findings(findings: List[Finding], context: str) -> None:
    """Raise :class:`AnalysisError` when any finding fired — the
    construction-time self-check spelling of the rules."""
    if findings:
        detail = "\n".join(format_finding(f) for f in findings)
        raise AnalysisError(
            f"static-analysis self-check failed for {context}:\n{detail}",
            findings)


def lint_program(program: Any, *,
                 blessed: Sequence[str] = DEFAULT_BLESSED_SCOPES,
                 collectives: Sequence[str] = GRAD_SYNC_COLLECTIVES,
                 collective_axes: Optional[Sequence[str]] = None,
                 flat_sizes: Sequence[int] = (),
                 flat_dtype: str = "float32",
                 shared_outputs: Sequence[Tuple[int, str]] = (),
                 shared_axis: Optional[str] = None,
                 label: str = "program") -> List[Finding]:
    """Run every applicable structural rule over one traced program and
    return the combined findings (the cross-talk surface the planted
    fixtures assert on: exactly one rule fires per planted bug)."""
    jaxpr = jx.jaxpr_of(program)
    findings = check_collective_placement(
        jaxpr, blessed=blessed, collectives=collectives,
        axes=collective_axes, label=label)
    if flat_sizes:
        findings += check_flat_materialization(
            jaxpr, flat_sizes, dtype=flat_dtype, label=label)
    if shared_outputs and shared_axis is not None:
        findings += check_shared_grad_reduction(
            jaxpr, shared_outputs, shared_axis, label=label)
    return findings


# ---------------------------------------------------------------------------
# jaxpr-recompile: the zero-recompile budget
# ---------------------------------------------------------------------------

class recompile_guard:
    """Context manager asserting the compile-storm counters (PR 1) stay
    FLAT across a driver loop.

    Installs listeners on a private registry, snapshots ``jax/compiles``
    and ``jax/traces`` at entry, and on exit emits a finding (and raises
    :class:`AnalysisError` unless ``raise_on_violation=False``) when
    either moved. Call :meth:`rebase` after the loop's warmup iteration
    — first dispatch legitimately compiles; the steady state must not.
    The serving scheduler (``SlotScheduler.run(no_recompile=True)``) and
    the elastic runner (``ElasticRunner.fit(no_recompile=True)``) wrap
    their loops in exactly this guard.
    """

    COUNTERS = ("jax/compiles", "jax/traces")

    def __init__(self, label: str = "loop",
                 raise_on_violation: bool = True):
        self.label = label
        self.raise_on_violation = raise_on_violation
        self.findings: List[Finding] = []
        self._reg = None
        self._base = {}

    def _snap(self) -> dict:
        snap = self._reg.snapshot()
        return {k: float(snap.get(k, 0.0)) for k in self.COUNTERS}

    def __enter__(self) -> "recompile_guard":
        from apex_tpu.observability.registry import MetricsRegistry
        from apex_tpu.observability.runtime import \
            install_compile_listeners
        self._reg = MetricsRegistry()
        install_compile_listeners(self._reg)
        self._base = self._snap()
        return self

    def rebase(self) -> None:
        """Re-baseline after warmup: compiles before this call are the
        expected first-dispatch cost, compiles after it are the storm."""
        self._base = self._snap()

    def __exit__(self, exc_type, exc, tb) -> bool:
        from apex_tpu.observability.runtime import \
            uninstall_compile_listeners
        now = self._snap()
        uninstall_compile_listeners(self._reg)
        if exc_type is not None:
            return False  # never mask the loop's own failure
        delta = {k: now[k] - self._base[k] for k in self.COUNTERS
                 if now[k] > self._base[k]}
        if delta:
            self.findings.append(Finding(
                "jaxpr-recompile", "STORM", self.label,
                f"compile-storm counters moved inside a zero-recompile "
                f"region: {delta} — a shape or static-arg leak is "
                f"retracing the steady-state step"))
        if self.findings and self.raise_on_violation:
            verify_findings(self.findings, f"recompile_guard "
                            f"({self.label})")
        return False


# ---------------------------------------------------------------------------
# real-program wiring: the trainer step and the serving engine
# ---------------------------------------------------------------------------

def _subtree_output_span(out_tree, index: int) -> Tuple[int, int]:
    """``(offset, count)`` of flat output leaves for element ``index`` of
    a tuple-structured output."""
    import jax
    leaves = [len(jax.tree_util.tree_leaves(t)) for t in out_tree]
    return sum(leaves[:index]), leaves[index]


def lint_trainer_step(trainer, state, tokens, targets, *,
                      donation: bool = True) -> List[Finding]:
    """Run the Family-A rules over a ``GPTHybridTrainer`` step on real
    arguments: flat-gradient barrier (ZeRO bucket layout's padded size),
    grad-sync collective placement on the data axis, shared-grad
    reduction over ``pipe``/``data`` for the updated shared params, and
    (``donation=True``) the donated-entry-point self-check on the
    COMPILED step — sharded programs pair donations with outputs at XLA
    compile time, so this half costs a backend compile; pass
    ``donation=False`` when the caller already verifies via
    ``trainer.jit_train_step(verify_donation=True)``."""
    import jax

    args = (*state, tokens, targets)
    jaxpr = jax.make_jaxpr(trainer.train_step)(*args).jaxpr
    findings = check_collective_placement(
        jaxpr, axes=("data",), label="trainer.train_step")

    layout = getattr(getattr(trainer, "opt", None), "_layout", None)
    if layout is not None:
        findings += check_flat_materialization(
            jaxpr, (layout.padded,), label="trainer.train_step")

    # updated shared params are output element 2 of
    # (loss, stage_stack, shared, opt_state, ls)
    out_shapes = jax.eval_shape(trainer.train_step, *args)
    offset, count = _subtree_output_span(out_shapes, 2)
    shared_paths = [
        jax.tree_util.keystr(p) for p, _ in
        jax.tree_util.tree_flatten_with_path(out_shapes[2])[0]]
    outputs = [(offset + i, f"new shared{shared_paths[i]}")
               for i in range(count)]
    mesh_axes = dict(zip(trainer.mesh.axis_names, trainer.mesh.devices.shape)) \
        if hasattr(trainer, "mesh") else {}
    for axis in ("pipe", "data"):
        if mesh_axes.get(axis, 1) > 1:
            findings += check_shared_grad_reduction(
                jaxpr, outputs, axis, label="trainer.train_step")

    if donation:
        compiled = jax.jit(trainer.train_step, donate_argnums=(0, 1, 2)
                           ).trace(*args).lower().compile()
        expected = sum(len(jax.tree_util.tree_leaves(s))
                       for s in state[:3])
        findings += check_donation(
            compiled, donated_args=tuple(state[:3]),
            expected_donated=expected, label="trainer.jit_train_step")
    else:
        findings += check_donation(donated_args=tuple(state[:3]),
                                   label="trainer.jit_train_step args")
    return findings


def lint_serving_engine(engine) -> List[Finding]:
    """Donation safety over the AOT serving programs (prefill / decode /
    release — plus ``verify`` on a speculative engine — all with the
    donated cache) plus grad-sync collective placement on the decode
    program (a serving step has no business reducing gradients at
    all)."""
    import jax
    cache = engine.cache
    n = len(jax.tree_util.tree_leaves(cache))
    nbytes = cache.nbytes()
    findings = check_donation(donated_args=cache,
                              label="ServingEngine.cache")
    programs = [("prefill", engine.prefill_compiled),
                ("decode", engine.decode_compiled),
                ("release", engine.release_compiled)]
    if getattr(engine, "verify_compiled", None) is not None:
        programs.append(("verify", engine.verify_compiled))
    for name, compiled in programs:
        findings += check_donation(
            compiled, expected_donated=n, min_alias_bytes=nbytes,
            label=f"ServingEngine.{name}")
    findings += check_collective_placement(
        engine.decode_traced, axes=None, label="ServingEngine.decode")
    if getattr(engine, "verify_traced", None) is not None:
        findings += check_collective_placement(
            engine.verify_traced, axes=None, label="ServingEngine.verify")
    return findings


# ---------------------------------------------------------------------------
# CLI selfchecks: tiny clean/planted program pairs per rule
# ---------------------------------------------------------------------------

def _one_axis_mesh(*names):
    import jax
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:1]).reshape(
        (1,) * len(names)), names)


def _selfcheck_donation():
    import jax
    import jax.numpy as jnp

    def clean_fn(a, b):
        return a + 1.0, b * 2.0

    def leaky_fn(a, b):
        return a + 1.0, jnp.zeros_like(b)  # b consumed, never aliased?

    a, b = jnp.arange(4.0), jnp.arange(8.0)
    clean = check_donation(
        jax.jit(clean_fn, donate_argnums=(0, 1)).trace(a, b).lower(),
        donated_args=(a, b), expected_donated=2)
    # planted: the same buffer donated twice (the PR 9 scale-plane bug)
    shared = jnp.arange(8.0)
    planted = check_donation(donated_args={"k_scale": shared,
                                           "v_scale": shared})
    # planted #2: a donated arg the program never uses -> dropped, never
    # aliased
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        lowered = jax.jit(lambda x, dead: x + 1.0,
                          donate_argnums=(0, 1)).trace(a, b).lower()
    planted += check_donation(lowered, expected_donated=2)
    return clean, planted


def _selfcheck_collectives():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from apex_tpu.utils.compat import shard_map_unchecked

    mesh = _one_axis_mesh("data")

    def body(blessed):
        def f(g):
            def sync(g):  # the indirection the AST check cannot see
                return jax.lax.psum_scatter(g, "data", tiled=True)
            if blessed:
                with jax.named_scope("optimizer_step"):
                    return sync(g)
            return sync(g)
        return shard_map_unchecked(f, mesh=mesh, in_specs=P(),
                                   out_specs=P("data"))

    g = jnp.arange(8.0)
    clean = check_collective_placement(
        jax.make_jaxpr(body(True))(g).jaxpr, axes=("data",))
    planted = check_collective_placement(
        jax.make_jaxpr(body(False))(g).jaxpr, axes=("data",))
    return clean, planted


def _selfcheck_flat():
    import jax
    import jax.numpy as jnp

    g1, g2 = jnp.arange(24.0), jnp.arange(40.0)
    padded = g1.size + g2.size

    def bucketed(g1, g2):
        return jnp.sum(g1 * g1) + jnp.sum(g2 * g2)

    def barrier(g1, g2):
        flat = jnp.concatenate([g1, g2])  # the full-gradient barrier
        return jnp.sum(flat * flat)

    clean = check_flat_materialization(
        jax.make_jaxpr(bucketed)(g1, g2).jaxpr, (padded,))
    planted = check_flat_materialization(
        jax.make_jaxpr(barrier)(g1, g2).jaxpr, (padded,))
    return clean, planted


def _selfcheck_shared_grad():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from apex_tpu.utils.compat import shard_map_unchecked

    mesh = _one_axis_mesh("pipe")

    def body(reduced):
        def f(shared, x):
            def loss(s):
                return jnp.sum(jnp.tanh(x * s))
            g = jax.grad(loss)(shared)
            if reduced:
                g = jax.lax.psum(g, "pipe")
            return g
        return shard_map_unchecked(f, mesh=mesh, in_specs=(P(), P()),
                                   out_specs=P())

    s, x = jnp.arange(4.0), jnp.ones(4)
    clean = check_shared_grad_reduction(
        jax.make_jaxpr(body(True))(s, x).jaxpr, [(0, "shared grad")],
        "pipe")
    planted = check_shared_grad_reduction(
        jax.make_jaxpr(body(False))(s, x).jaxpr, [(0, "shared grad")],
        "pipe")
    return clean, planted


def _selfcheck_recompile():
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda x: x * 2.0)
    step(jnp.ones(4))  # warm
    with recompile_guard("selfcheck", raise_on_violation=False) as g:
        for _ in range(3):
            step(jnp.ones(4))  # steady shape: no retrace
    clean = list(g.findings)
    with recompile_guard("selfcheck", raise_on_violation=False) as g:
        for n in (5, 6, 7):
            step(jnp.ones(n))  # shape leak: retraces every iteration
    return clean, list(g.findings)


register(Rule("jaxpr-donation", "jaxpr",
              "donated leaves are aliased in-place and no buffer is "
              "donated twice (PR 9 shared int8 scale class)",
              selfcheck=_selfcheck_donation))
register(Rule("jaxpr-collectives", "jaxpr",
              "grad-sync collectives trace inside blessed chokepoint "
              "scopes even when reached through helpers the AST check "
              "cannot see", selfcheck=_selfcheck_collectives))
register(Rule("jaxpr-flat-grad", "jaxpr",
              "no padded full-gradient vector materializes in a "
              "bucketed ZeRO program (PR 8 flat barrier class)",
              selfcheck=_selfcheck_flat))
register(Rule("jaxpr-shared-grad", "jaxpr",
              "replicated-param cotangents carry a reducing collective "
              "over the mesh axis (PR 7 shared-param drift class)",
              selfcheck=_selfcheck_shared_grad))
register(Rule("jaxpr-recompile", "jaxpr",
              "compile-storm counters stay flat across a zero-recompile "
              "driver loop (PR 1 counters as a scoped assertion)",
              selfcheck=_selfcheck_recompile))
