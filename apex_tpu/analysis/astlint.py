"""Shared AST-walk core for the Family-B repo lints.

Every historical ``scripts/check_*.py`` carried its own copy of the same
boilerplate: walk the package for ``.py`` files, parse each, extract
callee names / literal strings, format a report. That lives here once;
:mod:`apex_tpu.analysis.rules_ast` holds only each rule's actual policy.

No jax import anywhere on this path — the AST family stays pre-commit
fast and runs on hosts with no accelerator stack.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, Optional, Tuple

__all__ = ["repo_root", "iter_py_files", "iter_package_trees",
           "callee_name", "literal_str", "tuple_literal", "parse_file"]

PACKAGE = "apex_tpu"


def repo_root() -> str:
    """The repository root, resolved from the installed package location
    (``<repo>/apex_tpu/analysis/astlint.py``)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def iter_py_files(root: str) -> Iterator[str]:
    """Every ``.py`` under ``root``, sorted for stable reports."""
    for dirpath, _dirnames, filenames in sorted(os.walk(root)):
        for fname in sorted(filenames):
            if fname.endswith(".py"):
                yield os.path.join(dirpath, fname)


def parse_file(path: str, rel: str) -> Optional[ast.AST]:
    """Parse one file; unparseable sources are skipped (they are the
    interpreter's problem, not a lint's)."""
    with open(path) as f:
        try:
            return ast.parse(f.read(), filename=rel)
        except SyntaxError:
            return None


def iter_package_trees(repo: str, package: str = PACKAGE
                       ) -> Iterator[Tuple[str, ast.AST]]:
    """``(relpath, parsed_tree)`` for every parseable ``.py`` in the
    package under ``repo``."""
    pkg_root = os.path.join(repo, package)
    for path in iter_py_files(pkg_root):
        rel = os.path.relpath(path, repo)
        tree = parse_file(path, rel)
        if tree is not None:
            yield rel, tree


def callee_name(node: ast.Call) -> Optional[str]:
    """The terminal callee name of a call: ``f(...)`` -> ``f``,
    ``obj.attr(...)`` -> ``attr``, anything else -> None."""
    func = node.func
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def literal_str(node) -> Optional[str]:
    """A statically-known string: plain literals pass through, f-strings
    normalize each formatted field to a ``<>`` placeholder
    (``f"health/{name}/l2"`` -> ``health/<>/l2``), anything else is
    None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant):
                parts.append(str(piece.value))
            else:  # FormattedValue
                parts.append("<>")
        return "".join(parts)
    return None


def tuple_literal(node) -> list:
    """The string elements of a tuple/list literal."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    return []
