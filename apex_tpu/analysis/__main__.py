"""CLI: ``python -m apex_tpu.analysis [--all|--rule NAME] [--json]``.

Family-B (ast) rules run over this repository tree; Family-A (jaxpr)
and Family-C (perf) rules run their built-in selfchecks — each rule's
tiny clean program/history must stay silent AND its planted violation
must fire, so a green ``--all`` proves every rule in both directions (a
rule that stopped firing is as rotten as a tree that stopped passing). Exit status: 0 clean, 1 findings
(or a broken selfcheck), 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys

from apex_tpu.analysis.astlint import repo_root
from apex_tpu.analysis.core import (format_finding, get_rule, iter_rules)


def _run_ast(rule, repo, out):
    findings, notes = rule.run(repo)
    out["rules"].append({
        "rule": rule.name, "family": "ast", "ok": not findings,
        "findings": [f.to_dict() for f in findings],
        "checked": len(notes)})
    return findings, [f"{rule.name}: {len(notes)} site(s) checked"]


def _run_jaxpr(rule, out):
    clean, planted = rule.selfcheck()
    ok = not clean and bool(planted)
    out["rules"].append({
        "rule": rule.name, "family": rule.family, "ok": ok,
        "findings": [f.to_dict() for f in clean],
        "planted_fired": len(planted)})
    findings = list(clean)
    notes = []
    if clean:
        notes.append(f"{rule.name}: selfcheck FALSE-POSITIVE on the "
                     f"clean program")
    elif not planted:
        notes.append(f"{rule.name}: selfcheck planted violation did NOT "
                     f"fire — the rule is dead")
    else:
        notes.append(f"{rule.name}: selfcheck ok (clean silent, planted "
                     f"fires {len(planted)} finding(s))")
    return findings, notes, bool(planted)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description="unified static-analysis engine: jaxpr program lints "
                    "+ AST contract checks (docs/ANALYSIS.md)")
    group = parser.add_mutually_exclusive_group()
    group.add_argument("--all", action="store_true",
                       help="run every registered rule (default)")
    group.add_argument("--rule", help="run one rule by name")
    group.add_argument("--list", action="store_true",
                       help="list registered rules")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable findings on stdout")
    parser.add_argument("--repo", default=None,
                        help="repo root for the ast family (default: "
                             "the tree this package is installed from)")
    args = parser.parse_args(argv)

    if args.list:
        for rule in iter_rules():
            print(f"{rule.name:<22} [{rule.family:>5}]  {rule.doc}")
        return 0

    try:
        rules = [get_rule(args.rule)] if args.rule else list(iter_rules())
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2

    repo = args.repo or repo_root()
    out = {"repo": repo, "rules": []}
    all_findings, report, dead = [], [], []
    for rule in rules:
        if rule.family == "ast":
            findings, notes = _run_ast(rule, repo, out)
            all_findings += findings
            report += notes
        else:
            clean, notes, fired = _run_jaxpr(rule, out)
            all_findings += clean
            report += notes
            if not fired:
                dead.append(rule.name)

    ok = not all_findings and not dead
    if args.as_json:
        out["ok"] = ok
        print(json.dumps(out, indent=2))
    else:
        for line in report:
            print(line)
        for f in all_findings:
            print(format_finding(f))
        verdict = "clean" if ok else \
            f"{len(all_findings)} finding(s)" + \
            (f", dead rule(s): {dead}" if dead else "")
        print(f"apex_tpu.analysis: {len(rules)} rule(s) -> {verdict}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
