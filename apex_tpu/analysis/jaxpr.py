"""Shared jaxpr-inspection helpers (promoted from ``tests/_jaxpr_utils.py``).

Three suites (parallel/DDP, collective matmul, health) pin *program shape*
— collective counts, zero-cost-off identity — on the traced jaxpr, and the
Family-A program lints in :mod:`apex_tpu.analysis.program` are built on
the same walks. The helpers live here once; ``tests/_jaxpr_utils.py`` is a
re-import shim so older test imports keep resolving:

- :func:`jaxpr_str` — trace + normalize embedded object addresses, so two
  closures tracing identical programs compare equal;
- :func:`count_primitives` — substring census over the jaxpr text (the
  cheap check: primitive names like ``psum`` / ``ppermute`` appear only as
  equation heads in jaxpr pretty-printing);
- :func:`collective_census` — the ring-decomposition census
  (ppermute / all_gather / reduce_scatter) used by the collective-matmul
  and ZeRO bucketing assertions;
- :func:`iter_eqns` / :func:`count_eqns` — structural walk over the jaxpr
  (recursing into sub-jaxprs) for assertions that need equation *params*
  (axis names, operand sizes), where text matching would be ambiguous;
- :func:`eqn_scopes` / :func:`iter_eqns_scoped` — ``named_scope``
  provenance per equation (ancestor wrapper scopes threaded into
  sub-jaxprs), the blessed-chokepoint vocabulary of the collective
  placement lint;
- :func:`cone_has_reduction` — "is there a ``psum``-class reduction over
  axis X anywhere in this output's dependency cone" — the shared-gradient
  replication-soundness walk.
"""

from __future__ import annotations

import re

import jax

__all__ = ["jaxpr_str", "count_primitives", "collective_census",
           "iter_eqns", "count_eqns", "eqn_axes", "flat_materializations",
           "sub_jaxprs", "jaxpr_of", "eqn_scopes", "iter_eqns_scoped",
           "cone_has_reduction", "REDUCING_PRIMITIVES"]


def eqn_axes(eqn) -> tuple:
    """The mesh axes a collective equation reduces over, normalized to a
    tuple of names. reduce_scatter/all_gather carry ``axis_name``; psum
    (and 0.4.x check_rep's ``psum2`` spelling) carries ``axes``."""
    ax = eqn.params.get("axis_name") or eqn.params.get("axes")
    return (ax,) if isinstance(ax, str) else tuple(ax or ())


def jaxpr_str(fn, *args) -> str:
    """Jaxpr text with embedded object addresses normalized: two trainers
    build distinct model closures, and their reprs (``<function ... at
    0x...>``) would differ even when the traced programs are identical."""
    return re.sub(r"0x[0-9a-f]+", "0x", str(jax.make_jaxpr(fn)(*args)))


def count_primitives(text: str, *names: str) -> dict:
    """``{name: substring count}`` over jaxpr text. Order names from most
    to least specific when one is a prefix of another and subtract at the
    call site (e.g. ``psum`` also matches ``psum2``-style variants)."""
    return {name: text.count(name) for name in names}


def collective_census(text: str) -> dict:
    """The collective census shared by the ring-decomposition and
    DP-bucketing structural tests."""
    return {"ppermute": text.count("ppermute"),
            "all_gather": text.count("all_gather"),
            "reduce_scatter": text.count("reduce_scatter")}


def iter_eqns(jaxpr):
    """Depth-first over every equation, recursing into sub-jaxprs
    (closed call/scan/shard_map bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in sub_jaxprs(v):
                yield from iter_eqns(sub)


def sub_jaxprs(value):
    """Yield every (open) jaxpr reachable from one eqn param value."""
    try:  # the classes moved out of jax.core on the current-jax line
        from jax.extend.core import ClosedJaxpr, Jaxpr
    except ImportError:  # pragma: no cover - early 0.4.x
        from jax.core import ClosedJaxpr, Jaxpr
    if isinstance(value, ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, Jaxpr):
        yield value
    elif isinstance(value, (list, tuple)):
        for item in value:
            yield from sub_jaxprs(item)


# kept for the legacy underscore spelling some suites imported
_sub_jaxprs = sub_jaxprs


def jaxpr_of(program, args=None):
    """The open jaxpr behind ``program``: an open ``Jaxpr`` passes
    through, a ``ClosedJaxpr`` or anything with a ``.jaxpr``
    (``jax.jit(f).trace(*args)``) unwraps, and a callable traces via
    ``jax.make_jaxpr`` when ``args`` are supplied. A bare
    ``Compiled``/``Lowered`` has already erased its jaxpr — hold the
    ``Traced`` stage instead."""
    inner = getattr(program, "jaxpr", None)
    if inner is not None and inner is not program:
        return jaxpr_of(inner)  # ClosedJaxpr / Traced -> the open jaxpr
    if hasattr(program, "eqns"):
        return program
    if callable(program) and args is not None:
        return jax.make_jaxpr(program)(*args).jaxpr
    raise TypeError(
        f"cannot recover a jaxpr from {type(program).__name__}: pass a "
        "(Closed)Jaxpr, a traced stage (jax.jit(f).trace(*args)), or a "
        "callable plus example args")


def flat_materializations(jaxpr, size, dtype="float32") -> list:
    """Primitive names of equations that OUTPUT a 1-D ``dtype`` array of
    exactly ``size`` elements — the structural detector for "the full
    padded flat gradient materialized" (the barrier the span-local
    bucketed ravel/unravel removes). Wrapper equations carrying
    sub-jaxprs (shard_map/pjit/scan/...) are excluded: their outvars are
    aggregate *views* (e.g. the global aval of a sharded ZeRO master),
    not buffers the per-device program builds — any real materialization
    inside them is a leaf equation this walk still visits."""
    out = []
    for eqn in iter_eqns(jaxpr):
        if any(True for v in eqn.params.values() for _ in sub_jaxprs(v)):
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            if getattr(aval, "ndim", None) == 1 and aval.size == size \
                    and str(getattr(aval, "dtype", "")) == dtype:
                out.append(eqn.primitive.name)
    return out


def count_eqns(fn_or_jaxpr, name, *args, where=None) -> int:
    """Number of equations whose primitive is ``name``; ``where(eqn)``
    filters (e.g. on ``eqn.params['axis_name']`` or operand aval sizes).
    Pass a traceable callable plus its args, or an already-made
    (Closed)Jaxpr."""
    if callable(fn_or_jaxpr) and not hasattr(fn_or_jaxpr, "eqns"):
        jaxpr = jax.make_jaxpr(fn_or_jaxpr)(*args).jaxpr
    else:
        jaxpr = getattr(fn_or_jaxpr, "jaxpr", fn_or_jaxpr)
    return sum(1 for eqn in iter_eqns(jaxpr)
               if eqn.primitive.name == name
               and (where is None or where(eqn)))


# ---------------------------------------------------------------------------
# named_scope provenance
# ---------------------------------------------------------------------------

def eqn_scopes(eqn) -> str:
    """The ``named_scope`` stack string of one equation (empty when the
    equation was traced outside any scope). Transform wrappers may
    decorate names (``jvp(flash_attention)``); match scope names with a
    word-boundary search, not equality."""
    si = getattr(eqn, "source_info", None)
    ns = getattr(si, "name_stack", None)
    return "" if ns is None else str(ns)


def iter_eqns_scoped(jaxpr, _prefix: str = ""):
    """Depth-first ``(eqn, scope_stack_str)`` over every equation. The
    scope string accumulates ancestor wrapper equations' stacks, so an
    equation inside a scan whose *call site* sat under a scope still
    reports that scope."""
    for eqn in jaxpr.eqns:
        own = eqn_scopes(eqn)
        stack = "/".join(s for s in (_prefix, own) if s)
        yield eqn, stack
        for v in eqn.params.values():
            for sub in sub_jaxprs(v):
                yield from iter_eqns_scoped(sub, stack)


def scope_matches(stack: str, names) -> bool:
    """True when any of ``names`` appears as a whole scope word in the
    accumulated stack string."""
    return any(re.search(rf"\b{re.escape(n)}\b", stack) for n in names)


# ---------------------------------------------------------------------------
# dependency-cone reduction search (shared-gradient soundness)
# ---------------------------------------------------------------------------

# primitives that REDUCE over a mesh axis (0.4.x check_rep prints psum as
# psum2); all_gather is a broadcasting collective, not a reduction
REDUCING_PRIMITIVES = ("psum", "psum2", "psum_invariant", "psum_scatter",
                       "reduce_scatter", "all_reduce")


def _is_reduction(eqn, axis: str) -> bool:
    return (eqn.primitive.name in REDUCING_PRIMITIVES
            and axis in eqn_axes(eqn))


def _producer_map(jaxpr) -> dict:
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


def cone_has_reduction(jaxpr, out_index: int, axis: str) -> bool:
    """True when a reducing collective over ``axis`` appears in the
    dependency cone of output ``out_index``.

    The walk is conservative toward *silence* (it over-approximates the
    cone rather than under-finding reductions): wrapper equations whose
    sub-jaxpr outputs align 1:1 with the equation outputs
    (pjit/shard_map/scan/closed call) are descended precisely at the
    matching output index; wrappers with no such alignment count as
    reduced if a reduction over ``axis`` appears ANYWHERE inside them;
    and the walk always continues upstream through every wrapper input.
    """
    target = jaxpr.outvars[out_index]
    return _cone_walk(jaxpr, [target], axis, set())


def _cone_walk(jaxpr, roots, axis: str, seen: set) -> bool:
    producers = _producer_map(jaxpr)
    # Literals ride in var positions and are unhashable — never producers
    stack = [v for v in roots if not hasattr(v, "val")]
    while stack:
        var = stack.pop()
        eqn = producers.get(var)
        if eqn is None:
            continue  # an input or constant of this jaxpr
        key = (id(jaxpr), id(eqn))
        if key in seen:
            continue
        seen.add(key)
        if _is_reduction(eqn, axis):
            return True
        subs = [s for v in eqn.params.values() for s in sub_jaxprs(v)]
        if subs:
            aligned = [s for s in subs
                       if len(s.outvars) == len(eqn.outvars)]
            if aligned:
                idx = list(eqn.outvars).index(var)
                for sub in aligned:
                    if _cone_walk(sub, [sub.outvars[idx]], axis, seen):
                        return True
            else:
                for sub in subs:
                    if any(_is_reduction(e, axis)
                           for e in iter_eqns(sub)):
                        return True
        stack.extend(v for v in eqn.invars if not hasattr(v, "val"))
    return False
