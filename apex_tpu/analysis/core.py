"""Rule registry + structured findings for the static-analysis engine.

One vocabulary for both rule families:

- **Family A (jaxpr)** — program lints: a rule takes a traced / lowered /
  compiled program (plus rule-specific context) and returns
  :class:`Finding`\\ s. They run wherever a program exists — construction
  self-checks (``ServingEngine``), the dryrun gate, tests — and each one
  carries a CLI ``selfcheck`` that proves the rule on a tiny built-in
  clean/planted program pair.
- **Family B (ast)** — repo lints: a rule takes a repo root and AST-walks
  the package (no jax import). These are the six historical
  ``scripts/check_*.py`` contracts plus the metric-family meta-lint,
  consolidated onto one walker core (:mod:`apex_tpu.analysis.astlint`).
- **Family C (perf)** — selfcheck-only dynamic detectors (the perfwatch
  regression detector): like the jaxpr family they expose
  ``selfcheck() -> (clean, planted)`` and ride the same CLI leg — a
  detector that stops firing on its planted regression fails ``--all``
  like a finding (the PR 11 dead-rule convention).

``python -m apex_tpu.analysis --all`` runs every registered rule; each
``scripts/check_*.py`` shim runs exactly its ported rule with the
historical ``check(repo) -> (ok, lines)`` surface preserved.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Finding", "Rule", "AnalysisError", "RULES", "register",
           "get_rule", "iter_rules", "format_finding", "findings_to_ok_lines"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured violation.

    ``rule`` names the emitting rule; ``kind`` is the short marker the
    historical scripts printed (``RAW``, ``UNDOC``, ``ORPHAN``, ``EXIT``,
    ``MISSING``, ``UNKNOWN``, ``CHOKE``, and the new jaxpr-rule markers);
    ``where`` locates it (``file:line`` for AST rules, a program/equation
    description for jaxpr rules); ``message`` says what broke and how to
    fix or allowlist it.
    """
    rule: str
    kind: str
    where: str
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class AnalysisError(RuntimeError):
    """A self-check or construction-time lint failed. Carries the
    findings that fired."""

    def __init__(self, message: str, findings: Tuple[Finding, ...] = ()):
        super().__init__(message)
        self.findings = tuple(findings)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One registered rule.

    ``run``: for AST rules, ``run(repo) -> (findings, notes)`` where
    ``notes`` are the ``ok``-class report lines the historical scripts
    printed. jaxpr rules have no repo-wide ``run``; they are invoked
    programmatically (see :mod:`apex_tpu.analysis.program`) and expose
    ``selfcheck() -> (clean_findings, planted_findings)`` instead — the
    CLI asserts the clean program stays silent AND the planted violation
    fires, so ``--all`` proves every rule in both directions.
    """
    name: str
    family: str  # 'ast' | 'jaxpr' | 'perf'
    doc: str     # one line: the real bug class this rule encodes
    run: Optional[Callable[[str], Tuple[List[Finding], List[str]]]] = None
    selfcheck: Optional[
        Callable[[], Tuple[List[Finding], List[Finding]]]] = None


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.name in RULES:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    if rule.family not in ("ast", "jaxpr", "perf"):
        raise ValueError(f"unknown rule family {rule.family!r}")
    RULES[rule.name] = rule
    return rule


def get_rule(name: str) -> Rule:
    _ensure_loaded()
    try:
        return RULES[name]
    except KeyError:
        known = ", ".join(sorted(RULES))
        raise KeyError(f"unknown rule {name!r}; registered: {known}")


def iter_rules(family: Optional[str] = None):
    _ensure_loaded()
    for name in sorted(RULES):
        rule = RULES[name]
        if family is None or rule.family == family:
            yield rule


def _ensure_loaded() -> None:
    """Rule modules register on import; AST rules are import-light
    (stdlib ast only), jaxpr rules import jax lazily inside their
    bodies, the perf family imports only the (jax-free) perfwatch
    module."""
    from apex_tpu.analysis import (program, rules_ast,  # noqa: F401
                                   rules_perf)


def format_finding(f: Finding) -> str:
    return f"{f.kind:<8} {f.where}: {f.message}" if f.where else \
        f"{f.kind:<8} {f.message}"


def findings_to_ok_lines(findings: List[Finding],
                         notes: List[str]) -> Tuple[bool, List[str]]:
    """The historical ``check(repo) -> (ok, report_lines)`` shape the
    script shims preserve."""
    lines = list(notes) + [format_finding(f) for f in findings]
    return not findings, lines
