"""Unified static-analysis engine: jaxpr program lints + AST contract
checks.

Two rule families behind one registry and one CLI
(``python -m apex_tpu.analysis [--all|--rule NAME] [--json]``):

- **Family A (jaxpr)** — :mod:`apex_tpu.analysis.program`: rules that
  take a traced/lowered/compiled program and emit structured findings
  for the bug classes this repo previously caught late with hand-written
  one-off checks — donation safety (PR 9's double-donated scale buffer),
  collective chokepoint placement at the equation level, the
  flat-gradient materialization barrier (PR 8), shared-grad replication
  soundness under ``shard_map_unchecked`` (PR 7's drift), and the
  zero-recompile budget (:class:`recompile_guard`).
- **Family B (ast)** — :mod:`apex_tpu.analysis.rules_ast`: the six
  ``scripts/check_*.py`` contract checks consolidated onto one AST-walk
  core (:mod:`apex_tpu.analysis.astlint`), plus the metric-family
  meta-lint. The scripts remain as thin shims.

Shared jaxpr walks live in :mod:`apex_tpu.analysis.jaxpr` (promoted from
``tests/_jaxpr_utils.py``). Rule table + allowlisting instructions:
``docs/ANALYSIS.md``.

The analysis modules themselves import no jax until a Family-A rule
actually runs (Family B is stdlib-``ast`` only), so the AST family and
the script shims stay pre-commit fast — the only jax cost at import is
the parent package's own.
"""

from apex_tpu.analysis.core import (  # noqa: F401
    AnalysisError, Finding, Rule, RULES, format_finding, get_rule,
    iter_rules, register)
from apex_tpu.analysis.rules_ast import (  # noqa: F401
    rule_annotations, rule_bench_configs, rule_collectives,
    rule_elastic_exits, rule_metric_families, rule_metrics_doc,
    rule_remat_names)

__all__ = ["AnalysisError", "Finding", "Rule", "RULES", "format_finding",
           "get_rule", "iter_rules", "register",
           # Family A (lazy: importing them pulls jax)
           "check_donation", "check_collective_placement",
           "check_flat_materialization", "check_shared_grad_reduction",
           "lint_program", "lint_trainer_step", "lint_serving_engine",
           "recompile_guard", "verify_findings",
           "DEFAULT_BLESSED_SCOPES", "GRAD_SYNC_COLLECTIVES"]

_PROGRAM_NAMES = ("check_donation", "check_collective_placement",
                  "check_flat_materialization",
                  "check_shared_grad_reduction", "lint_program",
                  "lint_trainer_step", "lint_serving_engine",
                  "recompile_guard", "verify_findings",
                  "DEFAULT_BLESSED_SCOPES", "GRAD_SYNC_COLLECTIVES")


def __getattr__(name):
    if name in _PROGRAM_NAMES:
        from apex_tpu.analysis import program
        return getattr(program, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
