"""Family-C rule: the perfwatch regression detector's selfcheck.

A regression detector that silently stops firing is worse than no
detector — every later bench round reads as "no regressions" while the
trajectory rots. So the detector registers here as a selfcheck-only
rule, per the PR 11 convention: ``python -m apex_tpu.analysis --all``
runs it alongside the jaxpr selfchecks, a clean synthetic history must
stay silent, and a planted 20% throughput drop must fire — *with the
suspect region attributed* (a firing without a region means the
AttributionDiff wiring rotted, and is reported dead all the same).

The perfwatch module is jax-free, so this family keeps the CLI's
no-accelerator path fast. Details: docs/ANALYSIS.md, and
docs/OBSERVABILITY.md "Performance observatory".
"""

from __future__ import annotations

from typing import List, Tuple

from apex_tpu.analysis.core import Finding, Rule, register

__all__ = ["perfwatch_selfcheck"]


def perfwatch_selfcheck() -> Tuple[List[Finding], List[Finding]]:
    """``(clean_findings, planted_findings)`` over the built-in
    synthetic histories (see
    :func:`apex_tpu.observability.perfwatch.selfcheck`)."""
    from apex_tpu.observability.perfwatch import selfcheck
    clean, planted = selfcheck()

    def _wrap(finding) -> Finding:
        kind = "DRIFT" if type(finding).__name__ == "DriftShift" \
            else "REGRESSION"
        return Finding("perf-regression", kind, finding.metric,
                       finding.message())

    return [_wrap(f) for f in clean], [_wrap(f) for f in planted]


register(Rule(
    "perf-regression", "perf",
    "the perfwatch detector still fires: clean synthetic history "
    "silent, planted 20% drop flagged with its suspect region",
    selfcheck=perfwatch_selfcheck))
