"""ZeRO-style sharded-optimizer data parallelism.

Reference: ``reference:apex/contrib/optimizers/distributed_fused_adam.py``
(flat grad buffer, ``reduce_scatter`` of grads :409, optimizer state sharded
across the DP group :202-207, ``all_gather`` of updated params :477, comm
overlapped with bprop via grad hooks :162) and ``distributed_fused_lamb.py``
(same scheme + global grad-norm clip + per-tensor trust ratios).

TPU redesign: the whole scheme collapses to three collectives inside
``shard_map`` over the ``data`` mesh axis:

1. grads (replicated layout, one pytree per device) are raveled into one
   flat fp32 vector and ``psum_scatter``'d — each device receives the
   *summed* 1/dp shard it owns, the exact ``reduce_scatter`` of :409;
2. optimizer math (Adam/LAMB, fp32 master params + moments) runs on the
   flat shard only — per-device optimizer state is 1/dp of the dense
   version, the ZeRO memory win;
3. the updated master shard is ``all_gather``'d (:477) and unraveled back
   to the parameter pytree in the parameter dtype.

The reference's manual comm/compute overlap (grad hooks kicking off
reduce-scatters mid-backward, stream pools) is XLA's job here: with the
train step jitted end to end, the latency-hiding scheduler overlaps the
psum_scatter with the tail of the backward. Donate the optimizer state to
avoid the post-backward copy wall.

Per-tensor quantities (LAMB trust ratios) survive the flat layout via a
static segment-id map from flat index to tensor index (``segment_sum`` on
the shard + ``psum`` = exact per-tensor norms, the role of
``multi_tensor_l2norm`` in ``distributed_fused_lamb.py:435-470``).

``init`` must run inside ``shard_map`` (it slices this rank's shard with
``axis_index``); the natural place is the first jitted train step or an
explicit jitted init step.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import OptimizerBase, bias_correction
from apex_tpu.optimizers._flatten import (FlatLayout, build_layout, ravel,
                                          segment_ids, unravel)
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB",
           "ZeroAdamState", "ZeroLambState"]


# invariant-typed gather shared with the SP/CP layer
from apex_tpu.utils.vma import invariant_all_gather as _all_gather_flat


class ZeroAdamState(NamedTuple):
    step: jnp.ndarray     # i32 scalar
    master: jnp.ndarray   # fp32 flat shard of master params
    exp_avg: jnp.ndarray  # fp32 flat shard
    exp_avg_sq: jnp.ndarray


# identical layout; one definition so shard-spec plumbing is shared
ZeroLambState = ZeroAdamState


class _DistributedFusedBase(OptimizerBase):
    """Shared flat-shard plumbing, built on the same
    :mod:`apex_tpu.optimizers._flatten` layout helpers as
    :class:`~apex_tpu.optimizers.FlatOptimizer` (``chunks`` = dp here)."""

    def __init__(self, axis_name: str = "data"):
        self.axis_name = axis_name
        self._layout: Optional[FlatLayout] = None

    # -- flat layout ------------------------------------------------------
    def _dp(self, lay: FlatLayout) -> int:
        return lay.padded // lay.chunk

    def _layout_for(self, params: Any) -> FlatLayout:
        lay = build_layout(params, chunks=_axis_size(self.axis_name))
        if self._layout is not None and (
                self._layout.shapes != lay.shapes
                or self._layout.chunk != lay.chunk):
            raise ValueError("parameter structure changed between calls")
        self._layout = lay
        return lay

    def _my_slice(self, flat: jnp.ndarray, lay: FlatLayout) -> jnp.ndarray:
        rank = jax.lax.axis_index(self.axis_name)
        return jax.lax.dynamic_slice_in_dim(flat, rank * lay.chunk, lay.chunk)

    def _shard_grads(self, grads: Any, lay: FlatLayout) -> jnp.ndarray:
        """reduce_scatter: flat-averaged grads, this rank's shard only."""
        flat_g = ravel(grads, lay)
        g = jax.lax.psum_scatter(flat_g, self.axis_name, scatter_dimension=0,
                                 tiled=True)
        return g / self._dp(lay)

    def _gather_params(self, master: jnp.ndarray, lay: FlatLayout,
                       like: Any = None) -> Any:
        flat = _all_gather_flat(master, self.axis_name, axis=0)
        new_params = unravel(flat, lay)
        if like is None:
            return new_params
        # the flat master mixes leaves with different varying-axes sets, so
        # every unraveled leaf inherits the union (e.g. an LN weight comes
        # back typed tensor-varying next to TP-sharded leaves). Replicated-
        # by-construction leaves are value-identical across those extra
        # axes, so a pmean is a value identity that restores each leaf's
        # original type (required by the caller's out_specs).

        from apex_tpu.utils.vma import leaf_vma

        def rec(n, p):
            extra = leaf_vma(n) - leaf_vma(p)
            if extra:
                n = jax.lax.pmean(n, tuple(sorted(extra)))
            return n

        return jax.tree_util.tree_map(rec, new_params, like)


class DistributedFusedAdam(_DistributedFusedBase):
    """ZeRO sharded Adam/AdamW (``distributed_fused_adam.py:9``).

    Numerics match :class:`apex_tpu.optimizers.FusedAdam` with DDP grad
    averaging, while per-device optimizer state (fp32 master + m + v) is
    1/dp of the dense version.
    """

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 axis_name: str = "data"):
        super().__init__(axis_name)
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def init(self, params: Any) -> ZeroAdamState:
        lay = self._layout_for(params)
        master = self._my_slice(ravel(params, lay), lay)
        zeros = jnp.zeros(lay.chunk, jnp.float32)
        return ZeroAdamState(step=jnp.asarray(0, jnp.int32), master=master,
                             exp_avg=zeros, exp_avg_sq=zeros)

    def _step(self, grads: Any, state: ZeroAdamState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None
              ) -> Tuple[Any, ZeroAdamState]:
        lay = self._layout_for(params)
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            bc2 = bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2 = self.beta1, self.beta2

        g = self._shard_grads(grads, lay)
        p32 = state.master
        if not self.adam_w_mode:
            g = g + wd * p32
        m = b1 * state.exp_avg + (1.0 - b1) * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
        if self.adam_w_mode:
            update = update + wd * p32
        new_master = p32 - lr * update
        new_params = self._gather_params(new_master, lay, like=params)
        return new_params, ZeroAdamState(step=t, master=new_master,
                                         exp_avg=m, exp_avg_sq=v)


class DistributedFusedLAMB(_DistributedFusedBase):
    """ZeRO sharded LAMB (``distributed_fused_lamb.py:10``): global grad-norm
    clip, then per-tensor trust ratios — per-tensor norms come from
    ``segment_sum`` on the flat shard + ``psum`` (exact, not approximated).
    """

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, axis_name: str = "data"):
        super().__init__(axis_name)
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params: Any) -> ZeroLambState:
        lay = self._layout_for(params)
        master = self._my_slice(ravel(params, lay), lay)
        zeros = jnp.zeros(lay.chunk, jnp.float32)
        return ZeroLambState(step=jnp.asarray(0, jnp.int32), master=master,
                             exp_avg=zeros, exp_avg_sq=zeros)

    def _per_tensor(self, vec_sq: jnp.ndarray, seg: jnp.ndarray,
                    lay: FlatLayout) -> jnp.ndarray:
        """psum of shard-local segment sums -> per-tensor sums (n_tensors+1,
        last slot is padding)."""
        part = jax.ops.segment_sum(vec_sq, seg, num_segments=len(lay.sizes) + 1)
        return jax.lax.psum(part, self.axis_name)

    def _step(self, grads: Any, state: ZeroLambState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None
              ) -> Tuple[Any, ZeroLambState]:
        lay = self._layout_for(params)
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            bc2 = bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2 = self.beta1, self.beta2
        seg = self._my_slice(segment_ids(lay), lay)

        g = self._shard_grads(grads, lay)
        # phase 1: global grad-norm clip (reference fused_lamb.py:124-152)
        gnorm_sq = jax.lax.psum(jnp.sum(g * g), self.axis_name)
        gnorm = jnp.sqrt(gnorm_sq)
        clip = jnp.where(
            (self.max_grad_norm > 0) & (gnorm > self.max_grad_norm),
            gnorm / self.max_grad_norm, 1.0)
        g = g / clip

        p32 = state.master
        m = b1 * state.exp_avg + (1.0 - b1) * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps) + wd * p32

        # phase 2: per-tensor trust ratios
        p_norm = jnp.sqrt(self._per_tensor(p32 * p32, seg, lay))
        u_norm = jnp.sqrt(self._per_tensor(update * update, seg, lay))
        if self.use_nvlamb:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / u_norm, 1.0)
        new_master = p32 - lr * jnp.take(ratio, seg) * update
        new_params = self._gather_params(new_master, lay, like=params)
        return new_params, ZeroLambState(step=t, master=new_master,
                                         exp_avg=m, exp_avg_sq=v)
