"""ZeRO-style sharded-optimizer data parallelism.

Reference: ``reference:apex/contrib/optimizers/distributed_fused_adam.py``
(flat grad buffer, ``reduce_scatter`` of grads :409, optimizer state sharded
across the DP group :202-207, ``all_gather`` of updated params :477, comm
overlapped with bprop via grad hooks :162) and ``distributed_fused_lamb.py``
(same scheme + global grad-norm clip + per-tensor trust ratios).

TPU redesign: the whole scheme collapses to three collectives inside
``shard_map`` over the ``data`` mesh axis:

1. grads (replicated layout, one pytree per device) are raveled into one
   flat fp32 vector and ``psum_scatter``'d — each device receives the
   *summed* 1/dp shard it owns, the exact ``reduce_scatter`` of :409;
2. optimizer math (Adam/LAMB, fp32 master params + moments) runs on the
   flat shard only — per-device optimizer state is 1/dp of the dense
   version, the ZeRO memory win;
3. the updated master shard is ``all_gather``'d (:477) and unraveled back
   to the parameter pytree in the parameter dtype.

The reference's manual comm/compute overlap (grad hooks kicking off
reduce-scatters mid-backward, stream pools) is XLA's job here: with the
train step jitted end to end, the latency-hiding scheduler overlaps the
psum_scatter with the tail of the backward. Donate the optimizer state to
avoid the post-backward copy wall.

**Bucketing** (``bucket_bytes=...``): one monolithic reduce-scatter +
all-gather leaves the scheduler nothing to overlap *within* the optimizer
phase — the whole gather waits on the whole update which waits on the
whole scatter. With ``bucket_bytes`` set, the flat vector is carved into
B fixed-size buckets on the shared :func:`~apex_tpu.optimizers._flatten.
bucket_bounds` grid (each a multiple of dp): grads reduce-scatter
per-bucket through the :func:`~apex_tpu.parallel.distributed.
reduce_scatter_grads` chokepoint, Adam's moment/update math runs
per-bucket-shard, and each bucket's updated master all-gathers as soon as
its own math is done — bucket k's gather transfer rides under bucket
k+1's update (and, schedule permitting, under the next step's first
forward, since the gathered params are the only consumers). The master
shard's element order becomes bucket-major (rank slices *within* each
bucket, concatenated) — ``init``/``step``/gather all derive it from the
same static grid, and ``bucket_bytes`` must therefore be identical across
``init`` and every ``step`` (it is a layout property, like dp).
``bucket_bytes=None`` (default) is the single-bucket monolithic path,
numerically and collectively identical to the pre-bucketing module.

Per-tensor quantities (LAMB trust ratios) survive the flat layout via a
static segment-id map from flat index to tensor index (``segment_sum`` on
the shard + ``psum`` = exact per-tensor norms, the role of
``multi_tensor_l2norm`` in ``distributed_fused_lamb.py:435-470``).

``init`` must run inside ``shard_map`` (it slices this rank's shard with
``axis_index``); the natural place is the first jitted train step or an
explicit jitted init step.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.observability import ingraph as _metrics
from apex_tpu.optimizers._base import OptimizerBase, bias_correction
from apex_tpu.optimizers._flatten import (FlatLayout, bucket_bounds,
                                          build_layout, ravel,
                                          ravel_span, segment_ids,
                                          unravel_parts)
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["DistributedFusedAdam", "DistributedFusedLAMB",
           "ZeroAdamState", "ZeroLambState"]


# invariant-typed gather shared with the SP/CP layer
from apex_tpu.utils.vma import invariant_all_gather as _all_gather_flat


class ZeroAdamState(NamedTuple):
    step: jnp.ndarray     # i32 scalar
    master: jnp.ndarray   # fp32 flat shard of master params
    exp_avg: jnp.ndarray  # fp32 flat shard
    exp_avg_sq: jnp.ndarray
    # bucket-grid stamp: the bucket_bytes this state's shard layout was
    # built with (0 = monolithic), i32 scalar. The flat shards are
    # bucket-major, so stepping a state under a *different* grid — e.g. a
    # checkpoint trained with one ddp_bucket_bytes restored into a config
    # with another — would silently permute every master/moment element;
    # :meth:`_DistributedFusedBase.check_state` compares this stamp
    # against the optimizer's config wherever the state is concrete (the
    # trainer's jit boundary, eager steps) and fails loudly instead.
    bucket_stamp: Any = 0


# identical layout; one definition so shard-spec plumbing is shared
ZeroLambState = ZeroAdamState


def _cat(parts: list) -> jnp.ndarray:
    """Concat per-bucket pieces; the monolithic single-bucket path skips
    the copy (one definition so the two paths cannot diverge)."""
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


class _DistributedFusedBase(OptimizerBase):
    """Shared flat-shard plumbing, built on the same
    :mod:`apex_tpu.optimizers._flatten` layout helpers as
    :class:`~apex_tpu.optimizers.FlatOptimizer` (``chunks`` = dp here)."""

    def __init__(self, axis_name: str = "data",
                 bucket_bytes: Optional[int] = None):
        self.axis_name = axis_name
        self.bucket_bytes = bucket_bytes
        self._layout: Optional[FlatLayout] = None

    # -- flat layout ------------------------------------------------------
    def _dp(self, lay: FlatLayout) -> int:
        return lay.padded // lay.chunk

    def _layout_for(self, params: Any) -> FlatLayout:
        lay = build_layout(params, chunks=_axis_size(self.axis_name))
        if self._layout is not None and (
                self._layout.shapes != lay.shapes
                or self._layout.chunk != lay.chunk):
            raise ValueError("parameter structure changed between calls")
        self._layout = lay
        return lay

    def _bounds(self, lay: FlatLayout):
        """Global ``(offset, size)`` bucket spans (one span = monolithic)."""
        return bucket_bounds(lay, self.bucket_bytes)

    def _stamp(self) -> jnp.ndarray:
        return jnp.asarray(self.bucket_bytes or 0, jnp.int32)

    def check_state(self, state: Any) -> None:
        """Loud guard for the bucket-grid/state-layout contract: raises
        ``ValueError`` when ``state`` was built under a different
        ``bucket_bytes`` than this optimizer's (the shard order would
        silently permute). A no-op on traced values — call where the
        state is concrete: :meth:`GPTHybridTrainer.jit_train_step` does,
        which is exactly where a restored checkpoint re-enters the step."""
        stamp = getattr(state, "bucket_stamp", None)
        if stamp is None:
            return
        try:
            got = int(stamp)
        except Exception:  # traced: the host-boundary caller owns the check
            return
        expected = int(self.bucket_bytes or 0)
        if got != expected:
            raise ValueError(
                f"ZeRO state was built with bucket_bytes="
                f"{got or None} but this optimizer is configured with "
                f"bucket_bytes={self.bucket_bytes}; the flat shard layout "
                f"is bucket-major, so stepping it would silently permute "
                f"master params and moments. Rebuild the state (init) or "
                f"restore with the matching ddp_bucket_bytes.")

    def _shard_bounds(self, lay: FlatLayout):
        """``(offset, size)`` spans of each bucket's slice *within this
        rank's shard* (the shard is the bucket-major concat of per-bucket
        rank slices)."""
        dp = self._dp(lay)
        out, off = [], 0
        for _goff, n in self._bounds(lay):
            out.append((off, n // dp))
            off += n // dp
        return tuple(out)

    def _my_slice(self, flat: jnp.ndarray, lay: FlatLayout) -> jnp.ndarray:
        """This rank's master shard: its ``1/dp`` slice of every bucket,
        concatenated bucket-major (a single contiguous slice when
        unbucketed)."""
        rank = jax.lax.axis_index(self.axis_name)
        dp = self._dp(lay)
        parts = [
            jax.lax.dynamic_slice_in_dim(flat, off + rank * (n // dp),
                                         n // dp)
            for off, n in self._bounds(lay)]
        return _cat(parts)

    def _shard_grad_parts(self, grads: Any, lay: FlatLayout) -> list:
        """Per-bucket reduce_scatter: flat-averaged grads, this rank's slice
        of each bucket — B independent collectives the scheduler can overlap
        with the per-bucket update math downstream. Each bucket is raveled
        span-locally (``_flatten.ravel_span``): its reduce-scatter consumes
        only the grad leaves in its span, so the scheduler can issue it
        under the tail of the backward (and under the accumulation window)
        as soon as those leaves exist, instead of waiting on a full-tree
        concatenate of every gradient."""
        from apex_tpu.parallel.distributed import reduce_scatter_grads
        bounds = self._bounds(lay)
        if _metrics.recording():
            _metrics.record("ddp/reduce_scatter_bytes",
                            float(4 * lay.padded), reduce="sum")
            _metrics.record("zero/shard_bytes", float(4 * lay.chunk),
                            reduce="mean")
            if self.bucket_bytes is not None:
                # the bucket-grid metrics are the bucketed path's contract
                # (docs/OBSERVABILITY.md) — a monolithic ZeRO step must
                # not report a degenerate 1-bucket grid as bucketing-on
                _metrics.record("ddp/num_buckets", float(len(bounds)),
                                reduce="mean")
                _metrics.record("ddp/bucket_bytes",
                                float(4 * max(n for _, n in bounds)),
                                reduce="mean")
        inv_dp = 1.0 / self._dp(lay)
        return [
            reduce_scatter_grads(
                ravel_span(grads, lay, off, n),
                self.axis_name) * inv_dp
            for off, n in bounds]

    def _shard_grads(self, grads: Any, lay: FlatLayout) -> jnp.ndarray:
        """reduce_scatter: flat-averaged grads, this rank's shard only."""
        return _cat(self._shard_grad_parts(grads, lay))

    def _gather_master_parts(self, parts: list, lay: FlatLayout) -> list:
        """Per-bucket all-gather of updated master slices back to
        per-bucket full spans. Each bucket's gather depends only on that
        bucket's update, so it can start while later buckets are still in
        their math — and downstream, each parameter leaf is unraveled
        from only its own buckets (:meth:`_unravel_parts_like`), so the
        full flat vector is never concatenated back together."""
        return [_all_gather_flat(p, self.axis_name, axis=0) for p in parts]

    def _unravel_parts_like(self, parts: list, lay: FlatLayout,
                            like: Any = None) -> Any:
        """Per-bucket inverse of ravel: ``parts[i]`` covers the i-th
        bucket span; each leaf is assembled from only the parts covering
        it — parameter leaf j is ready as soon as its own buckets'
        gathers land, not after every bucket's."""
        new_params = unravel_parts(parts, self._bounds(lay), lay)
        if like is None:
            return new_params
        # the flat master mixes leaves with different varying-axes sets, so
        # every unraveled leaf inherits the union (e.g. an LN weight comes
        # back typed tensor-varying next to TP-sharded leaves). Replicated-
        # by-construction leaves are value-identical across those extra
        # axes, so a pmean is a value identity that restores each leaf's
        # original type (required by the caller's out_specs).

        from apex_tpu.utils.vma import leaf_vma

        def rec(n, p):
            extra = leaf_vma(n) - leaf_vma(p)
            if extra:
                n = jax.lax.pmean(n, tuple(sorted(extra)))
            return n

        return jax.tree_util.tree_map(rec, new_params, like)

    def _gather_params(self, master: jnp.ndarray, lay: FlatLayout,
                       like: Any = None) -> Any:
        """all_gather of a whole updated master shard (per-bucket under the
        hood) and unravel back to the parameter pytree."""
        parts = [master[o:o + n] for o, n in self._shard_bounds(lay)]
        return self._unravel_parts_like(
            self._gather_master_parts(parts, lay), lay, like)


class DistributedFusedAdam(_DistributedFusedBase):
    """ZeRO sharded Adam/AdamW (``distributed_fused_adam.py:9``).

    Numerics match :class:`apex_tpu.optimizers.FusedAdam` with DDP grad
    averaging, while per-device optimizer state (fp32 master + m + v) is
    1/dp of the dense version.
    """

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 axis_name: str = "data",
                 bucket_bytes: Optional[int] = None):
        super().__init__(axis_name, bucket_bytes=bucket_bytes)
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def init(self, params: Any) -> ZeroAdamState:
        lay = self._layout_for(params)
        master = self._my_slice(ravel(params, lay), lay)
        zeros = jnp.zeros(lay.chunk, jnp.float32)
        return ZeroAdamState(step=jnp.asarray(0, jnp.int32), master=master,
                             exp_avg=zeros, exp_avg_sq=zeros,
                             bucket_stamp=self._stamp())

    def _step(self, grads: Any, state: ZeroAdamState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None
              ) -> Tuple[Any, ZeroAdamState]:
        self.check_state(state)  # loud on eager grid mismatch; traced no-op
        lay = self._layout_for(params)
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            bc2 = bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2 = self.beta1, self.beta2

        # Per-bucket pipeline: bucket b's chain is
        #   reduce_scatter(b) -> moment/update math(b) -> all_gather(b)
        # with no cross-bucket dependencies AND no full-tree joins on
        # either end (span-local ravel in, per-bucket unravel out), so
        # XLA's latency-hiding scheduler can issue bucket k's scatter
        # under the backward tail the moment its grads exist, run bucket
        # k's gather transfer under bucket k+1's scatter + math, and hand
        # each layer its updated params as soon as that layer's buckets
        # land. Unbucketed this degenerates to the original single-chain
        # program.
        g_parts = self._shard_grad_parts(grads, lay)
        sbounds = self._shard_bounds(lay)
        ms, vs, masters, gathered = [], [], [], []
        for g, (o, n) in zip(g_parts, sbounds):
            p32 = state.master[o:o + n]
            if not self.adam_w_mode:
                g = g + wd * p32
            m = b1 * state.exp_avg[o:o + n] + (1.0 - b1) * g
            v = b2 * state.exp_avg_sq[o:o + n] + (1.0 - b2) * g * g
            update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.adam_w_mode:
                update = update + wd * p32
            new_master = p32 - lr * update
            ms.append(m)
            vs.append(v)
            masters.append(new_master)
            gathered.append(_all_gather_flat(new_master, self.axis_name,
                                             axis=0))
        new_params = self._unravel_parts_like(gathered, lay, like=params)
        return new_params, ZeroAdamState(
            step=t, master=_cat(masters), exp_avg=_cat(ms),
            exp_avg_sq=_cat(vs), bucket_stamp=state.bucket_stamp)


class DistributedFusedLAMB(_DistributedFusedBase):
    """ZeRO sharded LAMB (``distributed_fused_lamb.py:10``): global grad-norm
    clip, then per-tensor trust ratios — per-tensor norms come from
    ``segment_sum`` on the flat shard + ``psum`` (exact, not approximated).

    With ``bucket_bytes`` the reduce-scatter and param all-gather are
    per-bucket like Adam's, but the update math stays whole-shard: the
    global clip and cross-shard trust-ratio psums are barriers every
    bucket's update depends on, so a per-bucket math pipeline would buy
    nothing (the overlap win here is scatter-vs-backward and
    gather-vs-unravel only).
    """

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, axis_name: str = "data",
                 bucket_bytes: Optional[int] = None):
        super().__init__(axis_name, bucket_bytes=bucket_bytes)
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params: Any) -> ZeroLambState:
        lay = self._layout_for(params)
        master = self._my_slice(ravel(params, lay), lay)
        zeros = jnp.zeros(lay.chunk, jnp.float32)
        return ZeroLambState(step=jnp.asarray(0, jnp.int32), master=master,
                             exp_avg=zeros, exp_avg_sq=zeros,
                             bucket_stamp=self._stamp())

    def _per_tensor(self, vec_sq: jnp.ndarray, seg: jnp.ndarray,
                    lay: FlatLayout) -> jnp.ndarray:
        """psum of shard-local segment sums -> per-tensor sums (n_tensors+1,
        last slot is padding). Routed through the distributed.py psum
        chokepoint (scripts/check_collectives.py bans raw grad-path psums
        in this package); imported lazily — apex_tpu.parallel's __init__
        imports the optimizers package back."""
        from apex_tpu.parallel.distributed import grouped_psum
        part = jax.ops.segment_sum(vec_sq, seg, num_segments=len(lay.sizes) + 1)
        return grouped_psum(part, self.axis_name)

    def _step(self, grads: Any, state: ZeroLambState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None
              ) -> Tuple[Any, ZeroLambState]:
        self.check_state(state)  # loud on eager grid mismatch; traced no-op
        lay = self._layout_for(params)
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            bc2 = bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2 = self.beta1, self.beta2
        seg = self._my_slice(segment_ids(lay), lay)

        from apex_tpu.parallel.distributed import grouped_psum
        g = self._shard_grads(grads, lay)
        # phase 1: global grad-norm clip (reference fused_lamb.py:124-152)
        gnorm_sq = grouped_psum(jnp.sum(g * g), self.axis_name)
        gnorm = jnp.sqrt(gnorm_sq)
        clip = jnp.where(
            (self.max_grad_norm > 0) & (gnorm > self.max_grad_norm),
            gnorm / self.max_grad_norm, 1.0)
        g = g / clip

        p32 = state.master
        m = b1 * state.exp_avg + (1.0 - b1) * g
        v = b2 * state.exp_avg_sq + (1.0 - b2) * g * g
        update = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps) + wd * p32

        # phase 2: per-tensor trust ratios
        p_norm = jnp.sqrt(self._per_tensor(p32 * p32, seg, lay))
        u_norm = jnp.sqrt(self._per_tensor(update * update, seg, lay))
        if self.use_nvlamb:
            ratio = jnp.where(u_norm > 0, p_norm / u_norm, 1.0)
        else:
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              p_norm / u_norm, 1.0)
        new_master = p32 - lr * jnp.take(ratio, seg) * update
        new_params = self._gather_params(new_master, lay, like=params)
        return new_params, ZeroLambState(step=t, master=new_master,
                                         exp_avg=m, exp_avg_sq=v,
                                         bucket_stamp=state.bucket_stamp)
