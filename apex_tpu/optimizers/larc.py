"""LARC — layer-wise adaptive rate clipping as a grad transform.

Reference: ``reference:apex/parallel/LARC.py:5-107``. The torch version wraps
an optimizer and mutates ``p.grad`` in ``step``:
``adaptive_lr = trust_coefficient * ||p|| / (||g|| + ||p||*wd + eps)``; with
``clip=True`` it becomes ``min(adaptive_lr/lr, 1)``; weight decay is absorbed
into the grad and zeroed on the inner optimizer. Here the same transform is a
pure function over (grads, params) applied before any inner optimizer step.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import OptimizerBase

__all__ = ["LARC", "larc_transform_grads"]


def larc_transform_grads(grads: Any, params: Any, lr: Any,
                         trust_coefficient: float = 0.02,
                         clip: bool = True, eps: float = 1e-8,
                         weight_decay: Any = 0.0) -> Any:
    """Per-tensor LARC grad rewrite (``reference:apex/parallel/LARC.py:78-104``)."""
    lr = jnp.asarray(lr, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)

    def _one(g, p):
        g32 = jnp.asarray(g).astype(jnp.float32)
        p32 = jnp.asarray(p).astype(jnp.float32)
        pn = jnp.sqrt(jnp.sum(p32 * p32))
        gn = jnp.sqrt(jnp.sum(g32 * g32))
        adaptive_lr = trust_coefficient * pn / (gn + pn * wd + eps)
        if clip:
            adaptive_lr = jnp.minimum(adaptive_lr / lr, 1.0)
        # the reference leaves the grad completely untouched (no decay either)
        # when either norm is zero (LARC.py:92 'if param_norm != 0 and ...')
        active = (pn != 0.0) & (gn != 0.0)
        new_g = jnp.where(active, (g32 + wd * p32) * adaptive_lr, g32)
        return new_g.astype(jnp.asarray(g).dtype)

    return jax.tree_util.tree_map(_one, grads, params)


class LARC(OptimizerBase):
    """Wrapper: LARC grad transform + inner optimizer with its decay disabled,
    mirroring the weight-decay absorption of ``LARC.step``."""

    def __init__(self, optimizer: OptimizerBase, trust_coefficient: float = 0.02,
                 clip: bool = True, eps: float = 1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    def init(self, params: Any) -> Any:
        return self.optim.init(params)

    def _step(self, grads: Any, state: Any, params: Any,
              lr: Optional[Any] = None, **kw) -> Tuple[Any, Any]:
        eff_lr = self.optim.lr if lr is None else lr
        wd = getattr(self.optim, "weight_decay", 0.0)
        grads = larc_transform_grads(
            grads, params, eff_lr, self.trust_coefficient, self.clip,
            self.eps, weight_decay=wd)
        # inner optimizer runs with weight decay absorbed (LARC.py:81-85,105-107)
        import inspect
        if "weight_decay" in inspect.signature(self.optim._step).parameters:
            return self.optim._step(grads, state, params, lr=lr,
                                    weight_decay=0.0, **kw)
        saved = getattr(self.optim, "weight_decay", None)
        if saved is None:
            return self.optim._step(grads, state, params, lr=lr, **kw)
        self.optim.weight_decay = 0.0
        try:
            return self.optim._step(grads, state, params, lr=lr, **kw)
        finally:
            self.optim.weight_decay = saved
