"""FlatOptimizer — single-device ``multi_tensor_apply`` performance tier.

Wraps any elementwise optimizer from this suite so its update runs over ONE
flat fp32 buffer instead of a tree of small leaves — the TPU analog of the
reference's batched-kernel launches
(``reference:apex/multi_tensor_apply/multi_tensor_apply.py:28-34`` chunking
into ``multi_tensor_adam``/``sgd``/... kernels).

Measured reality on current jax/XLA (v5e, bench.py config 3, RN50's 161
leaves): XLA already fuses the per-leaf tree_map update well — per-leaf
FusedAdam runs ~1.0 ms/step vs ~4.4 ms flat (the ravel/unravel concat adds
two full passes over the parameters), and inside a full donated RN50 train
step FlatOptimizer(FusedSGD) and plain FusedSGD time identically. Use the
flat tier when leaf-count pathology actually bites (thousands of tiny
leaves, where per-leaf dispatch dominates) or when a single flat buffer is
wanted for layout reasons; otherwise the per-leaf optimizers are already
the fast path. (An earlier round's docstring claimed 7.4 ms -> <1 ms for
per-leaf vs flat SGD; that did not reproduce — recorded here so the claim
dies.)

Only valid for optimizers whose math is elementwise over (grad, param,
state) — FusedAdam, FusedAdagrad, FusedSGD. Per-tensor-norm optimizers
(LAMB, NovoGrad, LARC) need the segment machinery of the ZeRO tier instead.
Per-param-group hyperparameters (different lr/wd per leaf) are not
representable in a single flat buffer; use the wrapped optimizer directly
for those.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax.numpy as jnp

from apex_tpu.optimizers._base import OptimizerBase
from apex_tpu.optimizers._flatten import build_layout, ravel, unravel

__all__ = ["FlatOptimizer"]


class FlatOptimizer(OptimizerBase):
    """``FlatOptimizer(FusedSGD(...))`` — identical numerics (the wrapped
    update is elementwise, so flattening commutes with it), one fused pass.

    State is the wrapped optimizer's state over the flat vector; params keep
    their tree shape and dtypes at the API boundary (bf16 params round-trip
    through the fp32 buffer, which is exactly amp O2's master-weight rule).
    """

    def __init__(self, inner: OptimizerBase):
        self.inner = inner
        self._layout = None

    def _layout_for(self, params: Any):
        lay = build_layout(params)
        if self._layout is not None and self._layout.shapes != lay.shapes:
            raise ValueError("parameter structure changed between calls")
        self._layout = lay
        return lay

    def init(self, params: Any) -> Any:
        lay = self._layout_for(params)
        return self.inner.init(ravel(params, lay))

    def _step(self, grads: Any, state: Any, params: Any,
              **kw) -> Tuple[Any, Any]:
        lay = self._layout_for(params)
        flat_g = ravel(grads, lay)
        flat_p = ravel(params, lay)
        new_flat, new_state = self.inner._step(flat_g, state, flat_p, **kw)
        return unravel(new_flat, lay), new_state
