"""FlatOptimizer — single-device ``multi_tensor_apply`` performance tier.

Wraps any elementwise optimizer from this suite so its update runs over ONE
flat fp32 buffer instead of a tree of small leaves — the TPU analog of the
reference's batched-kernel launches
(``reference:apex/multi_tensor_apply/multi_tensor_apply.py:28-34`` chunking
into ``multi_tensor_adam``/``sgd``/... kernels).

Two tiers:

* **Persistent-flat (the performance tier)** — ``init_flat`` ravels params
  and moments ONCE; thereafter the master params live flat (donate them in
  jit), the model applies through ``unflatten`` (slice+reshape views XLA
  fuses into the consumers), and AD taken w.r.t. the flat buffer hands the
  gradient back as one flat vector, so a step never concatenates anything:

      opt = FlatOptimizer(FusedSGD(lr=0.1, momentum=0.9))
      fstate = opt.init_flat(params)
      def loss_fn(flat):
          return loss(opt.unflatten(flat), batch)      # views, not copies
      g = jax.grad(loss_fn)(fstate.flat_params)
      fstate = opt.flat_step(g, fstate)                # ONE fused loop

  This is what ``multi_tensor_apply`` actually buys the reference: the
  update is a single pass over contiguous memory no matter how many
  parameter tensors exist.

* **Compat tier** — the plain ``init``/``step`` pytree protocol still
  works, but it must ravel grads+params and unravel the result EVERY step
  (two extra full passes over the parameters); measured 4.2x slower than
  the per-leaf optimizers on RN50's 161 leaves (bench.py config 3, r03).
  Per-leaf tree_map is already well-fused by XLA at O(100) leaves; the
  flat tier wins when leaf count is large (O(1000)+ tiny leaves) or when
  the grads are already flat (the persistent pattern above).

Only valid for optimizers whose math is elementwise over (grad, param,
state) — FusedAdam, FusedAdagrad, FusedSGD. Per-tensor-norm optimizers
(LAMB, NovoGrad, LARC) need the segment machinery of the ZeRO tier instead.
Per-param-group hyperparameters (different lr/wd per leaf) are not
representable in a single flat buffer; use the wrapped optimizer directly
for those.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax.numpy as jnp

from apex_tpu.optimizers._base import OptimizerBase
from apex_tpu.amp.scaler import select_tree
from apex_tpu.optimizers._flatten import build_layout, ravel, unravel

__all__ = ["FlatOptimizer", "FlatState"]


class FlatState(NamedTuple):
    """Persistent flat training state: fp32 master params + wrapped-optimizer
    state, both over the one padded flat vector."""
    flat_params: jnp.ndarray
    inner_state: Any


class FlatOptimizer(OptimizerBase):
    """``FlatOptimizer(FusedSGD(...))`` — identical numerics (the wrapped
    update is elementwise, so flattening commutes with it), one fused pass.

    State is the wrapped optimizer's state over the flat vector; params keep
    their tree shape and dtypes at the API boundary (bf16 params round-trip
    through the fp32 buffer, which is exactly amp O2's master-weight rule).
    """

    def __init__(self, inner: OptimizerBase):
        self.inner = inner
        self._layout = None

    def _layout_for(self, params: Any):
        lay = build_layout(params)
        if self._layout is not None and self._layout.shapes != lay.shapes:
            raise ValueError("parameter structure changed between calls")
        self._layout = lay
        return lay

    # -- persistent-flat tier ----------------------------------------------

    def init_flat(self, params: Any) -> FlatState:
        """Ravel ``params`` once into the resident fp32 master vector and
        build the wrapped optimizer's state over it. Everything after this
        stays flat — donate the returned state through jit."""
        lay = self._layout_for(params)
        flat = ravel(params, lay)
        return FlatState(flat, self.inner.init(flat))

    def unflatten(self, flat_params: jnp.ndarray) -> Any:
        """Original-dtype tree views of the flat master vector for the model
        apply (slice+reshape+cast; XLA fuses these into the consumers, and
        their AD transpose writes the cotangent straight into one flat
        gradient buffer)."""
        if self._layout is None:
            raise ValueError("call init_flat (or init) first")
        return unravel(flat_params, self._layout)

    def params_of(self, fstate: FlatState) -> Any:
        """Tree-shaped view of the current params (checkpoint/export)."""
        return self.unflatten(fstate.flat_params)

    def flat_step(self, flat_grads: jnp.ndarray, fstate: FlatState,
                  grads_finite: Optional[jnp.ndarray] = None,
                  **kw) -> FlatState:
        """One fused elementwise pass over the flat buffers. ``flat_grads``
        is a gradient w.r.t. ``fstate.flat_params`` (take ``jax.grad`` of a
        loss composed with :meth:`unflatten`)."""
        new_flat, new_inner = self.inner._step(
            flat_grads.astype(jnp.float32), fstate.inner_state,
            fstate.flat_params, **kw)
        new = FlatState(new_flat, new_inner)
        if grads_finite is None:
            return new
        return select_tree(grads_finite, new, fstate)

    # -- compat pytree tier -------------------------------------------------

    def init(self, params: Any) -> Any:
        lay = self._layout_for(params)
        return self.inner.init(ravel(params, lay))

    def _step(self, grads: Any, state: Any, params: Any,
              **kw) -> Tuple[Any, Any]:
        lay = self._layout_for(params)
        flat_g = ravel(grads, lay)
        flat_p = ravel(params, lay)
        new_flat, new_state = self.inner._step(flat_g, state, flat_p, **kw)
        return unravel(new_flat, lay), new_state
