"""FusedNovoGrad — per-layer second-moment NovoGrad.

Reference: ``reference:apex/optimizers/fused_novograd.py:4-213`` +
``reference:csrc/multi_tensor_novograd.cu:96-127``. The second moment is one
scalar *norm* per tensor (not squared; ``fused_novograd.py:157-176``), blended
in RMS form for ``norm_type=2`` (``sqrt(b2*v^2 + (1-b2)*||g||^2)``) and
linearly for ``norm_type=0`` (L-inf); if
``init_zero`` is false the first step seeds ``v = ||g||`` so the first blend is
a no-op. MOMENT_MODE_0 (``reg_inside_moment``) normalizes+decays the grad
before the momentum blend; MOMENT_MODE_1 (default) is decoupled.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (
    OptimizerBase, bias_correction, tree_unzip, tree_zeros_like_f32)

__all__ = ["FusedNovoGrad", "NovoGradState"]


class NovoGradState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any    # momentum, fp32, per-element
    exp_avg_sq: Any # norm EMA, fp32, one scalar per tensor


class FusedNovoGrad(OptimizerBase):
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.95, 0.98), eps: float = 1e-8,
                 weight_decay: float = 0.0, reg_inside_moment: bool = False,
                 grad_averaging: bool = True, norm_type: int = 2,
                 init_zero: bool = False, amsgrad: bool = False):
        if amsgrad:
            raise RuntimeError("FusedNovoGrad does not support the AMSGrad variant.")
        if norm_type not in (0, 2):
            raise RuntimeError("FusedNovoGrad only supports l2/inf norm.")
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.reg_inside_moment = reg_inside_moment
        self.grad_averaging = grad_averaging
        self.norm_type = norm_type
        self.init_zero = init_zero

    def init(self, params: Any) -> NovoGradState:
        return NovoGradState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=tree_zeros_like_f32(params),
            exp_avg_sq=jax.tree_util.tree_map(
                lambda p: jnp.zeros((), jnp.float32), params))

    def _grad_norm(self, g32):
        if self.norm_type == 0:
            return jnp.max(jnp.abs(g32))
        return jnp.sqrt(jnp.sum(g32 * g32))

    def _step(self, grads: Any, state: NovoGradState, params: Any,
              lr: Optional[Any] = None) -> Tuple[Any, NovoGradState]:
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(self.weight_decay, jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            # v is an EMA of *norms*, so its correction carries a sqrt
            # (reference:csrc/multi_tensor_novograd.cu:151)
            bc2 = jnp.sqrt(bias_correction(self.beta2, t))
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2, eps = self.beta1, self.beta2, self.eps
        beta3 = (1.0 - b1) if self.grad_averaging else 1.0
        first = state.step == 0

        def _update(g, p, m, v):
            p32 = jnp.asarray(p).astype(jnp.float32)
            g32 = jnp.asarray(g).astype(jnp.float32)
            gn = self._grad_norm(g32)
            # L2 blends in RMS form, L-inf linearly
            # (reference:csrc/multi_tensor_l2norm_kernel.cu multi_tensor_norm_out:
            #  "L-2: gn = sqrt(a*gn^2 + b*n^2); L-inf: gn = a*gn + b*n")
            if self.norm_type == 2:
                blended = jnp.sqrt(b2 * v * v + (1.0 - b2) * gn * gn)
            else:
                blended = b2 * v + (1.0 - b2) * gn
            if self.init_zero:
                new_v = blended
            else:
                # first step seeds v = ||g|| so the blend is identity
                new_v = jnp.where(first, gn, blended)
            denom = new_v / bc2 + eps
            if self.reg_inside_moment:  # MOMENT_MODE_0
                gg = g32 / denom + wd * p32
                m = b1 * m + beta3 * gg
                new_p = p32 - lr * (m / bc1)
            else:  # MOMENT_MODE_1
                m = b1 * m + beta3 * g32
                update = (m / bc1) / denom + wd * p32
                new_p = p32 - lr * update
            return new_p.astype(jnp.asarray(p).dtype), m, new_v

        out = jax.tree_util.tree_map(
            _update, grads, params, state.exp_avg, state.exp_avg_sq)
        new_params, new_m, new_v = tree_unzip(
            out, jax.tree_util.tree_structure(params), 3)
        return new_params, NovoGradState(step=t, exp_avg=new_m, exp_avg_sq=new_v)
