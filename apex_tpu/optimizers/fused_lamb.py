"""FusedLAMB + FusedMixedPrecisionLamb — pytree LAMB matching the reference.

Two-phase structure of ``reference:apex/optimizers/fused_lamb.py:96-213``:
(1) global grad norm via ``multi_tensor_l2norm`` and clip coefficient
``clipped = gn/max_grad_norm if gn > max_grad_norm else 1``
(``reference:csrc/multi_tensor_lamb.cu:66``); (2) per-param Adam-style update
(``multi_tensor_lamb.cu:120-143``: MOMENT_MODE_0 folds L2 into the scaled grad,
MOMENT_MODE_1 = AdamW appends ``decay*p`` to the update), then per-tensor trust
ratio ``lr * ||p||/||update||`` applied only where ``use_nvlamb or decay != 0``
(``multi_tensor_lamb.cu:244-262``).

FusedMixedPrecisionLamb (``reference:apex/optimizers/fused_mixed_precision_lamb.py:8-255``)
is the same math driven by fp32 master params with low-precision model params
regenerated after the step, and a dynamic ``grad_scale`` divisor folded into
the grad read (kernels ``multi_tensor_l2norm_mp``/``lamb_mp``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.multi_tensor_apply import tree_global_norm
from apex_tpu.optimizers._base import (
    OptimizerBase, bias_correction, tree_unzip, tree_zeros_like_f32)

__all__ = ["FusedLAMB", "LAMBState", "FusedMixedPrecisionLamb",
           "MixedPrecisionLambState"]


class LAMBState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


class FusedLAMB(OptimizerBase):
    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-6,
                 weight_decay: float = 0.01, adam_w_mode: bool = True,
                 grad_averaging: bool = True, max_grad_norm: float = 1.0,
                 use_nvlamb: bool = False, amsgrad: bool = False):
        if amsgrad:
            raise RuntimeError("FusedLAMB does not support the AMSGrad variant.")
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.grad_averaging = grad_averaging
        self.max_grad_norm = max_grad_norm
        self.use_nvlamb = use_nvlamb

    def init(self, params: Any) -> LAMBState:
        return LAMBState(step=jnp.asarray(0, jnp.int32),
                         exp_avg=tree_zeros_like_f32(params),
                         exp_avg_sq=tree_zeros_like_f32(params))

    def _step(self, grads: Any, state: LAMBState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None,
              grad_scale: Any = 1.0) -> Tuple[Any, LAMBState]:
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        inv_gs = 1.0 / jnp.asarray(grad_scale, jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1, bc2 = bias_correction(self.beta1, t), bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2, eps = self.beta1, self.beta2, self.eps
        beta3 = (1.0 - b1) if self.grad_averaging else 1.0

        # Phase 1: global grad-norm clip coefficient (fused_lamb.py:124-133).
        gnorm = tree_global_norm(grads) * inv_gs
        clip = jnp.where(gnorm > self.max_grad_norm,
                         gnorm / self.max_grad_norm, 1.0)

        def _update(g, p, m, v):
            p32 = jnp.asarray(p).astype(jnp.float32)
            sg = jnp.asarray(g).astype(jnp.float32) * inv_gs / clip
            if not self.adam_w_mode:  # MOMENT_MODE_0: L2 on scaled grad
                sg = sg + wd * p32
            m = b1 * m + beta3 * sg
            v = b2 * v + (1.0 - b2) * sg * sg
            update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if self.adam_w_mode:  # MOMENT_MODE_1
                update = update + wd * p32
            # Stage 2: per-tensor trust ratio (multi_tensor_lamb.cu:244-262).
            pnorm = jnp.sqrt(jnp.sum(p32 * p32))
            unorm = jnp.sqrt(jnp.sum(update * update))
            ratio = jnp.where((pnorm != 0.0) & (unorm != 0.0),
                              lr * pnorm / unorm, lr)
            if not self.use_nvlamb:
                # trust ratio only for decayed params
                ratio = jnp.where(wd != 0.0, ratio, lr)
            new_p = p32 - ratio * update
            return new_p.astype(jnp.asarray(p).dtype), m, v

        out = jax.tree_util.tree_map(
            _update, grads, params, state.exp_avg, state.exp_avg_sq)
        new_params, new_m, new_v = tree_unzip(
            out, jax.tree_util.tree_structure(params), 3)
        return new_params, LAMBState(step=t, exp_avg=new_m, exp_avg_sq=new_v)


class MixedPrecisionLambState(NamedTuple):
    step: jnp.ndarray
    master_params: Any  # fp32
    exp_avg: Any
    exp_avg_sq: Any


class FusedMixedPrecisionLamb(OptimizerBase):
    """LAMB over fp32 masters with low-precision model params regenerated
    after each step; ``grad_scale`` (the live loss scale) divides grads inside
    the update so callers can feed *scaled* grads directly
    (``reference:apex/optimizers/fused_mixed_precision_lamb.py:140-255``)."""

    def __init__(self, **lamb_kwargs):
        self._lamb = FusedLAMB(**lamb_kwargs)
        # mirror the inner hyperparams so wrappers (LARC) and schedulers see
        # the same surface as every other optimizer here
        self.lr = self._lamb.lr
        self.weight_decay = self._lamb.weight_decay

    def init(self, params: Any) -> MixedPrecisionLambState:
        master = jax.tree_util.tree_map(
            lambda p: jnp.asarray(p).astype(jnp.float32), params)
        inner = self._lamb.init(params)
        return MixedPrecisionLambState(
            step=inner.step, master_params=master,
            exp_avg=inner.exp_avg, exp_avg_sq=inner.exp_avg_sq)

    def _step(self, grads: Any, state: MixedPrecisionLambState, params: Any,
              lr: Optional[Any] = None, weight_decay: Optional[Any] = None,
              grad_scale: Any = 1.0) -> Tuple[Any, MixedPrecisionLambState]:
        if lr is None:
            lr = self.lr
        if weight_decay is None:
            weight_decay = self.weight_decay
        inner_state = LAMBState(state.step, state.exp_avg, state.exp_avg_sq)
        new_master, new_inner = self._lamb._step(
            grads, inner_state, state.master_params, lr=lr,
            weight_decay=weight_decay, grad_scale=grad_scale)
        new_params = jax.tree_util.tree_map(
            lambda mp, p: mp.astype(jnp.asarray(p).dtype), new_master, params)
        return new_params, MixedPrecisionLambState(
            step=new_inner.step, master_params=new_master,
            exp_avg=new_inner.exp_avg, exp_avg_sq=new_inner.exp_avg_sq)
