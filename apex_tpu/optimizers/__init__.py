"""Fused-optimizer suite (``reference:apex/optimizers/__init__.py:1-6``).

Pure pytree update functions; XLA fuses each step into a few loops over the
whole parameter set, which is the TPU analog of the one-kernel
``multi_tensor_apply`` launches the reference uses.
"""

from apex_tpu.optimizers._base import OptimizerBase  # noqa: F401
from apex_tpu.optimizers.distributed_fused import (  # noqa: F401
    DistributedFusedAdam, DistributedFusedLAMB, ZeroAdamState, ZeroLambState)
from apex_tpu.optimizers.flat import FlatOptimizer, FlatState  # noqa: F401
from apex_tpu.optimizers.fused_adam import (  # noqa: F401
    AdagradState, AdamState, FusedAdagrad, FusedAdam)
from apex_tpu.optimizers.fused_lamb import (  # noqa: F401
    FusedLAMB, FusedMixedPrecisionLamb, LAMBState, MixedPrecisionLambState)
from apex_tpu.optimizers.fused_novograd import (  # noqa: F401
    FusedNovoGrad, NovoGradState)
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState  # noqa: F401
from apex_tpu.optimizers.larc import LARC, larc_transform_grads  # noqa: F401

__all__ = [
    "OptimizerBase",
    "DistributedFusedAdam", "ZeroAdamState",
    "DistributedFusedLAMB", "ZeroLambState",
    "FlatOptimizer",
    "FlatState",
    "FusedAdam", "AdamState",
    "FusedAdagrad", "AdagradState",
    "FusedLAMB", "LAMBState",
    "FusedMixedPrecisionLamb", "MixedPrecisionLambState",
    "FusedNovoGrad", "NovoGradState",
    "FusedSGD", "SGDState",
    "LARC", "larc_transform_grads",
]
