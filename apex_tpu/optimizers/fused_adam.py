"""FusedAdam / FusedAdagrad — pytree updates matching the reference kernels.

Math from ``reference:csrc/multi_tensor_adam.cu:82-113`` (ADAM_MODE_0 = L2
regularization folded into the grad, ADAM_MODE_1 = decoupled AdamW) and
``reference:csrc/multi_tensor_adagrad.cu:60-84``; Python surface from
``reference:apex/optimizers/fused_adam.py:4-173`` / ``fused_adagrad.py:5``.
All moment math runs in fp32 regardless of param dtype, as the CUDA kernels'
``MATH_T = float`` does.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (
    OptimizerBase, bias_correction, tree_unzip, tree_zeros_like_f32)

__all__ = ["FusedAdam", "AdamState", "FusedAdagrad", "AdagradState"]


class AdamState(NamedTuple):
    step: jnp.ndarray  # i32 scalar, 0-based count of applied steps
    exp_avg: Any       # m, fp32
    exp_avg_sq: Any    # v, fp32


class FusedAdam(OptimizerBase):
    """Adam/AdamW over a parameter pytree.

    ``adam_w_mode=True`` (default) is decoupled weight decay, matching
    ``reference:apex/optimizers/fused_adam.py:72``; ``amsgrad`` is rejected as
    in the reference (``fused_adam.py:80-81``).
    """

    def __init__(self, lr: float = 1e-3, bias_correction: bool = True,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 adam_w_mode: bool = True, weight_decay: float = 0.0,
                 amsgrad: bool = False):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.use_bias_correction = bias_correction
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.adam_w_mode = adam_w_mode
        self.weight_decay = weight_decay

    def init(self, params: Any) -> AdamState:
        return AdamState(step=jnp.asarray(0, jnp.int32),
                         exp_avg=tree_zeros_like_f32(params),
                         exp_avg_sq=tree_zeros_like_f32(params))

    def _step(self, grads: Any, state: AdamState, params: Any,
              lr: Optional[Any] = None,
              weight_decay: Optional[Any] = None) -> Tuple[Any, AdamState]:
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd = jnp.asarray(
            self.weight_decay if weight_decay is None else weight_decay,
            jnp.float32)
        t = state.step + 1
        if self.use_bias_correction:
            bc1 = bias_correction(self.beta1, t)
            bc2 = bias_correction(self.beta2, t)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)
        b1, b2, eps = self.beta1, self.beta2, self.eps

        def _update(g, p, m, v):
            p32 = jnp.asarray(p).astype(jnp.float32)
            g32 = jnp.asarray(g).astype(jnp.float32)
            if not self.adam_w_mode:  # ADAM_MODE_0: L2 into the grad
                g32 = g32 + wd * p32
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * g32 * g32
            denom = jnp.sqrt(v / bc2) + eps
            update = (m / bc1) / denom
            if self.adam_w_mode:  # ADAM_MODE_1: decoupled decay
                update = update + wd * p32
            new_p = p32 - lr * update
            return new_p.astype(jnp.asarray(p).dtype), m, v

        out = jax.tree_util.tree_map(
            _update, grads, params, state.exp_avg, state.exp_avg_sq)
        new_params, new_m, new_v = tree_unzip(
            out, jax.tree_util.tree_structure(params), 3)
        return new_params, AdamState(step=t, exp_avg=new_m, exp_avg_sq=new_v)


class AdagradState(NamedTuple):
    step: jnp.ndarray
    sum_sq: Any  # h, fp32


class FusedAdagrad(OptimizerBase):
    """Adagrad with L2 (mode 0) or AdamW-style decoupled decay (mode 1)
    per ``reference:csrc/multi_tensor_adagrad.cu:64-73``."""

    def __init__(self, lr: float = 1e-2, eps: float = 1e-10,
                 weight_decay: float = 0.0, adagrad_w_mode: bool = False):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.adagrad_w_mode = adagrad_w_mode

    def init(self, params: Any) -> AdagradState:
        return AdagradState(step=jnp.asarray(0, jnp.int32),
                            sum_sq=tree_zeros_like_f32(params))

    def _step(self, grads: Any, state: AdagradState, params: Any,
              lr: Optional[Any] = None) -> Tuple[Any, AdagradState]:
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        wd, eps = jnp.asarray(self.weight_decay, jnp.float32), self.eps

        def _update(g, p, h):
            p32 = jnp.asarray(p).astype(jnp.float32)
            g32 = jnp.asarray(g).astype(jnp.float32)
            if not self.adagrad_w_mode:
                g32 = g32 + wd * p32
            h = h + g32 * g32
            update = g32 / (jnp.sqrt(h) + eps)
            if self.adagrad_w_mode:
                update = update + wd * p32
            new_p = p32 - lr * update
            return new_p.astype(jnp.asarray(p).dtype), h

        out = jax.tree_util.tree_map(_update, grads, params, state.sum_sq)
        new_params, new_h = tree_unzip(
            out, jax.tree_util.tree_structure(params), 2)
        return new_params, AdagradState(step=state.step + 1, sum_sq=new_h)
