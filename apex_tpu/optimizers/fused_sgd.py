"""FusedSGD — momentum SGD matching ``reference:csrc/multi_tensor_sgd_kernel.cu``.

Semantics (``multi_tensor_sgd_kernel.cu:87-130``): grads are pre-multiplied by
``scale``; weight decay is applied before momentum unless
``wd_after_momentum``; first application seeds the momentum buffer with the
(decayed) grad; ``nesterov`` uses ``g + momentum*buf``. Python surface:
``reference:apex/optimizers/fused_sgd.py:6-226``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._base import (
    OptimizerBase, tree_unzip, tree_zeros_like_f32)

__all__ = ["FusedSGD", "SGDState"]


class SGDState(NamedTuple):
    step: jnp.ndarray      # i32; step==0 means momentum buffers are unseeded
    momentum_buf: Any      # fp32


class FusedSGD(OptimizerBase):
    """``materialize_master_grads`` is accepted for reference API compat but is
    a no-op here: there is no separate fp16-grad/fp32-master-grad wiring to
    choose between — grads are widened to fp32 inside the update
    (cf. ``reference:apex/optimizers/fused_sgd.py:100-226``)."""

    def __init__(self, lr: float = 1e-3, momentum: float = 0.0,
                 dampening: float = 0.0, weight_decay: float = 0.0,
                 nesterov: bool = False, wd_after_momentum: bool = False,
                 materialize_master_grads: bool = True):
        if nesterov and (momentum <= 0 or dampening != 0):
            raise ValueError(
                "Nesterov momentum requires a momentum and zero dampening")
        self.lr = lr
        self.momentum = momentum
        self.dampening = dampening
        self.weight_decay = weight_decay
        self.nesterov = nesterov
        self.wd_after_momentum = wd_after_momentum
        self.materialize_master_grads = materialize_master_grads

    def init(self, params: Any) -> SGDState:
        return SGDState(step=jnp.asarray(0, jnp.int32),
                        momentum_buf=tree_zeros_like_f32(params))

    def _step(self, grads: Any, state: SGDState, params: Any,
              lr: Optional[Any] = None,
              scale: Any = 1.0) -> Tuple[Any, SGDState]:
        lr = jnp.asarray(self.lr if lr is None else lr, jnp.float32)
        scale = jnp.asarray(scale, jnp.float32)
        wd = jnp.asarray(self.weight_decay, jnp.float32)
        mom, damp = self.momentum, self.dampening
        first_run = state.step == 0

        def _update(g, p, buf):
            p32 = jnp.asarray(p).astype(jnp.float32)
            g32 = jnp.asarray(g).astype(jnp.float32) * scale
            if not self.wd_after_momentum:
                g32 = g32 + wd * p32
            if mom != 0.0:
                # first_run seeds buf = g (multi_tensor_sgd_kernel.cu:110-113)
                seeded = jnp.where(first_run, g32, mom * buf + (1.0 - damp) * g32)
                step_dir = g32 + mom * seeded if self.nesterov else seeded
                buf = seeded
            else:
                step_dir = g32
            if self.wd_after_momentum:
                step_dir = step_dir + wd * p32
            new_p = p32 - lr * step_dir
            return new_p.astype(jnp.asarray(p).dtype), buf

        out = jax.tree_util.tree_map(_update, grads, params, state.momentum_buf)
        new_params, new_buf = tree_unzip(
            out, jax.tree_util.tree_structure(params), 2)
        return new_params, SGDState(step=state.step + 1, momentum_buf=new_buf)
