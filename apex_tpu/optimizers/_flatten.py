"""Flat-buffer parameter layout — the ``multi_tensor_apply`` memory tier.

The reference batches every optimizer/scaler elementwise op into chunked
kernels over a list of tensors (``reference:csrc/multi_tensor_apply.cuh``,
``apex/multi_tensor_apply``) because per-tensor kernel launches dominate at
hundreds of small parameters. XLA has the same failure mode — a tree_map'd
update over ~160 leaves becomes ~160 tiny fused loops at ~10% of HBM
bandwidth — and the same cure: run the elementwise math over ONE flat fp32
vector and slice it back. These helpers build the static layout
(shapes/dtypes/offsets, padded to a multiple of ``chunks``) shared by
:class:`~apex_tpu.optimizers.FlatOptimizer` (single-device tier) and the
ZeRO optimizers (sharded tier).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["FlatLayout", "build_layout", "ravel", "unravel", "segment_ids",
           "bucket_bounds"]


class FlatLayout(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    padded: int
    chunk: int            # padded // chunks


def build_layout(params: Any, chunks: int = 1) -> FlatLayout:
    """Static layout for ``params``; ``chunks`` is the shard count the
    padded length must divide into (dp for ZeRO, 1 for single device)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(np.shape(l)) for l in leaves)
    dtypes = tuple(jnp.asarray(l).dtype for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
    total = int(sum(sizes))
    padded = ((total + chunks - 1) // chunks) * chunks
    return FlatLayout(treedef, shapes, dtypes, sizes, offsets, total,
                      padded, padded // chunks)


def ravel(tree: Any, lay: FlatLayout) -> jnp.ndarray:
    """Concatenate the leaves into one flat fp32 vector (padded)."""
    leaves = lay.treedef.flatten_up_to(tree)
    flat = jnp.concatenate(
        [jnp.reshape(jnp.asarray(l), (-1,)).astype(jnp.float32)
         for l in leaves])
    if lay.padded != lay.total:
        flat = jnp.pad(flat, (0, lay.padded - lay.total))
    return flat


def unravel(flat: jnp.ndarray, lay: FlatLayout) -> Any:
    """Slice the flat vector back into the original tree (original dtypes)."""
    leaves = []
    for shape, dtype, size, off in zip(lay.shapes, lay.dtypes,
                                       lay.sizes, lay.offsets):
        leaves.append(jax.lax.dynamic_slice_in_dim(flat, off, size)
                      .reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(lay.treedef, leaves)


def bucket_bounds(lay: FlatLayout,
                  bucket_bytes: "int | None") -> Tuple[Tuple[int, int], ...]:
    """Static ``(offset, size)`` spans carving the padded flat vector into
    fixed-size buckets of ~``bucket_bytes`` fp32 elements — the
    torch-DDP-style bucketing grid shared by the bucketed DDP allreduce and
    the ZeRO per-bucket reduce-scatter/all-gather
    (:mod:`apex_tpu.parallel.distributed`).

    Every span's size is a multiple of ``lay.padded // lay.chunk`` (the
    shard count the layout was built for), so each bucket reduce-scatters
    cleanly over that axis. ``bucket_bytes=None`` means no bucketing: one
    span covering the whole vector (the monolithic path).
    """
    if bucket_bytes is None:
        return ((0, lay.padded),)
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    chunks = lay.padded // lay.chunk if lay.chunk else 1
    per = max(1, int(bucket_bytes) // 4)          # fp32 elements per bucket
    per = ((per + chunks - 1) // chunks) * chunks  # divisible by shard count
    bounds = []
    off = 0
    while off < lay.padded:
        n = min(per, lay.padded - off)  # tail stays divisible: padded%chunks==0
        bounds.append((off, n))
        off += n
    return tuple(bounds) or ((0, 0),)


def segment_ids(lay: FlatLayout) -> jnp.ndarray:
    """Static flat-index -> tensor-index map (padding gets an extra id so it
    never contaminates a real tensor's norm)."""
    ids = np.full(lay.padded, len(lay.sizes), np.int32)
    for i, (off, size) in enumerate(zip(lay.offsets, lay.sizes)):
        ids[off:off + size] = i
    return jnp.asarray(ids)
