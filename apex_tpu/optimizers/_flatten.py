"""Flat-buffer parameter layout — the ``multi_tensor_apply`` memory tier.

The reference batches every optimizer/scaler elementwise op into chunked
kernels over a list of tensors (``reference:csrc/multi_tensor_apply.cuh``,
``apex/multi_tensor_apply``) because per-tensor kernel launches dominate at
hundreds of small parameters. XLA has the same failure mode — a tree_map'd
update over ~160 leaves becomes ~160 tiny fused loops at ~10% of HBM
bandwidth — and the same cure: run the elementwise math over ONE flat fp32
vector and slice it back. These helpers build the static layout
(shapes/dtypes/offsets, padded to a multiple of ``chunks``) shared by
:class:`~apex_tpu.optimizers.FlatOptimizer` (single-device tier) and the
ZeRO optimizers (sharded tier).

Two performance properties live here:

- **layout memoization** — the layout is a pure function of the tree
  *structure* (treedef, shapes, dtypes, chunks), so :func:`build_layout`
  memoizes it (and :func:`segment_ids` memoizes its O(padded) host
  array). Callers that rebuild "per call" — the optimizers' defensive
  ``_layout_for``, every eager step, every retrace — hit the cache
  instead of recomputing cumsum/offset tables and re-materializing
  multi-hundred-MB segment maps; the traced program is byte-identical
  either way (regression-tested on the jaxpr).
- **span-local ravel/unravel** — :func:`ravel_span` builds one bucket's
  slice of the flat vector from ONLY the leaves overlapping that span,
  and :func:`unravel_parts` rebuilds each leaf from ONLY the bucket
  pieces covering it. A bucketed grad sync assembled this way carries no
  data dependency on the whole tree: bucket k's collective can be issued
  as soon as the backward has produced the leaves in span k (the
  full-tree ``concatenate`` of :func:`ravel` was a barrier every bucket
  waited on), and parameter leaf j becomes ready as soon as its own
  buckets' gathers land. Values are element-identical to
  ``ravel``/``unravel`` over the same spans.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["FlatLayout", "build_layout", "ravel", "unravel", "segment_ids",
           "bucket_bounds", "ravel_span", "unravel_parts",
           "layout_cache_stats", "clear_layout_cache"]


class FlatLayout(NamedTuple):
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    padded: int
    chunk: int            # padded // chunks


# (treedef, shapes, dtypes, chunks) -> FlatLayout. The key is the full
# static identity of a layout, so a hit returns the IDENTICAL object the
# first build produced — optimizer `_layout_for` guards that compare
# layouts across steps see one object, and eager/retraced steps skip the
# cumsum/offset rebuild. Bounded FIFO: a process cycling through many
# distinct models cannot leak layouts.
_LAYOUT_CACHE: dict = {}
_LAYOUT_CACHE_MAX = 64
_LAYOUT_STATS = {"hits": 0, "misses": 0}
# the segment maps are O(padded) int32 HOST arrays (GBs at 1B params),
# so their cache is bounded by BYTES, not entries — retention of a dead
# model's multi-GB map is capped at the budget, while the small maps
# tests and medium models produce still amortize fully
_SEGMENT_CACHE: dict = {}
_SEGMENT_CACHE_MAX_BYTES = 256 << 20


def layout_cache_stats() -> dict:
    """``{"hits": n, "misses": n}`` of the :func:`build_layout` memo —
    the regression surface for the cached-path tests."""
    return dict(_LAYOUT_STATS)


def clear_layout_cache() -> None:
    _LAYOUT_CACHE.clear()
    _SEGMENT_CACHE.clear()
    _LAYOUT_STATS["hits"] = _LAYOUT_STATS["misses"] = 0


def build_layout(params: Any, chunks: int = 1) -> FlatLayout:
    """Static layout for ``params``; ``chunks`` is the shard count the
    padded length must divide into (dp for ZeRO, 1 for single device).
    Memoized on the tree's static identity (treedef/shapes/dtypes/chunks):
    repeated calls — every step of an eager loop, every defensive
    ``_layout_for`` re-derivation — return the same object instead of
    rebuilding the offset tables per call."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(np.shape(l)) for l in leaves)
    dtypes = tuple(jnp.asarray(l).dtype for l in leaves)
    key = (treedef, shapes, dtypes, int(chunks))
    try:
        cached = _LAYOUT_CACHE.get(key)
    except TypeError:       # unhashable treedef (exotic custom nodes)
        cached, key = None, None
    if cached is not None:
        _LAYOUT_STATS["hits"] += 1
        return cached
    _LAYOUT_STATS["misses"] += 1
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(x) for x in np.cumsum((0,) + sizes[:-1]))
    total = int(sum(sizes))
    padded = ((total + chunks - 1) // chunks) * chunks
    lay = FlatLayout(treedef, shapes, dtypes, sizes, offsets, total,
                     padded, padded // chunks)
    if key is not None:
        if len(_LAYOUT_CACHE) >= _LAYOUT_CACHE_MAX:
            _LAYOUT_CACHE.pop(next(iter(_LAYOUT_CACHE)))
        _LAYOUT_CACHE[key] = lay
    return lay


def ravel(tree: Any, lay: FlatLayout) -> jnp.ndarray:
    """Concatenate the leaves into one flat fp32 vector (padded)."""
    leaves = lay.treedef.flatten_up_to(tree)
    flat = jnp.concatenate(
        [jnp.reshape(jnp.asarray(l), (-1,)).astype(jnp.float32)
         for l in leaves])
    if lay.padded != lay.total:
        flat = jnp.pad(flat, (0, lay.padded - lay.total))
    return flat


def unravel(flat: jnp.ndarray, lay: FlatLayout) -> Any:
    """Slice the flat vector back into the original tree (original dtypes)."""
    leaves = []
    for shape, dtype, size, off in zip(lay.shapes, lay.dtypes,
                                       lay.sizes, lay.offsets):
        leaves.append(jax.lax.dynamic_slice_in_dim(flat, off, size)
                      .reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(lay.treedef, leaves)


def ravel_span(tree: Any, lay: FlatLayout, off: int, size: int
               ) -> jnp.ndarray:
    """``ravel(tree, lay)[off:off+size]`` built from ONLY the leaves
    overlapping ``[off, off+size)`` — element-identical to slicing the
    full flat vector, but without the full-tree ``concatenate`` barrier:
    a bucket's collective assembled from this depends only on the grads
    in its own span, so XLA's scheduler can issue it as soon as the
    backward tail has produced those leaves (the backward-interleave the
    per-bucket ZeRO chains ride on)."""
    end = off + size
    if off < 0 or size <= 0 or end > lay.padded:
        raise ValueError(f"span [{off}, {end}) outside padded length "
                         f"{lay.padded} (or empty)")
    leaves = lay.treedef.flatten_up_to(tree)
    parts: List[jnp.ndarray] = []
    for leaf, loff, lsize in zip(leaves, lay.offsets, lay.sizes):
        lo, hi = max(off, loff), min(end, loff + lsize)
        if lo >= hi:
            continue
        flat_leaf = jnp.reshape(jnp.asarray(leaf), (-1,)).astype(jnp.float32)
        parts.append(jax.lax.slice_in_dim(flat_leaf, lo - loff, hi - loff))
    covered = max(0, min(end, lay.total) - min(off, lay.total))
    if covered < size:           # the padding tail past lay.total
        parts.append(jnp.zeros(size - covered, jnp.float32))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def unravel_parts(parts: Sequence[jnp.ndarray],
                  bounds: Sequence[Tuple[int, int]],
                  lay: FlatLayout) -> Any:
    """Rebuild the tree from per-span flat pieces (``parts[i]`` covers
    ``bounds[i]``, which must tile the padded vector in order) — the
    inverse of per-bucket :func:`ravel_span`, element-identical to
    ``unravel(concatenate(parts), lay)`` but with each leaf assembled
    from ONLY the pieces covering it: parameter leaf j's value depends
    on its own buckets' producers (the per-bucket all-gathers), not on
    every bucket's, so the first layers' params are ready while later
    buckets are still in flight."""
    if len(parts) != len(bounds):
        raise ValueError(f"{len(parts)} parts vs {len(bounds)} bounds")
    off = 0
    for boff, bsize in bounds:
        if boff != off or bsize <= 0:
            raise ValueError(
                f"bounds {tuple(bounds)} do not tile the flat vector "
                f"(expected contiguous spans from 0 to {lay.padded})")
        off += bsize
    if off != lay.padded:
        raise ValueError(
            f"bounds cover [0, {off}) but the layout is padded to "
            f"{lay.padded} — every leaf must be covered")
    leaves = []
    for shape, dtype, lsize, loff in zip(lay.shapes, lay.dtypes,
                                         lay.sizes, lay.offsets):
        lend = loff + lsize
        if lsize == 0:      # zero-size leaf: occupies no span anywhere
            leaves.append(jnp.zeros(shape, dtype))
            continue
        pieces = []
        for (boff, bsize), part in zip(bounds, parts):
            lo, hi = max(loff, boff), min(lend, boff + bsize)
            if lo >= hi:
                continue
            pieces.append(jax.lax.slice_in_dim(part, lo - boff, hi - boff))
        flat_leaf = pieces[0] if len(pieces) == 1 else \
            jnp.concatenate(pieces)
        leaves.append(flat_leaf.reshape(shape).astype(dtype))
    return jax.tree_util.tree_unflatten(lay.treedef, leaves)


def bucket_bounds(lay: FlatLayout,
                  bucket_bytes: "int | None") -> Tuple[Tuple[int, int], ...]:
    """Static ``(offset, size)`` spans carving the padded flat vector into
    fixed-size buckets of ~``bucket_bytes`` fp32 elements — the
    torch-DDP-style bucketing grid shared by the bucketed DDP allreduce and
    the ZeRO per-bucket reduce-scatter/all-gather
    (:mod:`apex_tpu.parallel.distributed`).

    Every span's size is a multiple of ``lay.padded // lay.chunk`` (the
    shard count the layout was built for), so each bucket reduce-scatters
    cleanly over that axis. ``bucket_bytes=None`` means no bucketing: one
    span covering the whole vector (the monolithic path).
    """
    if bucket_bytes is None:
        return ((0, lay.padded),)
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    chunks = lay.padded // lay.chunk if lay.chunk else 1
    per = max(1, int(bucket_bytes) // 4)          # fp32 elements per bucket
    per = ((per + chunks - 1) // chunks) * chunks  # divisible by shard count
    bounds = []
    off = 0
    while off < lay.padded:
        n = min(per, lay.padded - off)  # tail stays divisible: padded%chunks==0
        bounds.append((off, n))
        off += n
    return tuple(bounds) or ((0, 0),)


def segment_ids(lay: FlatLayout) -> jnp.ndarray:
    """Static flat-index -> tensor-index map (padding gets an extra id so it
    never contaminates a real tensor's norm). The O(padded) host build is
    memoized per layout (LAMB's step rebuilt it every call); only the
    HOST array is cached — the ``jnp.asarray`` runs per call, because a
    device value created inside one trace (a shard_map rewrite tracer)
    must never leak into another."""
    key = None
    try:
        ids = _SEGMENT_CACHE.get(lay)
        key = lay
    except TypeError:
        ids = None
    if ids is None:
        ids = np.full(lay.padded, len(lay.sizes), np.int32)
        for i, (off, size) in enumerate(zip(lay.offsets, lay.sizes)):
            ids[off:off + size] = i
        ids.setflags(write=False)
        if key is not None and ids.nbytes <= _SEGMENT_CACHE_MAX_BYTES:
            total = sum(v.nbytes for v in _SEGMENT_CACHE.values())
            while _SEGMENT_CACHE and \
                    total + ids.nbytes > _SEGMENT_CACHE_MAX_BYTES:
                total -= _SEGMENT_CACHE.pop(
                    next(iter(_SEGMENT_CACHE))).nbytes
            _SEGMENT_CACHE[key] = ids
    return jnp.asarray(ids)
