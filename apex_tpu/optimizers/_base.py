"""Shared machinery for the fused-optimizer suite.

The reference optimizers are ``torch.optim.Optimizer`` subclasses whose
``step`` launches one batched CUDA kernel over the whole parameter set
(``reference:apex/optimizers/fused_adam.py:90-173`` etc.). On TPU the natural
shape is a *pure update function over pytrees* that XLA fuses into a handful of
loops; the class carries only hyperparameters, and all mutable state (step
count, moments) is an explicit pytree the caller threads through jit.

Every optimizer here follows the same protocol::

    opt = FusedAdam(lr=1e-3)
    state = opt.init(params)
    new_params, new_state = opt.step(grads, state, params)

``step`` accepts ``grads_finite`` (a traced bool from
:func:`apex_tpu.amp.all_finite`) and skips the whole update on overflow via an
on-device select — the traced equivalent of amp's patched skip-step
(``reference:apex/amp/handle.py:128-154``). ``lr`` and other schedule-driven
scalars may be passed per-step to override the constructor value, mirroring
param-group ``group['lr']`` mutation in torch.

``as_optax()`` adapts any of these to an ``optax.GradientTransformation`` for
ecosystem interop.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import select_tree
from apex_tpu.observability import health as _health
from apex_tpu.observability import ingraph as _metrics

__all__ = ["OptimizerBase", "tree_unzip", "tree_zeros_like_f32",
           "bias_correction", "global_grad_norm"]


def global_grad_norm(grads: Any) -> jnp.ndarray:
    """Global L2 norm over every floating leaf, accumulated in fp32 — the
    quantity the reference's LAMB global grad-norm clip computes
    (``reference:apex/optimizers/fused_lamb.py:124-133``). Delegates to
    the shared :func:`~apex_tpu.multi_tensor_apply.tree_global_norm`."""
    from apex_tpu.multi_tensor_apply.multi_tensor_apply import (
        tree_global_norm)
    return tree_global_norm(grads)


def tree_unzip(out: Any, treedef, k: int) -> Tuple[Any, ...]:
    """Split a tree whose leaves are k-tuples into k trees of ``treedef``.
    ``k`` is explicit so empty trees (no leaves) still unzip correctly."""
    leaves = treedef.flatten_up_to(out)
    return tuple(treedef.unflatten([l[i] for l in leaves]) for i in range(k))


def tree_zeros_like_f32(params: Any) -> Any:
    """fp32 zeros with the shapes of ``params`` — optimizer state is always
    fp32 regardless of param dtype, matching the master-state behavior of the
    reference fused optimizers under amp O2."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)


def bias_correction(beta: float, step: jnp.ndarray) -> jnp.ndarray:
    """``1 - beta**t`` as an fp32 traced scalar (t = 1-based step count)."""
    return 1.0 - jnp.power(jnp.asarray(beta, jnp.float32), step.astype(jnp.float32))


class OptimizerBase:
    """Mixin providing the overflow-skip wrapper and the optax adapter."""

    def init(self, params: Any) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    def _step(self, grads: Any, state: Any, params: Any, **kw) -> Tuple[Any, Any]:
        raise NotImplementedError  # pragma: no cover - abstract

    @jax.named_scope("optimizer_step")
    def step(self, grads: Any, state: Any, params: Any,
             grads_finite: Optional[jnp.ndarray] = None, **kw) -> Tuple[Any, Any]:
        # the named_scope is a pyprof attribution region
        # (scripts/check_annotations.py contract) — the whole
        # update+overflow-select epilogue prices as one bucket.
        # thunked: the norm reduction is only added to the program when a
        # telemetry collector is active
        _metrics.record("optim/grad_norm",
                        lambda: global_grad_norm(grads), reduce="mean")
        # full-level watchdog: the grads as THIS optimizer consumes them
        # (post-unscale, post-sync — under ZeRO still per-data-rank), named
        # apart from amp's "grads" so neither record double-counts
        _health.observe_tree(grads, "optim_grads", min_level="full")
        new_params, new_state = self._step(grads, state, params, **kw)
        if grads_finite is None:
            new_params_out, new_state_out = new_params, new_state
        else:
            # Skip = keep old params AND old state (step count does not
            # advance), exactly like the reference skipping
            # optimizer.step() wholesale.
            new_params_out = select_tree(grads_finite, new_params, params)
            new_state_out = select_tree(grads_finite, new_state, state)
        # post-select params: a blowing-up health/params/abs_max curve is
        # the earliest pre-overflow warning the stream can give
        _health.observe_tree(new_params_out, "params", min_level="full")
        return new_params_out, new_state_out

    def as_optax(self):
        """Expose as an ``optax.GradientTransformationExtraArgs``; the update
        returns deltas so it composes with optax chains."""
        import optax

        def init_fn(params):
            return self.init(params)

        def update_fn(grads, state, params=None, **extra):
            if params is None:
                raise ValueError("this transformation requires params")
            new_params, new_state = self.step(grads, state, params, **extra)
            updates = jax.tree_util.tree_map(
                lambda n, p: n - p.astype(n.dtype), new_params, params)
            return updates, new_state

        return optax.GradientTransformationExtraArgs(init_fn, update_fn)
