"""JAX version portability shims.

The library targets current JAX (top-level ``jax.shard_map``, the VMA
varying-axes type system), but must stay importable — and keep its
non-model-parallel surface runnable — on the 0.4.x line still found in
some runtime images. Version-dependent lookups live here so call sites
stay clean.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "HAS_VMA", "axis_size", "shard_map_unchecked"]

try:  # jax >= 0.6: top-level export
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # 0.4.x line
    from jax.experimental.shard_map import shard_map  # noqa: F401

# The varying-manual-axes type system (jax.typeof / lax.pcast). Without it
# the vma helpers degrade to no-ops, which matches pre-VMA shard_map
# semantics (no replication types to reconcile). Known limitation of the
# degraded mode: programs whose AD correctness depends on the VMA
# replication rewrite (the 1F1B driver's shared-param cotangent
# accumulation, tied embedding+head grads) can differ numerically from
# the single-device reference on 0.4.x — the parity tests that assert
# those identities only pass on VMA jax.
HAS_VMA = hasattr(jax, "typeof") and hasattr(jax.lax, "pcast")


def shard_map_unchecked(f, **kwargs):
    """``shard_map`` with the replication check relaxed on pre-VMA jax.

    The 0.4.x ``check_rep`` inference cannot see through ``jax.vjp`` inside
    the body (the 1F1B schedule's backward driver), so replicated-by-
    construction outputs fail its static check; the VMA type system
    replaced that inference and verifies the same programs. On VMA jax
    this is plain ``shard_map`` — full checking stays on.
    """
    if not HAS_VMA:
        kwargs.setdefault("check_rep", False)
    return shard_map(f, **kwargs)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """Static size of a bound mesh axis (``lax.axis_size`` predates
        0.6; on 0.4.x ``core.axis_frame`` returns the size directly)."""
        import jax.core as core
        frame = core.axis_frame(axis_name)
        return frame if isinstance(frame, int) else frame.size
