"""Rank-aware logging (reference: ``reference:apex/__init__.py:27-39`` and
``reference:apex/transformer/log_util.py:5-20``).

Every record is prefixed with the process index and, once the parallel state is
initialized, the (dp, tp, pp, vpp) rank tuple — so multi-host logs interleave
legibly. ``rank_zero_only`` gates chatty messages the way amp's ``maybe_print``
does (``reference:apex/amp/_amp_state.py:39-51``).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

__all__ = ["RankInfoFormatter", "get_logger", "setup_logging", "rank_zero_only",
           "set_verbosity"]

_ROOT_NAME = "apex_tpu"


def _process_index() -> int:
    try:
        import jax
        return jax.process_index()
    except Exception:
        return int(os.environ.get("JAX_PROCESS_INDEX", 0))


def _rank_info() -> str:
    """(dp, tp, pp, vpp) like ``parallel_state.get_rank_info``
    (``reference:apex/transformer/parallel_state.py:250-259``)."""
    try:
        from apex_tpu.transformer import parallel_state
        if parallel_state.model_parallel_is_initialized():
            return str(parallel_state.get_rank_info())
    except Exception:
        pass
    return f"(proc {_process_index()})"


class RankInfoFormatter(logging.Formatter):
    def format(self, record):
        record.rank_info = _rank_info()
        return super().format(record)


_configured = False


def setup_logging(level: Optional[int] = None, stream=None) -> logging.Logger:
    """Install the rank-aware handler on the apex_tpu root logger (idempotent).

    ``level=None`` leaves an already-configured logger's level untouched, so
    implicit ``get_logger`` calls never reset a verbosity the user chose.
    """
    global _configured
    logger = logging.getLogger(_ROOT_NAME)
    if not _configured:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(RankInfoFormatter(
            "%(asctime)s %(levelname)s %(rank_info)s %(name)s: %(message)s",
            datefmt="%H:%M:%S"))
        logger.addHandler(handler)
        logger.propagate = False
        logger.setLevel(logging.INFO if level is None else level)
        _configured = True
    elif level is not None:
        logger.setLevel(level)
    return logger


def get_logger(name: Optional[str] = None) -> logging.Logger:
    setup_logging()
    return logging.getLogger(f"{_ROOT_NAME}.{name}" if name else _ROOT_NAME)


def set_verbosity(level: int) -> None:
    logging.getLogger(_ROOT_NAME).setLevel(level)


def rank_zero_only(fn):
    """Decorator: run only on process 0 (cf. ``maybe_print`` rank gating)."""

    def wrapped(*args, **kwargs):
        if _process_index() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped
