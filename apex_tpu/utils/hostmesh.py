"""Bootstrap a virtual multi-device CPU "mesh" in the current process.

The reference requires real GPUs for every distributed test (SURVEY.md §4).
We instead validate DP/TP/PP/SP shardings on XLA's CPU backend with
``--xla_force_host_platform_device_count=N``.  Two subtleties, learned the
hard way (VERDICT r1 item 1):

- The host environment may pre-register an accelerator platform (the axon
  TPU sitecustomize) before user code runs, so env vars alone cannot switch
  platforms — ``jax.config.update("jax_platforms", "cpu")`` must be used,
  and it only works before the backend is first touched.
- ``XLA_FLAGS`` may already carry a (different) device-count flag; it must
  be replaced, not merely left alone.

This switch is process-wide and effectively irreversible once the CPU
backend initializes: callers that also need a real accelerator in the same
process must do that work *first*, or run this in a subprocess (the driver
runs ``dryrun_multichip`` in its own process).
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def force_virtual_cpu_devices(n_devices: int, verify: bool = True) -> None:
    """Force the CPU platform with ``n_devices`` virtual devices.

    Must run before the JAX backend is first used (importing jax is fine;
    calling ``jax.devices()`` etc. is not).  Raises if a backend with fewer
    devices was already initialized.

    ``verify=False`` skips the device-count check — which itself
    INITIALIZES the backend. The multi-process bootstrap
    (:func:`apex_tpu.parallel.multiproc.initialize`) needs that:
    ``jax.distributed.initialize`` refuses to run after any backend use,
    so it sets the flags unverified, rendezvouses, and only then counts
    devices.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if _FLAG in flags:
        flags = re.sub(rf"{_FLAG}=\d+", f"{_FLAG}={n_devices}", flags)
    else:
        flags = (flags + f" {_FLAG}={n_devices}").strip()
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    if verify and jax.device_count() < n_devices:
        raise RuntimeError(
            f"needed {n_devices} virtual CPU devices but the "
            f"{jax.default_backend()} backend is already initialized with "
            f"{jax.device_count()} device(s); call force_virtual_cpu_devices "
            "before any JAX backend use (or in a fresh process)")
