"""Varying-manual-axes (VMA) helpers for scans inside ``shard_map``.

Under JAX's VMA type system a ``lax.scan`` carry must keep the same
varying-axes set every iteration, but a body that uses sharded params (e.g. a
TP bias add) *adds* axes to its output's set. Over-varying the carry up front
would be safe for values but makes AD insert spurious cross-replica psums
(each replica's identical loss counted once per replica), so the right fix is
the *minimal* fixed point, found by abstract evaluation.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp

from apex_tpu.utils.compat import HAS_VMA
from apex_tpu.utils.compat import axis_size as _axis_size

__all__ = ["cast_to_vma", "scan_stable_vma", "invariant_all_gather",
           "varying_all_gather",
           "reconcile_cotangent", "restore_invariant", "leaf_vma",
           "fixed_point_vma"]


def leaf_vma(x) -> frozenset:
    """The varying-manual-axes set of a value (empty outside shard_map,
    and on pre-VMA jax where there is no replication typing at all)."""
    if not HAS_VMA:
        return frozenset()
    return getattr(jax.typeof(x), "vma", None) or frozenset()


def restore_invariant(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Restore the device-INVARIANT type of a value that is replicated by
    construction but typed varying over ``axis_name``.

    The canonical case is a degenerate sharded axis: a param with in_spec
    ``P('tensor')`` is typed tensor-varying even when the axis has size 1,
    and a ``world_size == 1`` fast path that skips its closing collective
    (e.g. :class:`VocabParallelEmbedding`'s lookup) leaks that type into
    everything downstream, breaking replicated out_specs. The psum over the
    size-1 axis is a value identity that fixes the type; outside
    ``shard_map`` (empty vma) this is a no-op.
    """
    if axis_name in leaf_vma(x):
        return jax.lax.psum(x, axis_name)
    return x


def reconcile_cotangent(ct: jnp.ndarray, primal: jnp.ndarray) -> jnp.ndarray:
    """Match a ``custom_vjp`` bwd output's varying-axes type to its primal's.

    Plain-op AD under ``shard_map`` auto-pvaries a replicated operand that
    meets device-varying data, so the pvary transpose psums the cotangent
    back to the replicated total. A ``custom_vjp`` bwd rule sidesteps that
    machinery and must reconcile by hand — newer jax raises when the bwd
    output's varying axes differ from the primal's. Axes the cotangent has
    but the primal lacks are psummed (the chain-rule total for a replicated
    primal — identical to what plain AD produces); axes the primal has but
    the cotangent lacks are pvaried (type-only, value-preserving). No-op
    when the types already agree.
    """
    if not HAS_VMA:
        return ct
    ct_vma = leaf_vma(ct)
    p_vma = leaf_vma(primal)
    extra = tuple(sorted(ct_vma - p_vma))
    if extra:
        ct = jax.lax.psum(ct, extra)
    missing = tuple(sorted(p_vma - ct_vma))
    if missing:
        ct = jax.lax.pcast(ct, missing, to="varying")
    return ct


def cast_to_vma(x: jnp.ndarray, vma: frozenset) -> jnp.ndarray:
    """Upcast ``x`` to be device-varying over at least ``vma`` (idempotent;
    a no-op on pre-VMA jax, whose shard_map has no replication types)."""
    if not HAS_VMA:
        return x
    cur = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(a for a in vma if a not in cur)
    if missing:
        x = jax.lax.pcast(x, missing, to="varying")
    return x


def fixed_point_vma(body: Callable, init: Any, x0: Any = None,
                    max_iters: int = 8) -> Any:
    """Per-LEAF varying-axes fixed point for a scan carry.

    ``body(carry, x) -> (carry, ...)``; ``x0`` is a representative first
    scan element (None for a body that ignores ``x``). Returns a pytree of
    frozensets, one per carry leaf — the minimal axes the body actually
    varies each leaf over. Per-leaf minimality matters: a global union
    would over-vary replicated leaves (e.g. tensor-replicated LN grad
    accumulators), breaking replicated out_specs and making AD insert
    spurious cross-replica psums.
    """
    vma_tree = jax.tree_util.tree_map(leaf_vma, init)
    for _ in range(max_iters):
        init_c = jax.tree_util.tree_map(cast_to_vma, init, vma_tree)
        out = jax.eval_shape(lambda c: body(c, x0)[0], init_c)
        new_tree = jax.tree_util.tree_map(
            lambda v, o: v | leaf_vma(o), vma_tree, out)
        if jax.tree_util.tree_all(jax.tree_util.tree_map(
                lambda a, b: a == b, vma_tree, new_tree)):
            break
        vma_tree = new_tree
    return vma_tree


def scan_stable_vma(body: Callable, init: Any, xs: Any, max_iters: int = 4,
                    unroll: Any = 1):
    """``lax.scan`` whose carry VMA is fixed-pointed against the body
    (per-leaf, via :func:`fixed_point_vma`). ``unroll`` passes through to
    ``lax.scan`` (int factor or ``True`` for full unrolling — the form
    whose compiled program XLA's cost analysis can count end to end,
    used by the pyprof attribution validation path)."""
    first_x = jax.tree_util.tree_map(
        lambda v: jax.lax.index_in_dim(v, 0, 0, keepdims=False), xs)
    vma_tree = fixed_point_vma(body, init, first_x, max_iters=max_iters)

    def stable_body(carry, x):
        new_c, y = body(carry, x)
        return jax.tree_util.tree_map(cast_to_vma, new_c, vma_tree), y

    return jax.lax.scan(
        stable_body, jax.tree_util.tree_map(cast_to_vma, init, vma_tree),
        xs, unroll=unroll)


def varying_all_gather(x: jnp.ndarray, axis_name: str, axis: int = 0,
                       tiled: bool = True) -> jnp.ndarray:
    """``lax.all_gather`` with the input pre-cast device-varying — the
    library's single raw-gather chokepoint.

    On VMA jax a replicated-typed value cannot feed ``all_gather`` directly
    (the op demands a varying operand); on pre-VMA 0.4.x the cast is an
    identity and this is a plain ``all_gather``. Every gather outside this
    module must route here (or through :func:`invariant_all_gather`) so the
    version shim lives in exactly one place —
    ``scripts/check_collectives.py`` (wired into the test suite) flags raw
    ``lax.all_gather`` call sites anywhere else.
    """
    return jax.lax.all_gather(cast_to_vma(x, frozenset({axis_name})),
                              axis_name, axis=axis, tiled=tiled)


def invariant_all_gather(x: jnp.ndarray, axis_name: str, axis: int = 0
                         ) -> jnp.ndarray:
    """Tiled all-gather typed device-INVARIANT: every rank contributes a
    disjoint slice, so the gathered value is provably replicated and can
    cross ``P()`` out_specs / keep replicated-param AD semantics (a plain
    ``all_gather``'s varying type cannot). Wraps the private
    ``jax._src.lax.parallel.all_gather_invariant`` with an equivalent —
    slower, O(world x size) traffic — public-API fallback: place the slice
    at its offset in zeros and psum (disjoint one-hot sum). Shared by the
    ZeRO param gather and the sequence-parallel gathers."""
    try:
        from jax._src.lax.parallel import all_gather_invariant
    except ImportError:  # pragma: no cover - private symbol moved
        size = _axis_size(axis_name)
        rank = jax.lax.axis_index(axis_name)
        full = list(x.shape)
        full[axis] *= size
        return jax.lax.psum(
            jax.lax.dynamic_update_slice_in_dim(
                jnp.zeros(full, x.dtype), x, rank * x.shape[axis],
                axis=axis),
            axis_name)
    return all_gather_invariant(x, axis_name, axis=axis, tiled=True)
