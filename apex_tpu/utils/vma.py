"""Varying-manual-axes (VMA) helpers for scans inside ``shard_map``.

Under JAX's VMA type system a ``lax.scan`` carry must keep the same
varying-axes set every iteration, but a body that uses sharded params (e.g. a
TP bias add) *adds* axes to its output's set. Over-varying the carry up front
would be safe for values but makes AD insert spurious cross-replica psums
(each replica's identical loss counted once per replica), so the right fix is
the *minimal* fixed point, found by abstract evaluation.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp

__all__ = ["cast_to_vma", "scan_stable_vma", "invariant_all_gather"]


def cast_to_vma(x: jnp.ndarray, vma: frozenset) -> jnp.ndarray:
    """Upcast ``x`` to be device-varying over at least ``vma`` (idempotent)."""
    cur = getattr(jax.typeof(x), "vma", frozenset())
    missing = tuple(a for a in vma if a not in cur)
    if missing:
        x = jax.lax.pcast(x, missing, to="varying")
    return x


def scan_stable_vma(body: Callable, init: Any, xs: Any, max_iters: int = 4):
    """``lax.scan`` whose carry VMA is fixed-pointed against the body.

    ``body(carry, x) -> (carry, y)`` with a single-array carry.
    """
    carry_vma = getattr(jax.typeof(init), "vma", None) or frozenset()
    for _ in range(max_iters):
        init_c = cast_to_vma(init, carry_vma)
        first_x = jax.tree_util.tree_map(
            lambda v: jax.lax.index_in_dim(v, 0, 0, keepdims=False), xs)
        out_vma = getattr(jax.eval_shape(lambda c, x: body(c, x)[0],
                                         init_c, first_x),
                          "vma", None) or frozenset()
        if out_vma <= carry_vma:
            break
        carry_vma = carry_vma | out_vma

    def stable_body(carry, x):
        new_c, y = body(carry, x)
        return cast_to_vma(new_c, carry_vma), y

    return jax.lax.scan(stable_body, cast_to_vma(init, carry_vma), xs)


def invariant_all_gather(x: jnp.ndarray, axis_name: str, axis: int = 0
                         ) -> jnp.ndarray:
    """Tiled all-gather typed device-INVARIANT: every rank contributes a
    disjoint slice, so the gathered value is provably replicated and can
    cross ``P()`` out_specs / keep replicated-param AD semantics (a plain
    ``all_gather``'s varying type cannot). Wraps the private
    ``jax._src.lax.parallel.all_gather_invariant`` with an equivalent —
    slower, O(world x size) traffic — public-API fallback: place the slice
    at its offset in zeros and psum (disjoint one-hot sum). Shared by the
    ZeRO param gather and the sequence-parallel gathers."""
    try:
        from jax._src.lax.parallel import all_gather_invariant
    except ImportError:  # pragma: no cover - private symbol moved
        size = jax.lax.axis_size(axis_name)
        rank = jax.lax.axis_index(axis_name)
        full = list(x.shape)
        full[axis] *= size
        return jax.lax.psum(
            jax.lax.dynamic_update_slice_in_dim(
                jnp.zeros(full, x.dtype), x, rank * x.shape[axis],
                axis=axis),
            axis_name)
    return all_gather_invariant(x, axis_name, axis=axis, tiled=True)
