"""Wall-clock timers + the profiling workflow.

Reference: the Megatron-style timers of
``reference:apex/transformer/pipeline_parallel/_timers.py:6-79`` (`_Timer`
with ``cuda.synchronize`` around start/stop, ``_Timers.write`` to
TensorBoard, ``_Timers.log``) and the deprecated pyprof pipeline
(``reference:apex/pyprof``: NVTX-annotate -> nvprof -> attribute cost/op).

TPU re-design:

- ``Timer``/``Timers`` keep the reference API (start/stop/reset/elapsed,
  ``log``, ``write``) but synchronize by *fetching a value* from arrays you
  hand to ``stop(wait_for=...)`` — on async (and tunneled) backends a
  dispatch returns immediately, so the only honest fence is data
  materialization. Without ``wait_for`` the timer measures host wall time
  (dispatch cost), which is also meaningful and is what you want around
  blocking sections.
- pyprof's annotate->trace->attribute loop maps to ``jax.profiler``:
  annotations are ``jax.named_scope`` (emitted into HLO op metadata and
  visible in trace viewers and ``lower().as_text()``); the trace step is
  :func:`profile_trace` (a thin ``jax.profiler.trace`` wrapper); the
  attribution step is the trace viewer (tensorboard / xprof) or
  ``Compiled.cost_analysis()`` for a static FLOP/byte budget per program
  — the role of ``pyprof/prof``'s per-op flop counting.

Hot paths in this library are pre-annotated: DDP gradient allreduce
(``apex_ddp_allreduce``), SyncBatchNorm stats (``sync_bn_stats``), the
pipeline tick (``pipeline_tick``), and the flash-attention call
(``flash_attention``). A captured trace shows these names on the
corresponding fusions; ``scripts/check_annotations.py`` statically
verifies the set. For the structured per-step stream (metrics, not
traces) see :mod:`apex_tpu.observability` — its ``StepReporter`` can
snapshot these timers into TensorBoard/JSONL sinks and export their
start/stop spans as a Chrome trace (``docs/OBSERVABILITY.md``).

Typical workflow::

    from apex_tpu.utils.timers import Timers, profile_trace

    timers = Timers()
    with profile_trace("/tmp/trace"):      # step 2: capture
        for step in range(3):
            timers("fwd-bwd").start()
            grads = grad_fn(params, batch)
            timers("fwd-bwd").stop(wait_for=grads)
            timers("optimizer").start()
            params, opt_state = opt.step(grads, opt_state, params)
            timers("optimizer").stop(wait_for=params)
    timers.log(["fwd-bwd", "optimizer"])   # host-side summary
    # then: tensorboard --logdir /tmp/trace  (step 3: attribute)
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterable, Optional

import jax
import numpy as np

__all__ = ["Timer", "Timers", "profile_trace", "device_fence",
           "set_span_hook"]

# Installed by apex_tpu.observability.trace when span capture is enabled:
# a callable (name, t0, t1) fed from every Timer.stop. Kept as a plain
# module global (not an import of observability) so the default cost is
# one None check per stop and there is no import cycle.
_SPAN_HOOK = None


def set_span_hook(hook) -> None:
    global _SPAN_HOOK
    _SPAN_HOOK = hook


def device_fence(tree: Any) -> None:
    """Block until the computation producing ``tree`` has finished, by
    fetching one element of one leaf. ``jax.block_until_ready`` is
    insufficient on relayed backends (it can track dispatch, not
    completion), so the fence fetches data. One leaf suffices: device
    execution is stream-ordered, so materializing any output of the last
    queued program drains everything before it — and one fetch costs one
    host round trip instead of one per leaf."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and getattr(leaf, "size", 0):
            np.asarray(jax.device_get(jax.numpy.ravel(leaf)[0:1]))
            return


class Timer:
    """``_Timer`` (``_timers.py:9-56``) with explicit device fencing."""

    def __init__(self, name: str):
        self.name = name
        self.elapsed_ = 0.0
        self.count_ = 0
        self.started_ = False
        self._t0 = 0.0

    def start(self) -> None:
        assert not self.started_, f"timer {self.name} already started"
        self._t0 = time.perf_counter()
        self.started_ = True

    def stop(self, wait_for: Any = None) -> None:
        assert self.started_, f"timer {self.name} is not started"
        if wait_for is not None:
            device_fence(wait_for)
        t1 = time.perf_counter()
        self.elapsed_ += t1 - self._t0
        self.count_ += 1
        self.started_ = False
        if _SPAN_HOOK is not None:
            _SPAN_HOOK(self.name, self._t0, t1)

    def reset(self) -> None:
        self.elapsed_ = 0.0
        self.count_ = 0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        """Total elapsed seconds; restarts a running timer like the
        reference (``_timers.py:40-56``)."""
        was_running = self.started_
        if was_running:
            self.stop()
        out = self.elapsed_
        if reset:
            self.reset()
        if was_running:
            self.start()
        return out

    @contextlib.contextmanager
    def __call__(self, wait_for: Any = None):
        self.start()
        try:
            yield
        finally:
            self.stop(wait_for=wait_for)


class Timers:
    """``_Timers`` (``_timers.py:59-79``): a named group."""

    def __init__(self):
        self.timers: Dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def write(self, names: Iterable[str], writer, iteration: int,
              normalizer: float = 1.0, reset: bool = False) -> None:
        """Write to any object with ``add_scalar(tag, value, step)`` (the
        TensorBoard writer protocol, ``_timers.py:66-75``)."""
        assert normalizer > 0.0
        for name in names:
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(name + "-time", value, iteration)

    def log(self, names: Optional[Iterable[str]] = None,
            normalizer: float = 1.0, reset: bool = True) -> str:
        """Format + print ``time (ms) | name: x.xx`` (``_timers.py:76-79``);
        returns the string (also printed) for testability."""
        assert normalizer > 0.0
        if names is None:
            names = list(self.timers)
        string = "time (ms)"
        for name in names:
            ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
            string += " | {}: {:.2f}".format(name, ms)
        print(string, flush=True)
        return string


@contextlib.contextmanager
def profile_trace(log_dir: str, host_tracer_level: int = 2):
    """``jax.profiler.trace`` wrapper — step 2 of the annotate -> trace ->
    attribute workflow (module docstring). View with tensorboard/xprof."""
    with jax.profiler.trace(log_dir, create_perfetto_link=False):
        yield
