"""Preemption-aware auto-resume.

Reference: the ADLR cluster hook — ``get_autoresume``
(``reference:apex/transformer/pipeline_parallel/utils.py:142-144``),
``_set_adlr_autoresume`` (``testing/global_vars.py:156-172``), and the
``--adlr-autoresume-interval`` termination polling in the arg namespace.
The reference imports NVIDIA's external ``AutoResume`` module; here the
same workflow is self-contained and TPU-shaped: Cloud TPU preemptions
deliver SIGTERM with a grace window, so the request source is a signal
handler (plus an optional env-var / callable hook for cluster schedulers),
and the response is "checkpoint through :mod:`apex_tpu.checkpoint`, then
request a clean exit; on restart, ``restore_checkpoint(latest)``".

Usage::

    ar = AutoResume(interval=50)          # poll every 50 steps
    for step in range(start, total):
        ...train...
        if ar.termination_requested(step):
            save_checkpoint(dir, state, step, host_state={"step": step})
            ar.request_resume()           # exit(0) -> scheduler restarts
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from typing import Callable, Optional

__all__ = ["AutoResume"]


class AutoResume:
    """Termination detection + resume request.

    ``hook``: optional callable returning True when the scheduler wants
    the job to stop (the role of ADLR's ``AutoResume.termination_
    requested``); the ``APEX_TPU_TERMINATE`` env var (any non-empty
    value — whitespace-only strings count, only the empty string and an
    unset var do not) and SIGTERM are always honored.

    Every source LATCHES: once SIGTERM arrives, the env var reads
    non-empty, or the hook returns True on any polled step, the request
    is permanent for this instance — a hook that fires once at step K
    and then returns False at K+1 (or an env var cleared between polls)
    cannot lose the termination request.
    """

    def __init__(self, interval: int = 1,
                 hook: Optional[Callable[[], bool]] = None,
                 install_sigterm_handler: bool = True):
        if interval < 1:
            raise ValueError("interval must be >= 1")
        self.interval = interval
        self.hook = hook
        self._flag = threading.Event()
        self._prev_handler = None
        if install_sigterm_handler and threading.current_thread() is \
                threading.main_thread():
            self._prev_handler = signal.signal(
                signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        self._flag.set()
        if callable(self._prev_handler):
            self._prev_handler(signum, frame)

    def termination_requested(self, step: Optional[int] = None) -> bool:
        """True when the job should checkpoint and stop. With ``step``,
        external hooks are only polled every ``interval`` steps (the
        ``--adlr-autoresume-interval`` semantics); the SIGTERM flag is
        always checked."""
        if self._flag.is_set():
            return True
        if step is not None and step % self.interval:
            return False
        # "any non-empty" contract: a whitespace-only value is a request;
        # only unset / empty-string is not
        if os.environ.get("APEX_TPU_TERMINATE", "") != "" or (
                self.hook is not None and bool(self.hook())):
            # latch: a hook that returns True once at step K then False at
            # K+1 (or an env var cleared between polls) must not lose the
            # request — the next poll may be an interval-off step
            self._flag.set()
            return True
        return False

    def close(self) -> None:
        """Restore the previous SIGTERM handler. Call (or use the instance
        as a context manager) when the training run ends, so abandoned
        instances do not permanently swallow SIGTERM or chain handlers."""
        if self._prev_handler is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_handler)
            except ValueError:  # not in main thread anymore
                pass
            self._prev_handler = None

    def __enter__(self) -> "AutoResume":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def request_resume(self, exit_code: int = 0) -> None:
        """Clean exit so the scheduler restarts the job (ADLR
        ``request_resume``). Call after checkpointing."""
        sys.exit(exit_code)
