"""apex_tpu.utils — logging, timers, tree utilities, checkpointing."""

from apex_tpu.utils.autoresume import AutoResume  # noqa: F401
from apex_tpu.utils.timers import (  # noqa: F401
    Timer, Timers, device_fence, profile_trace)
from apex_tpu.utils.logging import (  # noqa: F401
    RankInfoFormatter,
    get_logger,
    rank_zero_only,
    set_verbosity,
    setup_logging,
)
