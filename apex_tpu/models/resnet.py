"""ResNet-50 — the amp/DDP convergence-path model.

Reference usage: ``reference:examples/imagenet/main_amp.py`` (torchvision
resnet50 under amp O0-O3 + apex DDP, the L1 test model) and the fused
bottleneck of ``reference:apex/contrib/bottleneck/bottleneck.py:512`` (cuDNN
conv+bias+relu fusion + halo-exchange spatial parallelism).

TPU design: NHWC convs via ``lax.conv_general_dilated`` (XLA fuses
bias+BN+ReLU epilogues natively — the entire point of fast_bottleneck is a
compiler built-in here), BN is :class:`apex_tpu.parallel.SyncBatchNorm` so
the same model runs single-chip or cross-replica synced, bf16 compute with
fp32 BN stats (amp O2's keep_batchnorm_fp32).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import BatchNormState, SyncBatchNorm

__all__ = ["ResNetConfig", "ResNet50", "Bottleneck"]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # resnet-50
    width: int = 64
    compute_dtype: Any = jnp.bfloat16
    params_dtype: Any = jnp.float32
    bn_axis_name: Optional[str] = None  # "data" => SyncBN
    bn_momentum: float = 0.1
    # apply the BN normalize at compute precision (stats stay fp32). bf16
    # shares fp32's exponent range so this is convergence-safe (unlike the
    # fp16 regime keep_batchnorm_fp32 guards against) and on an HBM-bound
    # chip removes the fp32 elementwise traffic of the fwd+bwd normalize —
    # measured 6% off the headline step, 86.7->79.8 GB/step (docs/PERF.md)
    bn_apply_compute_dtype: bool = True
    # MLPerf-style conv0 reformulation: fold 2x2 spatial blocks of the
    # input into channels (224x224x3 -> 112x112x12) and run the stem as a
    # 4x4 stride-1 conv with correspondingly rearranged (zero-padded 8x8)
    # weights — bit-identical math (parity-tested), 12 input channels
    # instead of 3 on the MXU contraction dim. Default OFF by measurement:
    # on v5e the headline step got SLOWER (94.1 -> 101.3 ms same-session
    # A/B) — this-generation XLA already handles the small-C stem well and
    # the asymmetric-padding form costs more than it saves. Kept as an
    # option for other chip generations.
    stem_space_to_depth: bool = False


def _conv_init(key, shape, dtype):
    # he/kaiming fan-out normal, torchvision's conv init
    fan_out = shape[0] * shape[1] * shape[3]
    std = (2.0 / fan_out) ** 0.5
    return std * jax.random.normal(key, shape, dtype)


def _conv(x, w, stride=1, padding="SAME"):
    # no preferred_element_type: the MXU accumulates bf16 convs in fp32
    # natively, and a widened output dtype breaks the conv transpose rule
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class Bottleneck:
    """1x1 -> 3x3 -> 1x1 with residual; BN+ReLU fused by XLA (the
    fast_bottleneck block)."""

    expansion = 4

    def __init__(self, cfg: ResNetConfig, in_ch: int, ch: int, stride: int):
        self.cfg = cfg
        self.in_ch, self.ch, self.stride = in_ch, ch, stride
        self.out_ch = ch * self.expansion
        self.bn = SyncBatchNorm(1, axis_name=cfg.bn_axis_name,
                                channel_axis=-1, momentum=cfg.bn_momentum)
        self.downsample = stride != 1 or in_ch != self.out_ch

    def _bn_init(self, n):
        return ({"weight": jnp.ones(n, self.cfg.params_dtype),
                 "bias": jnp.zeros(n, self.cfg.params_dtype)},
                BatchNormState(jnp.zeros(n, jnp.float32),
                               jnp.ones(n, jnp.float32),
                               jnp.asarray(0, jnp.int32)))

    def init(self, key):
        cfg = self.cfg
        ks = jax.random.split(key, 4)
        params, state = {}, {}
        params["conv1"] = _conv_init(ks[0], (1, 1, self.in_ch, self.ch),
                                     cfg.params_dtype)
        params["bn1"], state["bn1"] = self._bn_init(self.ch)
        params["conv2"] = _conv_init(ks[1], (3, 3, self.ch, self.ch),
                                     cfg.params_dtype)
        params["bn2"], state["bn2"] = self._bn_init(self.ch)
        params["conv3"] = _conv_init(ks[2], (1, 1, self.ch, self.out_ch),
                                     cfg.params_dtype)
        params["bn3"], state["bn3"] = self._bn_init(self.out_ch)
        if self.downsample:
            params["conv_ds"] = _conv_init(
                ks[3], (1, 1, self.in_ch, self.out_ch), cfg.params_dtype)
            params["bn_ds"], state["bn_ds"] = self._bn_init(self.out_ch)
        return params, state

    def _bn(self, p, s, x, training, z=None, relu=True):
        out, new_s = _bn_apply(self.cfg, p, s, x, training, z=z,
                               fuse_relu=relu)
        return out, new_s

    def __call__(self, params, state, x, training=True):
        new_state = {}
        h = _conv(x, params["conv1"])
        h, new_state["bn1"] = self._bn(params["bn1"], state["bn1"], h, training)
        h = _conv(h, params["conv2"], stride=self.stride)
        h, new_state["bn2"] = self._bn(params["bn2"], state["bn2"], h, training)
        h = _conv(h, params["conv3"])
        if self.downsample:
            sc = _conv(x, params["conv_ds"], stride=self.stride)
            sc, new_state["bn_ds"] = self._bn(params["bn_ds"], state["bn_ds"],
                                              sc, training, relu=False)
        else:
            sc = x
        # fused add+relu epilogue (batch_norm_add_relu of groupbn)
        h, new_state["bn3"] = self._bn(params["bn3"], state["bn3"], h,
                                       training, z=sc)
        return h, new_state


def _bn_apply(cfg, p, s, x, training, z=None, fuse_relu=True):
    from apex_tpu.parallel.sync_batchnorm import sync_batch_norm
    # bf16-only: fp16's narrow exponent range is exactly what the
    # reference's keep_batchnorm_fp32 guards against, so an fp16
    # compute_dtype keeps the fp32 apply
    apply_dtype = (cfg.compute_dtype
                   if (cfg.bn_apply_compute_dtype
                       and jnp.dtype(cfg.compute_dtype) == jnp.bfloat16)
                   else None)
    return sync_batch_norm(
        x, p["weight"], p["bias"], s, training=training,
        momentum=cfg.bn_momentum, channel_axis=-1,
        axis_name=cfg.bn_axis_name, z=z, fuse_relu=fuse_relu,
        apply_dtype=apply_dtype)


class ResNet50:
    """NHWC ResNet-v1.5 (stride-2 in the 3x3, torchvision convention)."""

    def __init__(self, config: ResNetConfig = ResNetConfig()):
        self.cfg = config
        self.blocks = []
        in_ch = config.width
        for i, n in enumerate(config.stage_sizes):
            ch = config.width * (2 ** i)
            stage = []
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                blk = Bottleneck(config, in_ch, ch, stride)
                stage.append(blk)
                in_ch = blk.out_ch
            self.blocks.append(stage)
        self.feat_ch = in_ch

    def init(self, key):
        cfg = self.cfg
        k_stem, k_fc, *k_blocks = jax.random.split(
            key, 2 + sum(cfg.stage_sizes))
        params = {"stem": {
            "conv": _conv_init(k_stem, (7, 7, 3, cfg.width), cfg.params_dtype)}}
        state = {"stem": {}}
        params["stem"]["bn"], state["stem"]["bn"] = \
            Bottleneck(cfg, 3, cfg.width, 1)._bn_init(cfg.width)
        ki = iter(k_blocks)
        for i, stage in enumerate(self.blocks):
            for j, blk in enumerate(stage):
                p, s = blk.init(next(ki))
                params[f"b{i}_{j}"] = p
                state[f"b{i}_{j}"] = s
        bound = 1.0 / (self.feat_ch ** 0.5)
        params["fc"] = {
            "weight": jax.random.uniform(
                k_fc, (cfg.num_classes, self.feat_ch), cfg.params_dtype,
                -bound, bound),
            "bias": jnp.zeros(cfg.num_classes, cfg.params_dtype)}
        return params, state

    def _stem_conv(self, w, x):
        """The 7x7/stride-2 stem conv, optionally in space-to-depth form
        (``stem_space_to_depth``): u = 2a + da - ... each original tap
        index u in [0,7) decomposes as u = 2*ka + da - 1 with ka in [0,4),
        da in {0,1}, so padding the kernel to 8x8 on the low side and
        folding (da, db) into channels gives an exactly-equivalent 4x4
        stride-1 conv over the 2x2-block-folded input, with asymmetric
        spatial padding (2,1)."""
        if not self.cfg.stem_space_to_depth:
            return jax.lax.conv_general_dilated(
                x, w.astype(x.dtype), (2, 2), [(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        n, hh, ww, c = x.shape
        if hh % 2 or ww % 2:
            raise ValueError("space-to-depth stem needs even input dims")
        xs = x.reshape(n, hh // 2, 2, ww // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(n, hh // 2, ww // 2,
                                                    4 * c)
        w8 = jnp.pad(w.astype(x.dtype), ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = w8.reshape(4, 2, 4, 2, c, w.shape[-1])
        w4 = w4.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    w.shape[-1])
        return jax.lax.conv_general_dilated(
            xs, w4, (1, 1), [(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def __call__(self, params, state, x, training=True):
        """x: (n, h, w, 3) NHWC; returns (logits fp32, new_state)."""
        cfg = self.cfg
        x = x.astype(cfg.compute_dtype)
        new_state = {"stem": {}}
        # the named_scope blocks are pyprof attribution regions
        # (scripts/check_annotations.py contract): stem conv+pool,
        # bottleneck body, pooled head — the granularity the per-region
        # roofline reports at
        with jax.named_scope("rn50_stem"):
            h = self._stem_conv(params["stem"]["conv"], x)
            h, new_state["stem"]["bn"] = _bn_apply(
                cfg, params["stem"]["bn"], state["stem"]["bn"], h, training)
            h = jax.lax.reduce_window(
                h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                [(0, 0), (1, 1), (1, 1), (0, 0)])
        with jax.named_scope("rn50_body"):
            for i, stage in enumerate(self.blocks):
                for j, blk in enumerate(stage):
                    h, new_state[f"b{i}_{j}"] = blk(
                        params[f"b{i}_{j}"], state[f"b{i}_{j}"], h,
                        training)
        with jax.named_scope("rn50_head"):
            h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
            w = params["fc"]["weight"].astype(jnp.float32)
            return h @ w.T + params["fc"]["bias"], new_state
