"""Standalone GPT — the flagship model / "model zoo" fixture.

Reference: ``reference:apex/transformer/testing/standalone_gpt.py`` (1,524
LoC) — ``ParallelMLP`` (:236), ``ParallelAttention`` (:285),
``ParallelTransformerLayer`` (:577), ``ParallelTransformer`` (:713),
``Embedding`` (:1000), ``TransformerLanguageModel`` (:1150), ``GPTModel``
(:1440). Same architecture (pre-LN GPT-2 style, learned positions, tied
output embedding, vocab-parallel loss), rebuilt TPU-first:

- attention is the Pallas flash kernel (no seqlen-2048 fused-softmax cap);
- QKV/proj/MLP are Column/Row-parallel over the ``tensor`` axis with heads
  sharded tp-ways, exactly the reference's sharding;
- homogeneous layers are stacked and scanned (``lax.scan``) so compile time
  is O(1) in depth — the idiomatic XLA shape for deep stacks — with optional
  per-layer remat (the reference's activation checkpointing);
- everything is bf16 compute / fp32 params by default (amp O2 semantics).

Works single-chip (tp=1, no mesh needed), under ``shard_map`` for TP, and as
a pipeline ``stage_fn`` (see :meth:`GPTModel.stage_fn`).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.normalization import fused_layer_norm_affine
from apex_tpu.ops.dropout import dropout
from apex_tpu.remat import RematPolicy, tag as _remat_tag
from apex_tpu.ops.flash_attention import (decode_attention, flash_attention,
                                          paged_decode_attention)
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
from apex_tpu.transformer import tensor_parallel as tp_mod
from apex_tpu.transformer.parallel_state import TENSOR_AXIS
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    _local_shard, init_method_normal)
from apex_tpu.utils.vma import scan_stable_vma

__all__ = ["GPTConfig", "GPTModel"]


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    """Sizes follow the Megatron arg names (``testing/arguments.py``)."""
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    ffn_hidden_size: Optional[int] = None  # default 4*hidden
    tensor_model_parallel_size: int = 1
    params_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    init_method_std: float = 0.02
    layernorm_epsilon: float = 1e-5
    # Per-layer activation rematerialization. ``remat_policy`` is the
    # knob: None | "none" | "full" | "selective" | "offload" | a
    # remat.RematPolicy instance ("selective" keeps the registry-tagged
    # GEMM/flash outputs resident and recomputes only the cheap LN/gelu
    # tier — see apex_tpu/remat.py). ``remat: bool`` is the deprecated
    # pre-policy spelling, honored (True -> "full") when remat_policy is
    # None. ``remat_names``: custom save-list for the name-based modes.
    remat: bool = False
    remat_policy: Any = None
    remat_names: Optional[Tuple[str, ...]] = None
    use_flash: Optional[bool] = None  # None = auto by shape/backend
    # Megatron-LM sequence parallelism: norms/dropout/residuals run on
    # (b, s/tp, h) sequence shards; ColumnParallel inputs all-gather the
    # sequence, RowParallel outputs reduce-scatter back to shards
    sequence_parallel: bool = False
    # Ring-decompose the SP gather/reduce-scatter under their GEMMs
    # (tensor_parallel.collective_matmul) so the dependent TP collectives
    # overlap with compute in fwd AND bwd; requires sequence_parallel
    tp_comm_overlap: bool = False
    # Layer-stack scan unroll factor (lax.scan's ``unroll``): 1 = compact
    # while loop (O(1) compile in depth), num_layers/True = fully
    # unrolled. Unrolled programs are what XLA's cost_analysis can count
    # end to end (a while body is priced once regardless of trip count),
    # so scripts/attribute_step.py uses True to validate the pyprof
    # roofline against flops_budget; on TPU, small factors (2-4) can also
    # buy scheduling overlap across layer boundaries.
    layer_scan_unroll: Any = 1
    # Dropout (standalone_gpt.py attention/hidden dropout; 0.0 = off so
    # eval-style calls stay deterministic without threading an rng).
    # Semantics under TP follow the reference's RNG stream layout
    # (tensor_parallel/random.py:200-230): hidden+embedding dropout draw
    # from the caller's key (identical across TP ranks — the activations
    # are replicated), attention-probability dropout folds in the TP rank
    # (the heads are sharded, each rank's slice gets an independent mask).
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0

    @property
    def ffn(self) -> int:
        return self.ffn_hidden_size or 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads


class GPTModel:
    """Param-factory GPT. ``init(key)`` -> params pytree; ``__call__`` gives
    logits; ``loss`` gives the LM loss (vocab-parallel when tp>1)."""

    def __init__(self, config: GPTConfig):
        cfg = config
        if cfg.hidden_size % cfg.num_attention_heads:
            raise ValueError("hidden_size must divide num_attention_heads")
        if cfg.num_attention_heads % cfg.tensor_model_parallel_size:
            raise ValueError("heads must divide tp size")
        self.cfg = cfg
        # remat policy resolved ONCE (the deprecation warning for the
        # legacy bool fires here); models gate their checkpoint_name tags
        # on uses_names so none/full programs stay tag-free and
        # jaxpr-identical to the pre-policy ones
        policy = RematPolicy.resolve(
            cfg.remat_policy, legacy_bool=cfg.remat,
            owner=type(cfg).__name__)
        if cfg.remat_names is not None:
            if not policy.uses_names:
                raise ValueError(
                    "remat_names requires a name-based remat_policy "
                    "('selective' or 'offload'), got "
                    f"{policy.mode!r}")
            if policy.names is not None and policy.names != tuple(
                    cfg.remat_names):
                raise ValueError(
                    "conflicting save-lists: remat_policy carries "
                    f"names={policy.names!r} but remat_names="
                    f"{tuple(cfg.remat_names)!r}; set the list in one "
                    "place")
            policy = dataclasses.replace(
                policy, names=tuple(cfg.remat_names))
        self.remat_policy = policy
        self._tag = (_remat_tag if policy.uses_names
                     else (lambda x, name: x))
        tp = cfg.tensor_model_parallel_size
        init = init_method_normal(cfg.init_method_std)
        # output-layer init scaled by sqrt(2*layers) (standalone_gpt.py
        # scaled_init_method pattern)
        out_init = init_method_normal(
            cfg.init_method_std / math.sqrt(2.0 * cfg.num_layers))
        self.embedding = VocabParallelEmbedding(
            cfg.vocab_size, cfg.hidden_size, init_method=init,
            params_dtype=cfg.params_dtype, world_size=tp)
        if cfg.sequence_parallel and tp <= 1:
            raise ValueError("sequence_parallel requires tp > 1")
        if cfg.tp_comm_overlap and not cfg.sequence_parallel:
            raise ValueError(
                "tp_comm_overlap requires sequence_parallel=True: only the "
                "SP gather->GEMM / GEMM->reduce-scatter pairs are dependent "
                "collectives (plain-TP collectives already overlap)")
        sp = cfg.sequence_parallel
        ov = cfg.tp_comm_overlap
        self.qkv = ColumnParallelLinear(
            cfg.hidden_size, 3 * cfg.hidden_size, gather_output=False,
            init_method=init, params_dtype=cfg.params_dtype, world_size=tp,
            sequence_parallel=sp, seq_axis=1, tp_comm_overlap=ov)
        self.proj = RowParallelLinear(
            cfg.hidden_size, cfg.hidden_size, input_is_parallel=True,
            init_method=out_init, params_dtype=cfg.params_dtype,
            world_size=tp, sequence_parallel=sp, seq_axis=1,
            tp_comm_overlap=ov)
        self.fc1 = ColumnParallelLinear(
            cfg.hidden_size, cfg.ffn, gather_output=False, init_method=init,
            params_dtype=cfg.params_dtype, world_size=tp,
            sequence_parallel=sp, seq_axis=1, tp_comm_overlap=ov)
        self.fc2 = RowParallelLinear(
            cfg.ffn, cfg.hidden_size, input_is_parallel=True,
            init_method=out_init, params_dtype=cfg.params_dtype,
            world_size=tp, sequence_parallel=sp, seq_axis=1,
            tp_comm_overlap=ov)

    # -- params -------------------------------------------------------------

    def _layer_init(self, key: jax.Array) -> dict:
        cfg = self.cfg
        k = jax.random.split(key, 4)
        h = cfg.hidden_size
        return {
            "ln1": {"weight": jnp.ones(h, cfg.params_dtype),
                    "bias": jnp.zeros(h, cfg.params_dtype)},
            "qkv": self.qkv.init(k[0]),
            "proj": self.proj.init(k[1]),
            "ln2": {"weight": jnp.ones(h, cfg.params_dtype),
                    "bias": jnp.zeros(h, cfg.params_dtype)},
            "fc1": self.fc1.init(k[2]),
            "fc2": self.fc2.init(k[3]),
        }

    def init(self, key: jax.Array) -> dict:
        cfg = self.cfg
        kw, kp, kl = jax.random.split(key, 3)
        layer_keys = jax.random.split(kl, cfg.num_layers)
        layers = jax.vmap(self._layer_init)(layer_keys)
        return {
            "embedding": {
                "word": self.embedding.init(kw),
                "position": init_method_normal(cfg.init_method_std)(
                    kp, (cfg.max_position_embeddings, cfg.hidden_size)
                ).astype(cfg.params_dtype),
            },
            "layers": layers,  # leaves stacked (num_layers, ...)
            "final_ln": {"weight": jnp.ones(cfg.hidden_size, cfg.params_dtype),
                         "bias": jnp.zeros(cfg.hidden_size, cfg.params_dtype)},
        }

    def param_specs(self, params: dict):
        """``PartitionSpec`` tree for a :meth:`init` params pytree under
        the standard TP layout (vocab-sharded embedding, per-layer TP
        stacks on axis 1, replicated norms/positions) — the specs every
        ``shard_map`` over the whole model needs; keep call sites on this
        helper instead of hand-copying the literal."""
        from jax.sharding import PartitionSpec as P
        return {
            "embedding": {"word": {"weight": P("tensor")},
                          "position": P()},
            "final_ln": {"weight": P(), "bias": P()},
            "layers": jax.tree_util.tree_map(
                lambda p: P(None, "tensor") if p.ndim >= 3 else P(),
                params["layers"]),
        }

    # -- blocks -------------------------------------------------------------

    def _ln(self, p: dict, x: jnp.ndarray) -> jnp.ndarray:
        # mixed-dtype rule: bf16 activations, fp32 ln params -> bf16 out.
        # The named_scope is a pyprof attribution region
        # (scripts/check_annotations.py contract).
        with jax.named_scope("gpt_ln"):
            out = fused_layer_norm_affine(
                x, p["weight"].astype(x.dtype), p["bias"].astype(x.dtype),
                self.cfg.hidden_size, eps=self.cfg.layernorm_epsilon)
        # dropped by the selective policy: recomputing an LN is one fused
        # elementwise pass — the cheap tier selective remat exists to shed
        return self._tag(out, "ln_out")

    @jax.named_scope("gpt_attention")
    def _attention(self, lp: dict, x: jnp.ndarray,
                   attn_seed=None, collect_kv: bool = False):
        cfg = self.cfg
        b = x.shape[0]
        local_heads = cfg.num_attention_heads // cfg.tensor_model_parallel_size
        qkv, _ = self.qkv(lp["qkv"], x)  # (b, s_full, 3*h/tp) — under SP
        # the ColumnParallel input gather restores the full sequence here
        qkv = self._tag(qkv, "qkv_out")
        s = qkv.shape[1]
        qkv = qkv.reshape(b, s, local_heads, 3 * cfg.head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = jnp.transpose(q, (0, 2, 1, 3))  # (b, nh, s, d)
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        rate = cfg.attention_dropout if attn_seed is not None else 0.0
        ctx = flash_attention(q, k, v, causal=True,
                              use_pallas=cfg.use_flash,
                              dropout_rate=rate, dropout_seed=attn_seed,
                              checkpoint_names=self.remat_policy.uses_names)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(b, s, -1)
        out, _ = self.proj(lp["proj"], ctx)
        out = self._tag(out, "attn_proj_out")
        if collect_kv:
            # prefill: the serving cache wants this layer's K/V alongside
            return out, (k, v)
        return out

    @jax.named_scope("gpt_mlp")
    def _mlp(self, lp: dict, x: jnp.ndarray) -> jnp.ndarray:
        h, _ = self.fc1(lp["fc1"], x)
        # tagged PRE-gelu: saving the GEMM output costs the same bytes and
        # leaves only the elementwise gelu to recompute for fc2's dW
        h = self._tag(h, "mlp_fc1_out")
        h = jax.nn.gelu(h, approximate=True)
        out, _ = self.fc2(lp["fc2"], h)
        return self._tag(out, "mlp_fc2_out")

    def _layer(self, lp: dict, x: jnp.ndarray, lrng=None,
               collect_kv: bool = False):
        cfg = self.cfg
        attn_seed = lrng["attn_seed"] if lrng is not None else None
        a = self._attention(lp, self._ln(lp["ln1"], x), attn_seed,
                            collect_kv=collect_kv)
        if collect_kv:
            a, kv = a
        if lrng is not None:
            a = dropout(a, cfg.hidden_dropout, lrng["h1"])
        x = x + a
        m = self._mlp(lp, self._ln(lp["ln2"], x))
        if lrng is not None:
            m = dropout(m, cfg.hidden_dropout, lrng["h2"])
        x = x + m
        return (x, kv) if collect_kv else x

    def _layer_rngs(self, dropout_rng: jax.Array) -> dict:
        """Per-layer dropout randomness, stacked (num_layers, ...) for the
        scan: attention seeds from the TP-rank-folded stream, hidden keys
        from the caller's (TP-replicated) stream."""
        cfg = self.cfg
        attn_key = dropout_rng
        if cfg.tensor_model_parallel_size > 1:
            attn_key = jax.random.fold_in(
                attn_key, jax.lax.axis_index(TENSOR_AXIS) + 1)
        seeds = jax.random.randint(
            jax.random.fold_in(attn_key, 1), (cfg.num_layers,), 0,
            2 ** 31 - 1)
        hidden_key = jax.random.fold_in(dropout_rng, 2)
        if cfg.sequence_parallel:
            # SP: hidden dropout acts on per-rank sequence shards, so each
            # rank needs an independent stream (Megatron SP RNG semantics)
            hidden_key = jax.random.fold_in(
                hidden_key, jax.lax.axis_index(TENSOR_AXIS) + 1)
        hkeys = jax.random.split(hidden_key, 2 * cfg.num_layers)
        hkeys = hkeys.reshape(cfg.num_layers, 2, *hkeys.shape[1:])
        return {"attn_seed": seeds, "h1": hkeys[:, 0], "h2": hkeys[:, 1]}

    # -- forward ------------------------------------------------------------

    @jax.named_scope("gpt_embed")
    def embed(self, params: dict, tokens: jnp.ndarray,
              dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        cfg = self.cfg
        h = self.embedding(params["embedding"]["word"], tokens)
        pos = params["embedding"]["position"][: tokens.shape[1]]
        h = (h + pos).astype(cfg.compute_dtype)
        if cfg.sequence_parallel:
            from apex_tpu.transformer.context_parallel import (
                scatter_to_sequence_parallel_region)
            h = scatter_to_sequence_parallel_region(h, TENSOR_AXIS,
                                                    seq_axis=1)
        if dropout_rng is not None:
            # embedding dropout at the hidden rate (standalone_gpt
            # Embedding); under SP the rate applies to this rank's shard
            # with a rank-folded key (Megatron's SP RNG stream)
            key = jax.random.fold_in(dropout_rng, 3)
            if cfg.sequence_parallel:
                key = jax.random.fold_in(
                    key, jax.lax.axis_index(TENSOR_AXIS) + 1)
            h = dropout(h, cfg.hidden_dropout, key)
        return h

    def tp_overlap_fwd_bytes(self, shard_shape: Tuple[int, ...]) -> int:
        """Per-rank forward-ring ppermute bytes for ONE pass through the
        layer stack on a ``(b, s/tp, h)`` activation shard — the
        ``tp/collective_bytes`` accounting (a trace-time constant). The
        backward rings move the same chunk counts with fp32 payloads
        (dX/dY cotangents), so train-step traffic is this plus the
        fp32-scaled mirror."""
        cfg = self.cfg
        tp = cfg.tensor_model_parallel_size
        shard = 1
        for d in shard_shape:
            shard *= d
        col_bytes = shard * jnp.dtype(cfg.compute_dtype).itemsize
        row_bytes = shard * 4  # the traveling partial-sum acc is fp32
        # two Column rings (qkv, fc1) + two Row rings (proj, fc2) per layer
        return cfg.num_layers * (tp - 1) * (2 * col_bytes + 2 * row_bytes)

    def record_tp_overlap(self, shard_shape: Tuple[int, ...],
                          passes: int = 1) -> None:
        """``tp/*`` telemetry for the ring-decomposed SP collectives — the
        single recording site, called at the step-trace level (outside the
        layer scan / custom_vjp) because a record inside the scanned rings
        would capture one body *trace* instead of ``num_layers``
        *executions*. ``passes``: layer-stack passes per step (microbatch
        count under the pipelined trainer)."""
        from apex_tpu.observability import ingraph
        if not ingraph.recording():
            return
        ingraph.record("tp/overlap_chunks",
                       float(self.cfg.tensor_model_parallel_size),
                       reduce="mean")
        ingraph.record("tp/collective_bytes",
                       float(passes * self.tp_overlap_fwd_bytes(
                           shard_shape)), reduce="sum")

    def transform(self, params: dict, x: jnp.ndarray,
                  dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """Run the layer stack (scan) + final LN. ``dropout_rng`` enables
        train-mode dropout (None = eval/deterministic)."""
        cfg = self.cfg
        if cfg.tp_comm_overlap:
            self.record_tp_overlap(x.shape)
        layer_fn = self.remat_policy.wrap(self._layer)
        use_dropout = dropout_rng is not None and (
            cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0)

        if use_dropout:
            xs = (params["layers"], self._layer_rngs(dropout_rng))

            def body(x, lp_rng):
                lp, lrng = lp_rng
                return layer_fn(lp, x, lrng), None
        else:
            xs = params["layers"]

            def body(x, lp):
                return layer_fn(lp, x), None

        x, _ = scan_stable_vma(body, x, xs,
                               unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        if cfg.sequence_parallel:
            from apex_tpu.transformer.context_parallel import (
                gather_from_sequence_parallel_region)
            x = gather_from_sequence_parallel_region(x, TENSOR_AXIS,
                                                     seq_axis=1,
                                                     invariant=True)
        return x

    @jax.named_scope("gpt_head_loss")
    def logits(self, params: dict, x: jnp.ndarray) -> jnp.ndarray:
        """Tied output embedding (standalone_gpt.py parallel_lm_logits):
        returns vocab-parallel logits (local shard) when tp>1."""
        w = _local_shard(params["embedding"]["word"]["weight"],
                         self.cfg.tensor_model_parallel_size)
        if self.cfg.tensor_model_parallel_size == 1:
            from apex_tpu.utils.vma import restore_invariant
            from apex_tpu.transformer.parallel_state import TENSOR_AXIS
            w = restore_invariant(w, TENSOR_AXIS)
        return jax.lax.dot_general(
            x, w.astype(x.dtype), (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    def __call__(self, params: dict, tokens: jnp.ndarray,
                 dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        return self.logits(params, self.transform(
            params, self.embed(params, tokens, dropout_rng), dropout_rng))

    def loss(self, params: dict, tokens: jnp.ndarray,
             targets: jnp.ndarray, loss_mask: Optional[jnp.ndarray] = None,
             dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """LM loss; vocab-parallel CE over the tensor axis when tp>1
        (``standalone_gpt.py`` post_language_model_processing).
        ``dropout_rng`` enables train-mode dropout."""
        logits = self(params, tokens, dropout_rng)
        with jax.named_scope("gpt_head_loss"):
            if self.cfg.tensor_model_parallel_size > 1:
                per_tok = vocab_parallel_cross_entropy(logits, targets)
            else:
                per_tok = softmax_cross_entropy_loss(
                    logits.reshape(-1, logits.shape[-1]),
                    targets.reshape(-1),
                    padding_idx=None, half_to_float=True
                ).reshape(targets.shape)
            if loss_mask is not None:
                return jnp.sum(per_tok * loss_mask) / jnp.maximum(
                    jnp.sum(loss_mask), 1.0)
            return jnp.mean(per_tok)

    # -- serving: KV-cached prefill/decode ----------------------------------

    def _require_cacheable(self):
        cfg = self.cfg
        if cfg.tensor_model_parallel_size != 1 or cfg.sequence_parallel:
            raise NotImplementedError(
                "the KV-cached serving path runs tp=1 (serve-mesh "
                "resharding is ROADMAP item 3); got tp="
                f"{cfg.tensor_model_parallel_size}, sequence_parallel="
                f"{cfg.sequence_parallel}")

    def _decode_layer(self, lp: dict, x: jnp.ndarray, layer_cache,
                      lengths: jnp.ndarray):
        """One layer of the decode step: ``x`` is ``(S, 1, hidden)`` (one
        token per slot), ``layer_cache`` this layer's ``(ck, cv, ksc,
        vsc)`` cache slices. Returns ``(x, (k_new, v_new))`` — the new
        token's K/V ``(S, H, D)``, appended to the cache by the caller
        AFTER the scan (the kernel merges the current token itself, so
        the cache is read-only inside the layer stack)."""
        cfg = self.cfg
        h = self._ln(lp["ln1"], x)
        with jax.named_scope("gpt_attention"):
            qkv, _ = self.qkv(lp["qkv"], h)       # (S, 1, 3*hidden)
            S = qkv.shape[0]
            qkv = qkv.reshape(S, cfg.num_attention_heads, 3 * cfg.head_dim)
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)   # (S, H, D)
            ck, cv, ksc, vsc = layer_cache
            ctx = decode_attention(q, ck, cv, lengths, k_new=k_new,
                                   v_new=v_new, k_scale=ksc, v_scale=vsc,
                                   use_pallas=cfg.use_flash)
            out, _ = self.proj(lp["proj"], ctx.reshape(S, 1, -1))
        x = x + out
        x = x + self._mlp(lp, self._ln(lp["ln2"], x))
        return x, (k_new, v_new)

    def forward(self, params: dict, tokens: jnp.ndarray,
                dropout_rng: Optional[jax.Array] = None,
                kv_cache=None, positions: Optional[jnp.ndarray] = None,
                slot=None, prompt_len=None,
                last_logit_only: bool = False,
                active: Optional[jnp.ndarray] = None,
                block_row: Optional[jnp.ndarray] = None,
                block_tables: Optional[jnp.ndarray] = None,
                lengths: Optional[jnp.ndarray] = None,
                append_block_ids: Optional[jnp.ndarray] = None,
                append_offsets: Optional[jnp.ndarray] = None,
                cow_src: Optional[jnp.ndarray] = None,
                cow_dst: Optional[jnp.ndarray] = None,
                mean_context: Optional[float] = None):
        """The cache-threading entry point (docs/SERVING.md).

        Without ``kv_cache`` this is :meth:`__call__`. With a
        :class:`~apex_tpu.serving.cache.KVCache` it dispatches on ``slot``:

        - **prefill** (``slot`` given): ``tokens`` is ``(1, P)`` — the
          ordinary causal forward (same flash path, same layer scan as
          training) that ALSO collects every layer's K/V and writes them
          into cache slot ``slot``, cursor set to ``prompt_len``
          (default ``P``; right-pad shorter prompts). Returns
          ``(logits (1, P, vocab), new_cache)``.
        - **decode** (no ``slot``): ``tokens`` is ``(max_seqs, 1)`` — one
          token per slot, every slot stepping together under a fixed
          shape. Attention runs the decode kernel over each slot's cached
          prefix, the new K/V are appended at each slot's own cursor, and
          cursors advance. ``positions`` (default: the cache cursors)
          indexes the position embedding. Returns
          ``(logits (max_seqs, vocab), new_cache)``.

        ``active`` (decode only): ``(max_seqs,)`` bool — slots NOT in it
        keep a frozen cursor (their garbage token lands at the same
        position each step and the next prefill overwrites it), so free
        slots never grow an attention prefix. Default: all advance.

        ``last_logit_only`` (prefill only): project the vocab head for
        JUST the position ``prompt_len - 1`` — logits come back
        ``(1, 1, vocab)``. The full-prompt head is the largest matmul in
        a prefill and a serving admission samples exactly one row of it;
        the serving engine always sets this (parity tests use the
        default full logits).

        With a :class:`~apex_tpu.serving.cache.PagedKVCache` the same
        two legs run against the global block pool instead
        (docs/SERVING.md "Paged serving"): **paged prefill** writes the
        collected K/V into the pool blocks named by ``block_row``
        (``(P // block_size,)`` int32, null-padded); **paged decode**
        (``block_row=None``) first resolves any copy-on-write pairs
        (``cow_src``/``cow_dst``, null pairs no-op), reads each slot's
        context through ``block_tables``/``lengths`` with the bounded
        paged kernel — HBM per step is O(actual context), not
        O(max_len) — and appends the new token at
        ``append_block_ids``/``append_offsets`` (host-computed; null
        entries drop the write). ``mean_context`` only prices the
        kernel's CostEstimate for pyprof.

        Both legs are inference-mode (no dropout) and are meant to be
        AOT-compiled with the cache donated — see
        :class:`apex_tpu.serving.engine.ServingEngine`.
        """
        if kv_cache is None:
            return self(params, tokens, dropout_rng)
        self._require_cacheable()
        # lazy: serving -> engine -> gpt would cycle at import time
        from apex_tpu.serving.cache import PagedKVCache
        if isinstance(kv_cache, PagedKVCache):
            if block_row is not None:
                return self._paged_prefill_forward(
                    params, tokens, kv_cache, block_row, prompt_len,
                    last_logit_only)
            return self._paged_decode_forward(
                params, tokens, kv_cache, block_tables, lengths,
                append_block_ids, append_offsets, cow_src, cow_dst,
                mean_context)
        if slot is not None:
            return self._prefill_forward(params, tokens, kv_cache, slot,
                                         prompt_len, last_logit_only)
        return self._decode_forward(params, tokens, kv_cache, positions,
                                    active)

    def _prefill_forward(self, params, tokens, cache, slot, prompt_len,
                         last_logit_only=False):
        cfg = self.cfg
        b, P = tokens.shape
        if b != 1:
            raise ValueError(f"prefill is per-request: tokens must be "
                             f"(1, P), got {tokens.shape}")
        if P > cache.max_len:
            raise ValueError(f"prompt window {P} exceeds cache max_len "
                             f"{cache.max_len}")
        if prompt_len is None:
            prompt_len = P
        elif isinstance(prompt_len, int):
            # a cursor past the written window would make every later
            # decode read stale cache — reject statically when we can
            if not 0 < prompt_len <= P:
                raise ValueError(f"prompt_len {prompt_len} outside the "
                                 f"written window (1, {P}]")
        else:
            # traced (the AOT engine path): clamp for the same reason
            prompt_len = jnp.clip(jnp.asarray(prompt_len, jnp.int32), 1,
                                  P)
        x = self.embed(params, tokens)

        def body(x, lp):
            return self._layer(lp, x, collect_kv=True)

        x, (k_all, v_all) = scan_stable_vma(body, x, params["layers"],
                                            unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        if last_logit_only:
            # the head is per-position: gathering the hidden row BEFORE
            # the vocab projection skips (P-1)/P of the prefill's
            # largest matmul
            x = jax.lax.dynamic_slice_in_dim(
                x, jnp.asarray(prompt_len, jnp.int32) - 1, 1, axis=1)
        logits = self.logits(params, x)
        # ys stacked (L, 1, H, P, D) -> (L, H, P, D) for the slot write
        cache = cache.write_prompt(k_all[:, 0], v_all[:, 0], slot,
                                   prompt_len)
        return logits, cache

    def _decode_forward(self, params, tokens, cache, positions,
                        active=None):
        cfg = self.cfg
        if tokens.ndim != 2 or tokens.shape[1] != 1:
            raise ValueError(f"decode tokens must be (max_seqs, 1), got "
                             f"{tokens.shape}")
        if positions is None:
            positions = cache.lengths
        with jax.named_scope("gpt_embed"):
            h = self.embedding(params["embedding"]["word"], tokens)
            pos = jnp.take(
                params["embedding"]["position"],
                jnp.clip(positions, 0, cfg.max_position_embeddings - 1),
                axis=0)[:, None]
            x = (h + pos).astype(cfg.compute_dtype)

        xs = (params["layers"], cache.k, cache.v)
        if cache.quantized:
            xs = xs + (cache.k_scale, cache.v_scale)

        def body(x, lp_c):
            lp, ck, cv = lp_c[:3]
            ksc, vsc = (lp_c[3], lp_c[4]) if cache.quantized else (None,
                                                                   None)
            return self._decode_layer(lp, x, (ck, cv, ksc, vsc),
                                      cache.lengths)

        x, (k_new, v_new) = scan_stable_vma(body, x, xs,
                                            unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        logits = self.logits(params, x)[:, 0]
        # `active` (``(max_seqs,)`` bool): only those slots advance their
        # cursor — free slots must not creep one garbage position per
        # step (see KVCache.append)
        return logits, cache.append(k_new, v_new, active)

    def _paged_decode_layer(self, lp: dict, x: jnp.ndarray, layer_pool,
                            block_tables: jnp.ndarray,
                            lengths: jnp.ndarray,
                            mean_context: Optional[float]):
        """One layer of the paged decode step: like :meth:`_decode_layer`
        but the context comes through each slot's block table, so only
        ~ceil(cursor/block_size) pool blocks are streamed per slot."""
        cfg = self.cfg
        h = self._ln(lp["ln1"], x)
        with jax.named_scope("gpt_attention"):
            qkv, _ = self.qkv(lp["qkv"], h)       # (S, 1, 3*hidden)
            S = qkv.shape[0]
            qkv = qkv.reshape(S, cfg.num_attention_heads, 3 * cfg.head_dim)
            q, k_new, v_new = jnp.split(qkv, 3, axis=-1)   # (S, H, D)
            kp, vp, ksc, vsc = layer_pool
            ctx = paged_decode_attention(
                q, kp, vp, block_tables, lengths, k_new=k_new,
                v_new=v_new, k_scale=ksc, v_scale=vsc,
                mean_context=mean_context, use_pallas=cfg.use_flash)
            out, _ = self.proj(lp["proj"], ctx.reshape(S, 1, -1))
        x = x + out
        x = x + self._mlp(lp, self._ln(lp["ln2"], x))
        return x, (k_new, v_new)

    def _paged_prefill_forward(self, params, tokens, cache, block_row,
                               prompt_len, last_logit_only=False):
        cfg = self.cfg
        b, P = tokens.shape
        if b != 1:
            raise ValueError(f"prefill is per-request: tokens must be "
                             f"(1, P), got {tokens.shape}")
        if P % cache.block_size != 0:
            raise ValueError(f"paged prefill window {P} must be a "
                             f"multiple of block_size {cache.block_size}")
        if prompt_len is None:
            prompt_len = P
        elif isinstance(prompt_len, int):
            if not 0 < prompt_len <= P:
                raise ValueError(f"prompt_len {prompt_len} outside the "
                                 f"written window (1, {P}]")
        else:
            prompt_len = jnp.clip(jnp.asarray(prompt_len, jnp.int32), 1,
                                  P)
        x = self.embed(params, tokens)

        def body(x, lp):
            return self._layer(lp, x, collect_kv=True)

        x, (k_all, v_all) = scan_stable_vma(body, x, params["layers"],
                                            unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        if last_logit_only:
            x = jax.lax.dynamic_slice_in_dim(
                x, jnp.asarray(prompt_len, jnp.int32) - 1, 1, axis=1)
        logits = self.logits(params, x)
        # ys stacked (L, 1, H, P, D) -> (L, H, P, D) block-scattered
        # into the pool; null block_row entries absorb the padding
        cache = cache.write_prompt_blocks(k_all[:, 0], v_all[:, 0],
                                          jnp.asarray(block_row,
                                                      jnp.int32))
        return logits, cache

    def _paged_decode_forward(self, params, tokens, cache, block_tables,
                              lengths, block_ids, offsets, cow_src,
                              cow_dst, mean_context=None):
        cfg = self.cfg
        if tokens.ndim != 2 or tokens.shape[1] != 1:
            raise ValueError(f"decode tokens must be (max_seqs, 1), got "
                             f"{tokens.shape}")
        if block_tables is None or lengths is None or block_ids is None \
                or offsets is None:
            raise ValueError("paged decode needs block_tables, lengths, "
                             "append_block_ids and append_offsets")
        lengths = jnp.asarray(lengths, jnp.int32)
        # copy-on-write FIRST: pending shared blocks become private
        # before this step reads or writes them (null pairs no-op, so
        # the program shape never changes — zero-recompile)
        if cow_src is not None:
            cache = cache.cow_copy(jnp.asarray(cow_src, jnp.int32),
                                   jnp.asarray(cow_dst, jnp.int32))
        with jax.named_scope("gpt_embed"):
            h = self.embedding(params["embedding"]["word"], tokens)
            pos = jnp.take(
                params["embedding"]["position"],
                jnp.clip(lengths, 0, cfg.max_position_embeddings - 1),
                axis=0)[:, None]
            x = (h + pos).astype(cfg.compute_dtype)

        xs = (params["layers"], cache.k, cache.v)
        if cache.quantized:
            xs = xs + (cache.k_scale, cache.v_scale)

        def body(x, lp_c):
            lp, kp, vp = lp_c[:3]
            ksc, vsc = (lp_c[3], lp_c[4]) if cache.quantized else (None,
                                                                   None)
            return self._paged_decode_layer(lp, x, (kp, vp, ksc, vsc),
                                            block_tables, lengths,
                                            mean_context)

        x, (k_new, v_new) = scan_stable_vma(body, x, xs,
                                            unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        logits = self.logits(params, x)[:, 0]
        return logits, cache.append(k_new, v_new,
                                    jnp.asarray(block_ids, jnp.int32),
                                    jnp.asarray(offsets, jnp.int32))

    # -- serving: speculative k-token verify --------------------------------

    def _verify_embed(self, params, tokens, lengths):
        """Embed ``tokens (S, Q)`` at positions ``lengths + [0..Q)`` —
        row i of the verify window sits where sequential decode step i
        would have put it."""
        cfg = self.cfg
        Q = tokens.shape[1]
        with jax.named_scope("gpt_embed"):
            h = self.embedding(params["embedding"]["word"], tokens)
            positions = lengths[:, None] + jnp.arange(Q)[None, :]
            pos = jnp.take(
                params["embedding"]["position"],
                jnp.clip(positions, 0, cfg.max_position_embeddings - 1),
                axis=0)                                # (S, Q, hidden)
            return (h + pos).astype(cfg.compute_dtype)

    def _verify_qkv(self, lp, h):
        """(S, Q, 3*hidden) -> rank-4 ``q, k_new, v_new`` (S, H, Q, D)
        plus their cache store+load images for the cross-draft merge."""
        cfg = self.cfg
        from apex_tpu.serving.cache import store_roundtrip
        qkv, _ = self.qkv(lp["qkv"], h)
        S, Q = qkv.shape[:2]
        qkv = qkv.reshape(S, Q, cfg.num_attention_heads,
                          3 * cfg.head_dim).transpose(0, 2, 1, 3)
        return jnp.split(qkv, 3, axis=-1), store_roundtrip

    def _verify_layer(self, lp: dict, x: jnp.ndarray, layer_cache,
                      lengths: jnp.ndarray):
        """One layer of the dense VERIFY step: like :meth:`_decode_layer`
        but ``x`` is ``(S, Q, hidden)`` — the last accepted token plus
        the in-flight drafts — scored against the cached prefix in one
        kernel pass; causality among the Q rows is the exact LSE merge
        inside :func:`decode_attention`, fed the cache-dtype store+load
        images so the numerics match Q sequential steps."""
        cfg = self.cfg
        h = self._ln(lp["ln1"], x)
        with jax.named_scope("gpt_attention"):
            (q, k_new, v_new), roundtrip = self._verify_qkv(lp, h)
            ck, cv, ksc, vsc = layer_cache
            quantized = ksc is not None
            ctx = decode_attention(
                q, ck, cv, lengths, k_new=k_new, v_new=v_new,
                k_scale=ksc, v_scale=vsc, use_pallas=cfg.use_flash,
                k_cast=roundtrip(k_new, ck.dtype, quantized),
                v_cast=roundtrip(v_new, ck.dtype, quantized))
            S, _, Q, _ = ctx.shape
            out, _ = self.proj(lp["proj"],
                               ctx.transpose(0, 2, 1, 3).reshape(S, Q, -1))
        x = x + out
        x = x + self._mlp(lp, self._ln(lp["ln2"], x))
        return x, (k_new, v_new)

    def _paged_verify_layer(self, lp: dict, x: jnp.ndarray, layer_pool,
                            block_tables: jnp.ndarray,
                            lengths: jnp.ndarray,
                            mean_context: Optional[float]):
        """One layer of the PAGED verify step: the bounded block-table
        fetch of :meth:`_paged_decode_layer`, amortized over Q rows."""
        cfg = self.cfg
        h = self._ln(lp["ln1"], x)
        with jax.named_scope("gpt_attention"):
            (q, k_new, v_new), roundtrip = self._verify_qkv(lp, h)
            kp, vp, ksc, vsc = layer_pool
            quantized = ksc is not None
            ctx = paged_decode_attention(
                q, kp, vp, block_tables, lengths, k_new=k_new,
                v_new=v_new, k_scale=ksc, v_scale=vsc,
                mean_context=mean_context, use_pallas=cfg.use_flash,
                k_cast=roundtrip(k_new, kp.dtype, quantized),
                v_cast=roundtrip(v_new, kp.dtype, quantized))
            S, _, Q, _ = ctx.shape
            out, _ = self.proj(lp["proj"],
                               ctx.transpose(0, 2, 1, 3).reshape(S, Q, -1))
        x = x + out
        x = x + self._mlp(lp, self._ln(lp["ln2"], x))
        return x, (k_new, v_new)

    def verify_forward(self, params: dict, tokens: jnp.ndarray, kv_cache,
                       block_tables: Optional[jnp.ndarray] = None,
                       lengths: Optional[jnp.ndarray] = None,
                       cow_src: Optional[jnp.ndarray] = None,
                       cow_dst: Optional[jnp.ndarray] = None,
                       mean_context: Optional[float] = None):
        """Speculative verify: score ``tokens (max_seqs, Q)`` — each
        slot's last accepted token plus its ``Q - 1`` drafts — in ONE
        pass over the cached prefix. Returns ``(logits (S, Q, vocab),
        (k_new, v_new) (L, S, H, Q, D), cache)`` — the cache comes back
        WITHOUT the window appended (for the paged pool it has only the
        COW pairs resolved): the engine decides the accepted counts from
        the logits first and then appends via ``append_k``, all inside
        the same AOT program. Dense caches read ``kv_cache.lengths``;
        the paged pool takes the host table/cursor mirrors like the
        decode leg."""
        self._require_cacheable()
        cfg = self.cfg
        if tokens.ndim != 2:
            raise ValueError(f"verify tokens must be (max_seqs, Q), got "
                             f"{tokens.shape}")
        from apex_tpu.serving.cache import PagedKVCache
        paged = isinstance(kv_cache, PagedKVCache)
        if paged:
            if block_tables is None or lengths is None:
                raise ValueError("paged verify needs block_tables and "
                                 "lengths")
            lengths = jnp.asarray(lengths, jnp.int32)
            # copy-on-write FIRST — same sequencing as the decode leg
            if cow_src is not None:
                kv_cache = kv_cache.cow_copy(
                    jnp.asarray(cow_src, jnp.int32),
                    jnp.asarray(cow_dst, jnp.int32))
        else:
            lengths = kv_cache.lengths
        x = self._verify_embed(params, tokens, lengths)

        xs = (params["layers"], kv_cache.k, kv_cache.v)
        if kv_cache.quantized:
            xs = xs + (kv_cache.k_scale, kv_cache.v_scale)

        def body(x, lp_c):
            lp, ck, cv = lp_c[:3]
            ksc, vsc = (lp_c[3], lp_c[4]) if kv_cache.quantized else \
                (None, None)
            if paged:
                return self._paged_verify_layer(
                    lp, x, (ck, cv, ksc, vsc), block_tables, lengths,
                    mean_context)
            return self._verify_layer(lp, x, (ck, cv, ksc, vsc), lengths)

        x, (k_new, v_new) = scan_stable_vma(body, x, xs,
                                            unroll=cfg.layer_scan_unroll)
        x = self._ln(params["final_ln"], x)
        logits = self.logits(params, x)            # (S, Q, vocab)
        return logits, (k_new, v_new), kv_cache

    def sp_grad_sync(self, grads: dict) -> dict:
        """Megatron-LM allreduces the grads of ``sequence_parallel``-marked
        params (the LayerNorms) in a separate pass
        (``allreduce_sequence_parallel_grad``) because torch autograd hands
        back per-rank partials. Here that reduction lives *inside* the
        fused-LN custom_vjp (``reconcile_cotangent`` psums replicated-param
        cotangents over the axes the activations vary on — the same total
        plain-op AD produces), so grads arrive at the optimizer already
        synced and this is an intentional no-op, retained for API parity
        with the Megatron training-loop call sequence."""
        return grads

    # -- pipeline integration ----------------------------------------------

    def stage_fn(self, num_stages: int):
        """Returns ``(stage_fn, split_params)`` for the pipeline schedules:
        the layer stack is split into ``num_stages`` equal chunks; embedding
        and head stay outside (run them in ``loss_fn`` / before feeding
        microbatches), matching build_model's pre/post_process split
        (``schedules/common.py:29-148``)."""
        if self.cfg.num_layers % num_stages:
            raise ValueError(
                f"num_layers ({self.cfg.num_layers}) must be divisible by "
                f"num_stages ({num_stages})")
        if self.cfg.sequence_parallel and num_stages > 1:
            raise NotImplementedError(
                "sequence_parallel does not compose with a real pipeline "
                "split yet: the inter-stage activations would cross the "
                "pipe axis as sequence shards and the shared LN grads "
                "would skip sp_grad_sync. num_stages == 1 (the hybrid "
                "trainer at pp=1) is supported — embed scatters and the "
                "head gathers, mirroring transform()")
        per = self.cfg.num_layers // num_stages

        def stage(stage_params: dict, x: jnp.ndarray, stage_idx) -> jnp.ndarray:
            layer_fn = self.remat_policy.wrap(self._layer)

            def body(x, lp):
                return layer_fn(lp, x), None

            x, _ = scan_stable_vma(body, x, stage_params,
                                   unroll=self.cfg.layer_scan_unroll)
            return x

        def split_params(params: dict):
            """(num_layers, ...) -> (num_stages, per, ...) stage stacking."""
            return jax.tree_util.tree_map(
                lambda p: p.reshape(num_stages, per, *p.shape[1:]),
                params["layers"])

        return stage, split_params

    def pipeline_fns(self, num_stages: int, targets: jnp.ndarray):
        """Full-model pipeline decomposition — embedding INSIDE the
        pipeline: stage 0 embeds tokens (pre_process), the last stage
        applies final LN + tied logits + LM loss (post_process), layer
        chunks in between (``reference:apex/transformer/pipeline_parallel/
        schedules/common.py:29-148``). The embedding + final-LN params are
        pipe-*shared*; the schedules psum their grads over ``pipe``, which
        realizes the tied-embedding allreduce over the embedding group
        (``reference:apex/transformer/parallel_state.py:215-247``,
        ``get_embedding_ranks`` — here the group is carved by grad masking
        rather than a process-group object).

        ``targets``: ``(M, mb, seq)`` int labels for the per-microbatch loss.

        Returns ``(stage_fn, embed_fn, head_loss_fn, split_params,
        shared_of)`` matching the ``shared_params``/``embed_fn`` arguments of
        the pipelined schedules: feed token microbatches ``(M, mb, seq)``
        directly as ``batch``.
        """
        stage, split_params = self.stage_fn(num_stages)

        def shared_of(params: dict) -> dict:
            return {"embedding": params["embedding"],
                    "final_ln": params["final_ln"]}

        def embed_fn(shared: dict, tokens: jnp.ndarray) -> jnp.ndarray:
            return self.embed({"embedding": shared["embedding"]}, tokens)

        def head_loss_fn(shared: dict, y: jnp.ndarray,
                         m: jnp.ndarray) -> jnp.ndarray:
            x = self._ln(shared["final_ln"], y)
            if self.cfg.sequence_parallel:
                # same placement as transform(): LN on the shard, then the
                # invariant gather so the tied head sees the full sequence
                # (and replicated-param grad accounting matches plain TP)
                from apex_tpu.transformer.context_parallel import (
                    gather_from_sequence_parallel_region)
                x = gather_from_sequence_parallel_region(
                    x, TENSOR_AXIS, seq_axis=1, invariant=True)
            logits = self.logits({"embedding": shared["embedding"]}, x)
            tgt = jax.lax.dynamic_index_in_dim(targets, m, 0, keepdims=False)
            if self.cfg.tensor_model_parallel_size > 1:
                per_tok = vocab_parallel_cross_entropy(logits, tgt)
            else:
                per_tok = softmax_cross_entropy_loss(
                    logits.reshape(-1, logits.shape[-1]), tgt.reshape(-1),
                    padding_idx=None, half_to_float=True
                ).reshape(tgt.shape)
            return jnp.mean(per_tok)

        return stage, embed_fn, head_loss_fn, split_params, shared_of
