"""Model zoo (``reference:apex/transformer/testing/standalone_*.py`` +
the imagenet example model)."""

from apex_tpu.models.bert import BertConfig, BertModel  # noqa: F401
from apex_tpu.models.gpt import GPTConfig, GPTModel  # noqa: F401
from apex_tpu.models.resnet import (  # noqa: F401
    Bottleneck, ResNet50, ResNetConfig)

__all__ = ["GPTConfig", "GPTModel", "BertConfig", "BertModel",
           "ResNetConfig", "ResNet50", "Bottleneck"]
