"""Standalone BERT (``reference:apex/transformer/testing/standalone_bert.py``,
218 LoC): bidirectional encoder sharing the GPT block structure (the
reference builds both from the same ParallelTransformer), plus token-type
embeddings, a pooler, and the MLM binary head. Padding masks ride the flash
kernel's additive bias instead of the seqlen-capped fused softmax."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.ops.dropout import dropout
from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.utils.vma import scan_stable_vma

__all__ = ["BertConfig", "BertModel"]


@dataclasses.dataclass(frozen=True)
class BertConfig(GPTConfig):
    num_token_types: int = 2
    add_pooler: bool = True
    add_binary_head: bool = True  # NSP/sentence-order head


class BertModel(GPTModel):
    def __init__(self, config: BertConfig):
        super().__init__(config)

    def init(self, key: jax.Array) -> dict:
        cfg = self.cfg
        k0, k1, k2 = jax.random.split(key, 3)
        params = super().init(k0)
        params["embedding"]["tokentype"] = (
            0.02 * jax.random.normal(
                k1, (cfg.num_token_types, cfg.hidden_size))
        ).astype(cfg.params_dtype)
        if cfg.add_pooler:
            params["pooler"] = {
                "weight": (0.02 * jax.random.normal(
                    k2, (cfg.hidden_size, cfg.hidden_size))
                ).astype(cfg.params_dtype),
                "bias": jnp.zeros(cfg.hidden_size, cfg.params_dtype)}
        # MLM head (standalone_bert.py BertLMHead:35-74): dense + LN +
        # tied-embedding logits with a trainable output bias. The output
        # bias is stored vocab-sharded (tp, V/tp) like the tied embedding,
        # so one P('tensor') spec covers it under TP.
        k3, k4 = jax.random.split(jax.random.fold_in(key, 17), 2)
        tp = cfg.tensor_model_parallel_size
        params["lm_head"] = {
            "dense": {
                "weight": (0.02 * jax.random.normal(
                    k3, (cfg.hidden_size, cfg.hidden_size))
                ).astype(cfg.params_dtype),
                "bias": jnp.zeros(cfg.hidden_size, cfg.params_dtype)},
            "ln": {"weight": jnp.ones(cfg.hidden_size, cfg.params_dtype),
                   "bias": jnp.zeros(cfg.hidden_size, cfg.params_dtype)},
            "bias": jnp.zeros((tp, cfg.vocab_size // tp),
                              cfg.params_dtype),
        }
        # the binary head reads the pooled [CLS], so it requires the pooler
        if cfg.add_binary_head and cfg.add_pooler:
            params["binary_head"] = {
                "weight": (0.02 * jax.random.normal(
                    k4, (2, cfg.hidden_size))).astype(cfg.params_dtype),
                "bias": jnp.zeros(2, cfg.params_dtype)}
        return params

    def _attention(self, lp, x, bias=None, attn_seed=None):
        cfg = self.cfg
        b, s, _ = x.shape
        local_heads = cfg.num_attention_heads // cfg.tensor_model_parallel_size
        qkv, _ = self.qkv(lp["qkv"], x)
        qkv = self._tag(qkv, "qkv_out")
        qkv = qkv.reshape(b, s, local_heads, 3 * cfg.head_dim)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (jnp.transpose(t, (0, 2, 1, 3)) for t in (q, k, v))
        rate = cfg.attention_dropout if attn_seed is not None else 0.0
        ctx = flash_attention(q, k, v, bias=bias, causal=False,
                              use_pallas=cfg.use_flash,
                              dropout_rate=rate, dropout_seed=attn_seed,
                              checkpoint_names=self.remat_policy.uses_names)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(b, s, -1)
        out, _ = self.proj(lp["proj"], ctx)
        return self._tag(out, "attn_proj_out")

    def _layer(self, lp, x, bias=None, lrng=None):
        cfg = self.cfg
        attn_seed = lrng["attn_seed"] if lrng is not None else None
        a = self._attention(lp, self._ln(lp["ln1"], x), bias, attn_seed)
        if lrng is not None:
            a = dropout(a, cfg.hidden_dropout, lrng["h1"])
        x = x + a
        m = self._mlp(lp, self._ln(lp["ln2"], x))
        if lrng is not None:
            m = dropout(m, cfg.hidden_dropout, lrng["h2"])
        return x + m

    def encode(self, params: dict, tokens: jnp.ndarray,
               token_types: Optional[jnp.ndarray] = None,
               attention_mask: Optional[jnp.ndarray] = None,
               dropout_rng: Optional[jax.Array] = None) -> jnp.ndarray:
        """``attention_mask``: (b, s) with 1 = attend, 0 = pad."""
        cfg = self.cfg
        # embedding dropout is applied to the FULL word+pos+tokentype sum
        # (Megatron Embedding semantics), so embed() runs without dropout
        h = self.embed(params, tokens)
        if token_types is not None:
            h = h + jnp.take(params["embedding"]["tokentype"], token_types,
                             axis=0).astype(h.dtype)
        if dropout_rng is not None:
            h = dropout(h, cfg.hidden_dropout,
                        jax.random.fold_in(dropout_rng, 3))
        bias = None
        if attention_mask is not None:
            bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0,
                             -10000.0).astype(jnp.float32)

        layer_fn = self.remat_policy.wrap(self._layer)
        use_dropout = dropout_rng is not None and (
            cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0)

        if use_dropout:
            xs = (params["layers"], self._layer_rngs(dropout_rng))

            def body(x, lp_rng):
                lp, lrng = lp_rng
                return layer_fn(lp, x, bias, lrng), None
        else:
            xs = params["layers"]

            def body(x, lp):
                return layer_fn(lp, x, bias), None

        h, _ = scan_stable_vma(body, h, xs)
        return self._ln(params["final_ln"], h)

    def pool(self, params: dict, h: jnp.ndarray) -> jnp.ndarray:
        """tanh-dense over the [CLS] position (standalone_bert pooler)."""
        cls = h[:, 0]
        w = params["pooler"]["weight"].astype(cls.dtype)
        b = params["pooler"]["bias"].astype(cls.dtype)
        return jnp.tanh(cls @ w.T + b)

    def lm_logits(self, params: dict, h: jnp.ndarray) -> jnp.ndarray:
        """MLM head (``standalone_bert.py`` ``BertLMHead:35-74``):
        gelu(dense) -> LN -> tied-embedding logits + output bias."""
        from apex_tpu.transformer.tensor_parallel.layers import _local_shard

        p = params["lm_head"]
        w = p["dense"]["weight"].astype(h.dtype)
        t = jax.nn.gelu(h @ w.T + p["dense"]["bias"].astype(h.dtype),
                        approximate=True)
        t = self._ln(p["ln"], t)
        logits = self.logits(params, t)  # vocab-parallel shard when tp>1
        bias = _local_shard(p["bias"], self.cfg.tensor_model_parallel_size)
        return logits + bias.astype(logits.dtype)

    def __call__(self, params, tokens, token_types=None, attention_mask=None,
                 dropout_rng=None):
        h = self.encode(params, tokens, token_types, attention_mask,
                        dropout_rng)
        return self.lm_logits(params, h)

    def loss(self, params, tokens, lm_labels, loss_mask=None,
             token_types=None, attention_mask=None, binary_labels=None,
             dropout_rng=None):
        """Pretraining loss (``standalone_bert.py``
        ``post_language_model_processing:76-99``): masked-LM CE over the
        ``loss_mask`` positions plus, when ``binary_labels`` is given and
        the model has a binary head, the sentence-order CE on the pooled
        [CLS]."""
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
        from apex_tpu.transformer.tensor_parallel.cross_entropy import (
            vocab_parallel_cross_entropy)

        h = self.encode(params, tokens, token_types, attention_mask,
                        dropout_rng)
        logits = self.lm_logits(params, h)
        if self.cfg.tensor_model_parallel_size > 1:
            per_tok = vocab_parallel_cross_entropy(logits, lm_labels)
        else:
            per_tok = softmax_cross_entropy_loss(
                logits.reshape(-1, logits.shape[-1]), lm_labels.reshape(-1),
                padding_idx=None, half_to_float=True
            ).reshape(lm_labels.shape)
        if loss_mask is not None:
            lm_loss = jnp.sum(per_tok * loss_mask) / jnp.maximum(
                jnp.sum(loss_mask), 1.0)
        else:
            lm_loss = jnp.mean(per_tok)
        if binary_labels is None or "binary_head" not in params:
            return lm_loss
        pooled = self.pool(params, h)
        bh = params["binary_head"]
        blogits = (pooled @ bh["weight"].astype(pooled.dtype).T
                   + bh["bias"].astype(pooled.dtype)).astype(jnp.float32)
        bloss = jnp.mean(softmax_cross_entropy_loss(
            blogits, binary_labels, padding_idx=None, half_to_float=True))
        return lm_loss + bloss
