"""In-graph metric accumulators: device scalars that ride through ``jit``.

Host-side ``Metric.observe()`` cannot run inside a traced step — and a
per-metric ``device_get`` would stall the XLA pipeline exactly the way the
reference's per-iteration overflow ``.item()`` sync does
(``reference:apex/amp/scaler.py:199-200``). The in-graph variant keeps the
whole protocol on device:

- instrumented code (amp scaler, DDP allreduce, pipeline schedules,
  optimizers) calls :func:`record(name, value, reduce=...)` with traced
  scalars;
- a reaping wrapper (:func:`reap` / :func:`collecting`) collects everything
  recorded during the trace into a :class:`Metrics` pytree that the step
  function returns as an extra output — a handful of device scalars, no
  host round-trip inside the step;
- :func:`aggregate` reduces each entry across mesh axes with
  ``psum``/``pmean``/``pmax`` according to its declared reduction, so
  per-rank values become mesh totals *inside* ``shard_map`` and cross the
  boundary replicated (``out_specs=P()``);
- the :class:`~apex_tpu.observability.report.StepReporter` fetches the
  final pytree once per report.

**Zero-cost default.** :func:`record` checks a module-level collector stack
at *trace time*: with no collector active it returns before touching its
arguments, so instrumented paths add no ops, no collectives, and no extra
outputs to the compiled program (asserted by
``tests/test_observability.py``). Expensive instrumentation values should
be passed as thunks — ``record("optim/grad_norm", lambda: global_norm(g))``
— so the value is only computed when telemetry is on.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = ["Metrics", "record", "recording", "recorded_names", "reap",
           "collecting", "aggregate", "REDUCTIONS"]

REDUCTIONS = ("sum", "mean", "max", "min")


@jax.tree_util.register_pytree_node_class
class Metrics:
    """An ordered ``{name: device scalar}`` mapping plus the static
    per-name reduction modes. Registered as a pytree so it crosses
    ``jit``/``shard_map`` boundaries (a prefix ``P()`` out_spec covers all
    leaves); the modes travel in the static treedef, which also means two
    steps recording the same names hit the same compilation cache entry.
    """

    def __init__(self, values: Optional[Dict[str, Any]] = None,
                 modes: Optional[Dict[str, str]] = None):
        self.values: Dict[str, Any] = dict(values or {})
        self.modes: Dict[str, str] = {k: (modes or {}).get(k, "mean")
                                      for k in self.values}

    def tree_flatten(self):
        keys = tuple(sorted(self.values))
        return [self.values[k] for k in keys], (
            keys, tuple(self.modes[k] for k in keys))

    @classmethod
    def tree_unflatten(cls, aux, children):
        keys, modes = aux
        return cls(dict(zip(keys, children)), dict(zip(keys, modes)))

    def __len__(self):
        return len(self.values)

    def __contains__(self, name):
        return name in self.values

    def __getitem__(self, name):
        return self.values[name]

    def as_dict(self) -> Dict[str, Any]:
        return dict(self.values)

    def as_floats(self) -> Dict[str, float]:
        """One transfer for the whole pytree, then plain floats."""
        host = jax.device_get(self.values)
        return {k: float(v) for k, v in host.items()}

    def __repr__(self):
        return f"Metrics({sorted(self.values)})"


class _Collector:
    def __init__(self):
        self.values: Dict[str, Any] = {}
        self.modes: Dict[str, str] = {}

    def add(self, name: str, value: Any, mode: str) -> None:
        prev_mode = self.modes.get(name)
        if prev_mode is not None and prev_mode != mode:
            raise ValueError(
                f"metric {name!r} recorded with reduce={mode!r} but was "
                f"previously recorded with reduce={prev_mode!r}")
        value = jnp.asarray(value, jnp.float32)
        if value.ndim:
            raise ValueError(
                f"in-graph metrics must be scalars; {name!r} got shape "
                f"{value.shape}")
        if name in self.values and mode == "sum":
            value = self.values[name] + value
        # non-sum re-records overwrite: last observation wins
        self.values[name] = value
        self.modes[name] = mode

    def freeze(self) -> Metrics:
        return Metrics(self.values, self.modes)


class _State(threading.local):
    def __init__(self):
        self.stack = []


_STATE = _State()


def recording() -> bool:
    """True when a collector is open — i.e. instrumentation is live for
    the code currently being traced/executed. Guard *computations* done
    only for telemetry with this (or pass a thunk to :func:`record`)."""
    return bool(_STATE.stack)


def recorded_names() -> Tuple[str, ...]:
    """The names recorded so far into the innermost open collector
    (empty when none is open). Lets instrumentation that derives metric
    names (the health watchdog's per-tree families) detect collisions
    within one step instead of silently overwriting."""
    if not _STATE.stack:
        return ()
    return tuple(_STATE.stack[-1].values)


def record(name: str, value: Union[Any, Callable[[], Any]],
           reduce: str = "mean") -> None:
    """Record a named scalar into the innermost open collector.

    No-op (before evaluating ``value``, which may be a thunk) when no
    collector is open. ``reduce`` declares how :func:`aggregate` combines
    per-rank values across the mesh: ``"sum"`` for additive quantities
    (bytes, skip counts), ``"mean"`` for replicated or averaged gauges,
    ``"max"``/``"min"`` for extrema. Re-recording a name in one step sums
    for ``"sum"`` mode and overwrites otherwise.
    """
    if not _STATE.stack:
        return
    if reduce not in REDUCTIONS:
        raise ValueError(f"unknown reduction {reduce!r}; "
                         f"expected one of {REDUCTIONS}")
    if callable(value):
        value = value()
    _STATE.stack[-1].add(name, value, reduce)


@contextlib.contextmanager
def collecting():
    """Open a collector around a region of traced code; yields the
    collector whose ``freeze()`` returns the :class:`Metrics` pytree.
    The collector MUST be frozen at the same trace level it was filled
    (inside the same ``shard_map``/``jit`` body), or the recorded tracers
    leak."""
    col = _Collector()
    _STATE.stack.append(col)
    try:
        yield col
    finally:
        popped = _STATE.stack.pop()
        assert popped is col


def reap(fn: Callable) -> Callable:
    """Wrap ``fn`` so it returns ``(out, Metrics)`` with everything
    recorded during its evaluation. Wrap at the trace level where the
    records happen — for shard_mapped steps, the *inner* function."""

    def wrapped(*args, **kwargs):
        with collecting() as col:
            out = fn(*args, **kwargs)
            metrics = col.freeze()
        return out, metrics

    return wrapped


def _cast_varying(x, axes: Tuple[str, ...]):
    # On VMA jax a replicated-typed value cannot feed psum directly; mark
    # it varying first (value identity; no-op on pre-VMA jax). Imported
    # lazily: utils.vma pulls in the whole utils package.
    from apex_tpu.utils.vma import cast_to_vma
    return cast_to_vma(x, frozenset(axes))


def aggregate(metrics: Metrics,
              axis_names: Union[None, str, Sequence[str]]) -> Metrics:
    """Reduce every entry across the given bound mesh axes according to its
    declared reduction. Call inside ``shard_map`` (axes bound); the result
    is replicated, so it can cross a ``P()`` out_spec. With ``None``/empty
    axes this is the identity (single-process, no mesh)."""
    if not axis_names:
        return metrics
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    axes = tuple(axis_names)
    reducers = {"sum": jax.lax.psum, "mean": jax.lax.pmean,
                "max": jax.lax.pmax, "min": jax.lax.pmin}
    out = {}
    for name, value in metrics.values.items():
        out[name] = reducers[metrics.modes[name]](
            _cast_varying(value, axes), axes)
    return Metrics(out, dict(metrics.modes))
