"""Numerics health watchdog: per-leaf NaN/overflow attribution, replica
agreement, and structured crash dumps.

Apex exists for mixed precision, and mixed precision fails in exactly one
way that a loss curve cannot explain: some tensor, on some replica, left
the representable range first, and everything downstream is noise. The amp
scaler's single all-finite bool (:func:`apex_tpu.amp.scaler.all_finite`)
says *that* a step overflowed; this module says *which leaf*, *how badly*,
and *whether the replicas still agree* — the first-failure attribution
large-scale training reports (MegaScale, arXiv:2402.15627) identify as the
main saver of wasted accelerator-hours.

Four pieces, all riding the existing telemetry spine:

- :func:`tensor_stats` — ONE fused in-graph pass over a pytree computing
  per-leaf finite-count, abs-max, squared-norm, and half-precision
  underflow count, returned as a :class:`TreeStats` pytree (stacked
  ``(num_leaves,)`` device vectors + static leaf paths);
- :func:`observe_tree` — the gated recorder: folds a tree's stats into the
  step's in-graph metrics as ``health/<tree>/*`` scalars, including
  ``health/<tree>/first_nonfinite_leaf`` — an argmax over per-leaf
  nonfinite flags whose device value :func:`decode_attribution` maps back
  to the parameter/grad *path name* host-side (the paths are trace-time
  statics, kept in a module side table);
- :func:`check_replica_agreement` / :func:`observe_replica_agreement` — a
  pmean-based divergence detector (max over leaves of elementwise
  ``|x - mean_over_replicas(x)|``) for DDP/TP state, catching silent
  replica corruption that an allreduce would average away;
- :class:`HealthConfig` + :class:`HealthMonitor` — the policy object
  threaded through :class:`~apex_tpu.training.GPTHybridTrainer`, the
  optimizer base and DDP, and the host-side
  :class:`~apex_tpu.observability.report.StepReporter` hook that reacts to
  a non-finite step (``raise`` / ``dump`` a :class:`CrashDump` / ``skip``).

**Zero-cost default.** Instrumented call sites (``amp.scaler.all_finite``,
``OptimizerBase.step``, ``allreduce_grads``, the hybrid trainer) call the
``observe_*`` wrappers, which check two *trace-time* gates before touching
their arguments: an active policy at a sufficient level
(:func:`activate` / :func:`active_level`) AND an open ingraph collector
(:func:`~apex_tpu.observability.ingraph.recording`). With either gate shut
they return immediately, so ``level="off"`` adds no ops, no collectives,
and no outputs to the traced program — asserted on the jaxpr by
``tests/test_health.py``, the same contract ``ingraph.record`` keeps.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import platform
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.observability import ingraph

__all__ = [
    "LEVELS", "ON_NONFINITE", "HealthConfig", "HealthMonitor",
    "TreeStats", "tensor_stats", "observe_tree",
    "check_replica_agreement", "observe_replica_agreement",
    "decode_attribution", "leaf_paths", "payload_nonfinite",
    "CrashDump", "NonFiniteError",
    "activate", "active", "active_level",
]

LEVELS = ("off", "cheap", "full")
ON_NONFINITE = ("raise", "dump", "skip")


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Numerics-watchdog policy.

    ``level`` selects the in-graph instrumentation tier: ``"off"`` is
    provably free (jaxpr-identical step), ``"cheap"`` adds the fused
    per-tree stats + first-nonfinite attribution on the amp grad check
    (one extra pass over the grads), ``"full"`` additionally watches the
    post-update params and runs the replica-agreement pmeans (one
    collective per leaf — a debugging tier, not an always-on one).

    ``on_nonfinite`` is the *host-side* reaction when a reported step
    carried non-finite values: ``"skip"`` trusts the in-graph select that
    already dropped the update (the amp default), ``"dump"`` additionally
    writes a :class:`CrashDump` to ``dump_dir``, ``"raise"`` writes the
    dump and raises :class:`NonFiniteError` so the loop stops. Enforced
    by the :class:`HealthMonitor` reporter hook.

    ``consecutive`` distinguishes routine loss-scale calibration from
    real divergence: dynamic loss scaling *deliberately* overflows every
    ``growth_interval`` steps (scale doubles until the scaled grads leave
    fp range, then backs off — benign, self-correcting, and recurring for
    the whole run), so with fp16 + ``DynamicLossScale`` a policy firing
    on every non-finite report would raise on the first calibration step
    or dump forever. The monitor only fires after ``consecutive``
    non-finite *reports* in a row (a clean report resets the streak); a
    backoff clears a calibration overflow by the next step, while true
    divergence stays non-finite. The default of 1 fires immediately —
    right for bf16 (no scaler-driven overflow) and for the pure watchdog
    metrics; fp16 dynamic-scale runs should set 2 or more.
    """

    level: str = "off"
    on_nonfinite: str = "skip"
    dump_dir: Union[str, os.PathLike] = "."
    consecutive: int = 1

    def __post_init__(self):
        if self.level not in LEVELS:
            raise ValueError(f"level must be one of {LEVELS}, "
                             f"got {self.level!r}")
        if self.on_nonfinite not in ON_NONFINITE:
            raise ValueError(f"on_nonfinite must be one of {ON_NONFINITE}, "
                             f"got {self.on_nonfinite!r}")
        if self.consecutive < 1:
            raise ValueError("consecutive must be >= 1, "
                             f"got {self.consecutive!r}")

    @property
    def enabled(self) -> bool:
        return self.level != "off"

    def reporter_hook(self) -> "HealthMonitor":
        """The ``StepReporter(hooks=[...])`` callable enforcing
        ``on_nonfinite`` on every reported payload."""
        return HealthMonitor(self)


class _State(threading.local):
    def __init__(self):
        self.stack: List[HealthConfig] = []


_STATE = _State()


@contextlib.contextmanager
def activate(config: Optional[HealthConfig]):
    """Make ``config`` the active policy for code traced inside the
    context (``None`` or ``level="off"`` activates nothing — the gates
    stay shut and instrumentation stays absent from the program)."""
    if config is None or not config.enabled:
        yield
        return
    _STATE.stack.append(config)
    try:
        yield
    finally:
        popped = _STATE.stack.pop()
        assert popped is config


def active() -> Optional[HealthConfig]:
    """The innermost active policy, or None."""
    return _STATE.stack[-1] if _STATE.stack else None


def active_level() -> str:
    cfg = active()
    return cfg.level if cfg is not None else "off"


def _live(min_level: str) -> bool:
    """Both trace-time gates: a policy at >= ``min_level`` is active AND
    an ingraph collector is open to carry the scalars out of the step."""
    cfg = active()
    if cfg is None or LEVELS.index(cfg.level) < LEVELS.index(min_level):
        return False
    return ingraph.recording()


# ---------------------------------------------------------------------------
# the fused per-leaf stats pass
# ---------------------------------------------------------------------------

def _float_leaves_with_paths(tree: Any):
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for kp, x in leaves:
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            out.append((jax.tree_util.keystr(kp), x))
    return out


@jax.tree_util.register_pytree_node_class
class TreeStats:
    """Per-leaf numerics summary of one pytree: four stacked
    ``(num_leaves,)`` fp32 device vectors plus the static leaf paths and
    element counts (which travel in the treedef, so reusing the same tree
    structure hits the same compilation cache entry).

    ``finite_count[i]`` counts finite elements of leaf ``i`` — in int32,
    NOT fp32: an fp32 count is exact only to 2^24, so a single NaN in a
    larger leaf (any realistic embedding table) would round away and the
    watchdog would miss exactly the leaves most likely to overflow;
    ``abs_max[i]`` is its max |x| (NaN-propagating — a NaN leaf reads as
    NaN, which is itself the signal); ``sq_sum[i]`` the fp32 sum of
    squares (``l2`` takes the sqrt of the total); ``underflow_count[i]``
    counts (int32) nonzero half-precision elements below the dtype's
    smallest normal (fp16 ``tiny`` = 6.1e-5, bf16 shares fp32's
    1.18e-38) — the gradient-underflow fraction dynamic loss scaling
    exists to fight. Per-leaf exactness holds to 2^31 elements per leaf;
    the *aggregated* counts are f32 metrics, approximate above 2^24 but
    still exactly zero/nonzero (sums of non-negative per-leaf values).
    """

    def __init__(self, paths: Tuple[str, ...], sizes: Tuple[int, ...],
                 half_mask: Tuple[bool, ...],
                 finite_count, abs_max, sq_sum, underflow_count):
        self.paths = tuple(paths)
        self.sizes = tuple(int(s) for s in sizes)
        self.half_mask = tuple(bool(h) for h in half_mask)
        self.finite_count = finite_count
        self.abs_max = abs_max
        self.sq_sum = sq_sum
        self.underflow_count = underflow_count

    def tree_flatten(self):
        return ((self.finite_count, self.abs_max, self.sq_sum,
                 self.underflow_count),
                (self.paths, self.sizes, self.half_mask))

    @classmethod
    def tree_unflatten(cls, aux, children):
        paths, sizes, half_mask = aux
        return cls(paths, sizes, half_mask, *children)

    # -- aggregate views ---------------------------------------------------
    @property
    def num_leaves(self) -> int:
        return len(self.paths)

    @property
    def total_size(self) -> int:
        return sum(self.sizes)

    def _nonfinite_per_leaf(self):
        """Per-leaf non-finite counts, exact in int32 (sizes - finite)."""
        sizes = jnp.asarray(self.sizes, jnp.int32)
        return sizes - self.finite_count

    def nonfinite_count(self):
        """Total non-finite elements across every leaf (f32 scalar; the
        per-leaf counts are exact, so this is exactly 0 iff clean)."""
        return jnp.sum(self._nonfinite_per_leaf().astype(jnp.float32))

    def nonfinite_flags(self):
        """Per-leaf bool: leaf ``i`` holds at least one non-finite."""
        return self._nonfinite_per_leaf() > 0

    def first_nonfinite_index(self):
        """Index of the first leaf (flatten order) carrying a non-finite
        element, -1 when every leaf is clean — the device scalar
        :func:`decode_attribution` maps back to ``paths``."""
        flags = self.nonfinite_flags()
        first = jnp.argmax(flags).astype(jnp.float32)
        return jnp.where(jnp.any(flags), first, jnp.float32(-1.0))

    def abs_max_total(self):
        return jnp.max(self.abs_max)

    def l2(self):
        return jnp.sqrt(jnp.sum(self.sq_sum))

    def underflow_fraction(self):
        """Underflowed share of the tree's *half-precision* elements
        (0 when the tree holds none)."""
        half = sum(s for s, h in zip(self.sizes, self.half_mask) if h)
        if half == 0:
            return jnp.float32(0.0)
        return (jnp.sum(self.underflow_count.astype(jnp.float32))
                / jnp.float32(half))

    def __repr__(self):
        return (f"TreeStats({self.num_leaves} leaves, "
                f"{self.total_size} elements)")


def tensor_stats(tree: Any) -> Optional[TreeStats]:
    """One fused pass over every floating leaf of ``tree``.

    Each leaf contributes four reductions (finite count, abs-max, squared
    sum, underflow count) that XLA fuses into the producing ops — the same
    no-extra-memory-pass property :func:`~apex_tpu.amp.scaler.all_finite`
    relies on. Returns None for a tree with no floating leaves.
    """
    pairs = _float_leaves_with_paths(tree)
    if not pairs:
        return None
    paths, sizes = [], []
    finite, amax, sq, under, half_mask = [], [], [], [], []
    for path, x in pairs:
        x = jnp.asarray(x)
        paths.append(path)
        sizes.append(int(x.size))
        x32 = x.astype(jnp.float32)
        isf = jnp.isfinite(x)
        # int32 counts: exact per leaf to 2^31 elements (an fp32 count is
        # exact only to 2^24 — one NaN in an embedding-table-sized leaf
        # would round away and never be detected)
        finite.append(jnp.sum(isf, dtype=jnp.int32))
        amax.append(jnp.max(jnp.abs(x32)) if x.size else jnp.float32(0.0))
        sq.append(jnp.sum(jnp.where(isf, x32, 0.0) ** 2))
        is_half = x.dtype in (jnp.float16, jnp.bfloat16)
        half_mask.append(is_half)
        if is_half and x.size:
            tiny = jnp.float32(jnp.finfo(x.dtype).tiny)
            under.append(jnp.sum(
                (x32 != 0.0) & (jnp.abs(x32) < tiny), dtype=jnp.int32))
        else:
            under.append(jnp.int32(0))
    return TreeStats(tuple(paths), tuple(sizes), tuple(half_mask),
                     jnp.stack(finite), jnp.stack(amax), jnp.stack(sq),
                     jnp.stack(under))


# ---------------------------------------------------------------------------
# attribution side table (trace-time statics -> host decode)
# ---------------------------------------------------------------------------

# tree name -> leaf paths, written when observe_tree traces. Paths are
# static per (tree structure, name); the last trace wins, which is correct
# for the steady-state training loop (one step program per name).
_LEAF_PATHS: Dict[str, Tuple[str, ...]] = {}


def leaf_paths(name: str) -> Optional[Tuple[str, ...]]:
    """The leaf-path table recorded for tree ``name`` (None if that tree
    was never observed in this process)."""
    return _LEAF_PATHS.get(name)


_FIRST_LEAF_SUFFIX = "/first_nonfinite_leaf"


def decode_attribution(payload: Dict[str, float]) -> Dict[str, str]:
    """Map every ``health/<tree>/first_nonfinite_leaf`` index in a fetched
    payload back to the offending leaf's path name.

    Returns ``{tree name: leaf path}`` for trees that flagged (index >= 0);
    clean trees and unknown names are omitted.
    """
    out: Dict[str, str] = {}
    for key, value in payload.items():
        if not (key.startswith("health/")
                and key.endswith(_FIRST_LEAF_SUFFIX)):
            continue
        name = key[len("health/"):-len(_FIRST_LEAF_SUFFIX)]
        paths = _LEAF_PATHS.get(name)
        idx = int(value)
        if paths is not None and 0 <= idx < len(paths):
            out[name] = paths[idx]
    return out


# ---------------------------------------------------------------------------
# gated recorders (the library's instrumentation points call these)
# ---------------------------------------------------------------------------

def observe_tree(tree: Any, name: str,
                 min_level: str = "cheap") -> Optional[TreeStats]:
    """Record ``health/<name>/*`` for ``tree`` into the step's in-graph
    metrics — no-op (before touching ``tree``) unless a policy at
    ``min_level`` or above is active AND a collector is open.

    Recorded scalars (see docs/OBSERVABILITY.md for the mesh reductions):
    ``nonfinite_count`` (sum of PER-RANK counts — exact for rank-sharded
    trees, ×replication-factor for replicated observations like
    post-allreduce DDP grads; exactly 0 iff every rank is clean, which
    is the alerting contract), ``abs_max`` (max), ``l2`` (mean — the
    local tree norm, pmean'd; for DDP-synced grads the replicas agree so
    this is the global norm), ``underflow_frac`` (mean), and
    ``first_nonfinite_leaf`` (max; -1 = clean, any flagged replica wins).

    Observing the same ``name`` twice in one step (e.g. a GAN step
    running two ``all_finite`` grad checks, both defaulting to "grads")
    records the second tree under ``<name>#2``, ``#3``, ... — a last-wins
    overwrite would sum the counts but drop the first tree's attribution,
    silently mis-answering "which leaf". Prefer passing distinct names at
    the call sites; the suffix keeps every check attributable regardless.
    """
    if not _live(min_level):
        return None
    stats = tensor_stats(tree)
    if stats is None:
        return None
    taken = set(ingraph.recorded_names())
    candidate, n = name, 1
    while f"health/{candidate}/first_nonfinite_leaf" in taken:
        n += 1
        candidate = f"{name}#{n}"
    name = candidate
    _LEAF_PATHS[name] = stats.paths
    ingraph.record(f"health/{name}/nonfinite_count",
                   stats.nonfinite_count(), reduce="sum")
    ingraph.record(f"health/{name}/abs_max",
                   stats.abs_max_total(), reduce="max")
    ingraph.record(f"health/{name}/l2", stats.l2(), reduce="mean")
    ingraph.record(f"health/{name}/underflow_frac",
                   stats.underflow_fraction(), reduce="mean")
    ingraph.record(f"health/{name}/first_nonfinite_leaf",
                   stats.first_nonfinite_index(), reduce="max")
    return stats


def check_replica_agreement(tree: Any,
                            axis_names: Union[str, Sequence[str]],
                            name: str = "params"):
    """Divergence of ``tree`` across the replicas of ``axis_names``:
    max over leaves of elementwise ``|x - mean_over_replicas(x)|``.

    Values that are replicated *by construction* (DDP params, synced
    grads, TP-replicated layernorms) read ~0; anything larger is silent
    replica corruption — a bad collective, a bitflip, a non-deterministic
    op — that an allreduce would quietly average into every replica.
    "~0", not exactly 0: the pmean's reduction order can differ from the
    identity by an ulp, so compiled collectives report O(1e-8·|x|)
    residue on healthy replicated state — alert on a threshold (e.g.
    1e-6 × ``health/<name>/abs_max``), not on nonzero. One pmean per
    leaf, so this is ``level="full"`` instrumentation (or an explicit
    debugging call). Must run where ``axis_names`` are bound; records
    ``health/<name>/replica_divergence`` (max) when a collector is open
    and always returns the f32 scalar.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    axes = tuple(axis_names)
    from apex_tpu.utils.vma import cast_to_vma
    devs = []
    for _, x in _float_leaves_with_paths(tree):
        x32 = jnp.asarray(x).astype(jnp.float32)
        if not x32.size:  # zero-size leaf: nothing to diverge on
            continue
        mean = jax.lax.pmean(cast_to_vma(x32, frozenset(axes)), axes)
        devs.append(jnp.max(jnp.abs(x32 - mean)))
    d = jnp.max(jnp.stack(devs)) if devs else jnp.float32(0.0)
    ingraph.record(f"health/{name}/replica_divergence", d, reduce="max")
    return d


def observe_replica_agreement(tree: Any,
                              axis_names: Union[str, Sequence[str]],
                              name: str = "params"):
    """Gated :func:`check_replica_agreement`: runs only at
    ``level="full"`` with a collector open (the pmeans are real
    collectives — never free)."""
    if not _live("full"):
        return None
    return check_replica_agreement(tree, axis_names, name)


# ---------------------------------------------------------------------------
# host side: crash dumps + the reporter hook
# ---------------------------------------------------------------------------

def payload_nonfinite(payload: Dict[str, float]) -> bool:
    """True when a fetched step payload shows non-finite values: any
    ``health/*/nonfinite_count`` > 0, or the amp scaler counted an
    overflow this step."""
    for key, value in payload.items():
        if key.startswith("health/") and key.endswith("/nonfinite_count"):
            if value > 0:
                return True
    return payload.get("amp/overflow_count", 0.0) > 0.0


def _versions() -> Dict[str, str]:
    out = {"python": platform.python_version(), "jax": jax.__version__}
    try:
        import jaxlib
        out["jaxlib"] = jaxlib.__version__
    except Exception:
        pass
    try:
        import numpy
        out["numpy"] = numpy.__version__
    except Exception:
        pass
    try:
        import apex_tpu
        out["apex_tpu"] = apex_tpu.__version__
    except Exception:
        pass
    try:
        out["backend"] = jax.default_backend()
    except Exception:
        pass
    return out


@dataclasses.dataclass
class CrashDump:
    """Structured record of a numerics failure: everything the post-mortem
    needs without re-running the job. ``attribution`` maps each flagged
    tree to the leaf path that went non-finite first
    (:func:`decode_attribution`); ``metrics`` is the full step payload
    (in-graph + host registry + timers) the reporter had assembled;
    ``requests`` is the serving flight-recorder window — the last-N
    per-request lifecycle records
    (:meth:`~apex_tpu.observability.reqtrace.RequestRecord.to_dict`) the
    :class:`~apex_tpu.observability.slo.SLOTracker` captures on an SLO
    violation (empty for training-side dumps)."""

    step: int
    metrics: Dict[str, float]
    attribution: Dict[str, str]
    config: Dict[str, Any]
    versions: Dict[str, str]
    wall_time: float
    requests: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)

    @classmethod
    def from_payload(cls, step: int, payload: Dict[str, float],
                     config: Optional[HealthConfig] = None,
                     requests: Sequence[Dict[str, Any]] = ()
                     ) -> "CrashDump":
        cfg_dict = dataclasses.asdict(config) if config is not None else {}
        cfg_dict = {k: (os.fspath(v) if isinstance(v, os.PathLike) else v)
                    for k, v in cfg_dict.items()}
        return cls(step=int(step), metrics=dict(payload),
                   attribution=decode_attribution(payload),
                   config=cfg_dict, versions=_versions(),
                   wall_time=time.time(), requests=list(requests))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def write(self, dump_dir: Union[str, os.PathLike] = ".",
              prefix: str = "health_dump") -> str:
        """Write ``<prefix>_step<N>.json`` into ``dump_dir`` (created
        if missing); returns the path. Non-finite metric values — which
        essentially every real dump carries (``abs_max`` = inf on an
        overflow) — serialize as the STRINGS ``"NaN"``/``"Infinity"``/
        ``"-Infinity"``, not Python's bare ``Infinity`` literals: the
        dump exists for post-mortem tooling, and strict parsers (jq,
        ``JSON.parse``, Go) reject non-standard literals wholesale."""
        from apex_tpu.observability.sinks import json_safe_metrics
        dump_dir = os.fspath(dump_dir)
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(dump_dir,
                            f"{prefix}_step{self.step:08d}.json")
        doc = dict(self.to_dict(), metrics=json_safe_metrics(self.metrics),
                   requests=[json_safe_metrics(r) for r in self.requests])
        with open(path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True, allow_nan=False)
        return path


class NonFiniteError(RuntimeError):
    """A reported step carried non-finite values and the active policy
    said ``on_nonfinite="raise"``. Carries the :class:`CrashDump` (and the
    path it was written to, when it was)."""

    def __init__(self, message: str, dump: CrashDump,
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.dump = dump
        self.dump_path = dump_path


class HealthMonitor:
    """The :class:`~apex_tpu.observability.report.StepReporter` hook
    enforcing a :class:`HealthConfig`'s ``on_nonfinite`` policy.

    Called once per reported payload (after the sinks emitted, so the
    telemetry stream always carries the failing step). Keeps the list of
    written dump paths in ``dumps`` for the caller/tests, and the current
    non-finite streak in ``streak`` (fires at
    ``config.consecutive`` — see :class:`HealthConfig`).
    """

    def __init__(self, config: HealthConfig):
        self.config = config
        self.dumps: List[str] = []
        self.streak = 0

    def __call__(self, step: int, payload: Dict[str, float]) -> None:
        if self.config.on_nonfinite == "skip":
            return  # the in-graph select already dropped the update
        if not payload_nonfinite(payload):
            self.streak = 0
            return
        self.streak += 1
        if self.streak < self.config.consecutive:
            return  # could be a routine loss-scale calibration overflow
        dump = CrashDump.from_payload(step, payload, self.config)
        path = dump.write(self.config.dump_dir)
        self.dumps.append(path)
        if self.config.on_nonfinite == "raise":
            att = ", ".join(f"{k} -> {v}" for k, v in
                            dump.attribution.items()) or "unattributed"
            raise NonFiniteError(
                f"non-finite values at step {step} ({att}); "
                f"crash dump: {path}", dump, dump_path=path)
