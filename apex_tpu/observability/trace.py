"""Wall-clock span capture + Chrome-trace (Perfetto-loadable) conversion.

:class:`~apex_tpu.utils.timers.Timer` records *elapsed totals*; the trace
viewers want *spans*. When span recording is enabled, every ``Timer.stop``
pushes ``(name, t0, t1)`` here via a hook installed into
``apex_tpu.utils.timers`` (a plain module-global check — one ``is None``
test per stop when disabled, and no import cycle: this module imports
nothing from the rest of the library). The
:class:`~apex_tpu.observability.sinks.ChromeTraceSink` drains the buffer
each report and writes the standard ``traceEvents`` JSON, which loads in
``chrome://tracing`` / Perfetto next to a ``jax.profiler.trace`` capture —
host-side step phases and device-side ops in the same timeline workflow.
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, NamedTuple, Optional

__all__ = ["Span", "spans_enabled", "enable_spans", "disable_spans",
           "record_span", "drain_spans", "span_recording",
           "chrome_trace_events"]


class Span(NamedTuple):
    name: str
    start: float  # perf_counter seconds
    end: float


_LOCK = threading.Lock()
_SPANS: List[Span] = []
_ENABLED = False


def spans_enabled() -> bool:
    return _ENABLED


def record_span(name: str, start: float, end: float) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _SPANS.append(Span(name, start, end))


def _install_timer_hook(on: bool) -> None:
    from apex_tpu.utils import timers
    timers.set_span_hook(record_span if on else None)


def enable_spans() -> None:
    global _ENABLED
    _ENABLED = True
    _install_timer_hook(True)


def disable_spans() -> None:
    global _ENABLED
    _ENABLED = False
    _install_timer_hook(False)
    # drop undrained spans: a later session must not inherit them (and
    # mislabel them with its own step numbers)
    with _LOCK:
        _SPANS.clear()


def drain_spans() -> List[Span]:
    with _LOCK:
        out = list(_SPANS)
        _SPANS.clear()
    return out


@contextlib.contextmanager
def span_recording():
    """Enable span capture for a region (e.g. the whole training loop)."""
    was = _ENABLED
    enable_spans()
    try:
        yield
    finally:
        if not was:
            disable_spans()


def chrome_trace_events(spans, pid: int = 0, tid: int = 0,
                        step: Optional[int] = None) -> List[dict]:
    """Convert spans to Chrome-trace complete events (``ph="X"``, micro-
    second timestamps). ``step``, when given, lands in ``args`` so the
    viewer can filter by training step."""
    events = []
    for s in spans:
        ev = {"name": s.name, "ph": "X", "cat": "apex_tpu",
              "ts": s.start * 1e6, "dur": (s.end - s.start) * 1e6,
              "pid": pid, "tid": tid}
        if step is not None:
            ev["args"] = {"step": step}
        events.append(ev)
    return events
