"""Wall-clock span capture + Chrome-trace (Perfetto-loadable) conversion.

:class:`~apex_tpu.utils.timers.Timer` records *elapsed totals*; the trace
viewers want *spans*. When span recording is enabled, every ``Timer.stop``
pushes ``(name, t0, t1)`` here via a hook installed into
``apex_tpu.utils.timers`` (a plain module-global check — one ``is None``
test per stop when disabled, and no import cycle: this module imports
nothing from the rest of the library). The
:class:`~apex_tpu.observability.sinks.ChromeTraceSink` drains the buffer
each report and writes the standard ``traceEvents`` JSON, which loads in
``chrome://tracing`` / Perfetto next to a ``jax.profiler.trace`` capture —
host-side step phases and device-side ops in the same timeline workflow.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterable, List, NamedTuple, Optional

__all__ = ["Span", "spans_enabled", "enable_spans", "disable_spans",
           "record_span", "drain_spans", "span_recording",
           "chrome_trace_events", "epoch_offset", "trace_metadata",
           "merge_chrome_traces"]


class Span(NamedTuple):
    name: str
    start: float  # perf_counter seconds
    end: float


_LOCK = threading.Lock()
_SPANS: List[Span] = []
_ENABLED = False


def spans_enabled() -> bool:
    return _ENABLED


def record_span(name: str, start: float, end: float) -> None:
    if not _ENABLED:
        return
    with _LOCK:
        _SPANS.append(Span(name, start, end))


def _install_timer_hook(on: bool) -> None:
    from apex_tpu.utils import timers
    timers.set_span_hook(record_span if on else None)


def enable_spans() -> None:
    global _ENABLED
    _ENABLED = True
    _install_timer_hook(True)


def disable_spans() -> None:
    global _ENABLED
    _ENABLED = False
    _install_timer_hook(False)
    # drop undrained spans: a later session must not inherit them (and
    # mislabel them with its own step numbers)
    with _LOCK:
        _SPANS.clear()


def drain_spans() -> List[Span]:
    with _LOCK:
        out = list(_SPANS)
        _SPANS.clear()
    return out


@contextlib.contextmanager
def span_recording():
    """Enable span capture for a region (e.g. the whole training loop)."""
    was = _ENABLED
    enable_spans()
    try:
        yield
    finally:
        if not was:
            disable_spans()


def epoch_offset() -> float:
    """``time.time() − time.perf_counter()`` — the translation from this
    process's ``perf_counter`` timebase to the shared unix epoch.

    Every span/tick in the Chrome exports is stamped in ``perf_counter``
    seconds, whose zero point is *process-local* and arbitrary: two
    ranks' traces loaded together would land decades apart (or overlap
    meaninglessly). Stamping this offset into each trace's metadata
    makes the per-rank timebases recoverable after the fact, so
    :func:`merge_chrome_traces` can re-stamp every event onto one shared
    (epoch) timeline for a multi-rank Perfetto view. Sampled at call
    time; the two clocks drift only at NTP-slew rates, far below span
    resolution over a trace's lifetime."""
    return time.time() - time.perf_counter()


def trace_metadata() -> dict:
    """The metadata block both Chrome exporters stamp into their
    documents: the clock the ``ts`` fields are in plus the epoch offset
    that aligns it across processes."""
    return {"clock": "perf_counter", "epoch_offset_s": epoch_offset()}


def merge_chrome_traces(docs: Iterable[dict]) -> dict:
    """Merge per-rank Chrome-trace documents into one aligned view.

    Each input must carry ``metadata.epoch_offset_s`` (both exporters
    stamp it); every event's ``ts`` is shifted by its document's offset,
    so all events land on the shared epoch-microseconds timeline —
    cross-rank ordering becomes meaningful even though each rank stamped
    its own ``perf_counter``. A document missing the offset raises —
    silently merging unaligned timebases is the bug this function
    exists to prevent.

    Pids: both exporters default to ``pid=0``, so two ranks' files
    usually COLLIDE — merged as-is their spans would interleave in one
    indistinguishable lane. When any pid appears in more than one
    document, every ``(document, pid)`` pair is re-stamped to a fresh
    pid (document order, then pid order), keeping each source's
    internal pid structure while separating the sources; collision-free
    inputs keep their pids verbatim. The merged document's metadata
    records ``clock: "epoch"`` with offset 0.
    """
    docs = list(docs)
    for i, doc in enumerate(docs):
        meta = doc.get("metadata") or {}
        if "epoch_offset_s" not in meta:
            raise ValueError(
                f"trace document {i} carries no metadata.epoch_offset_s "
                f"— cannot align its process-local perf_counter timebase")
    doc_pids = [{ev.get("pid", 0) for ev in doc.get("traceEvents", [])}
                for doc in docs]
    seen: set = set()
    collide = False
    for pids in doc_pids:
        if pids & seen:
            collide = True
            break
        seen |= pids
    remap: dict = {}
    if collide:
        for i, pids in enumerate(doc_pids):
            for p in sorted(pids, key=repr):
                remap[(i, p)] = len(remap)
    events: List[dict] = []
    for i, doc in enumerate(docs):
        shift_us = float(doc["metadata"]["epoch_offset_s"]) * 1e6
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            if collide:
                ev["pid"] = remap[(i, ev.get("pid", 0))]
            events.append(ev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"clock": "epoch", "epoch_offset_s": 0.0}}


def chrome_trace_events(spans, pid: int = 0, tid: int = 0,
                        step: Optional[int] = None) -> List[dict]:
    """Convert spans to Chrome-trace complete events (``ph="X"``, micro-
    second timestamps). ``step``, when given, lands in ``args`` so the
    viewer can filter by training step."""
    events = []
    for s in spans:
        ev = {"name": s.name, "ph": "X", "cat": "apex_tpu",
              "ts": s.start * 1e6, "dur": (s.end - s.start) * 1e6,
              "pid": pid, "tid": tid}
        if step is not None:
            ev["args"] = {"step": step}
        events.append(ev)
    return events
