"""Fleet observability: cross-rank metric aggregation, straggler
signals, gang postmortems, and a live ``/metrics`` endpoint.

Everything before this module is strictly per-rank: each process owns a
:class:`~apex_tpu.observability.registry.MetricsRegistry`, a health
payload, and a heartbeat file — and the supervisor that decides restarts
and shrinks (:class:`~apex_tpu.elastic.launch.LocalLauncher`) is blind
to all of it except file mtimes. This module is the merge layer:

- :class:`FleetPublisher` (rank side) — periodically writes an atomic
  JSON snapshot of the local registry (typed: counters, gauges,
  histogram buckets + observed min/max), the last ``health/*`` payload
  it saw, and the completed-step counter into
  ``run_dir/fleet/rank_<i>.json`` (write-to-temp + ``os.replace``, the
  same torn-read discipline as the checkpoint sidecar). Host-side only:
  the worker's AOT/jitted step programs are byte-identical with the
  publisher on or off (asserted in ``tests/test_fleet.py``, the PR 12
  tracing contract).
- :func:`merge_registry_dicts` / :class:`FleetAggregator` (supervisor
  side) — merge every rank snapshot into ONE registry: counters sum,
  gauges carry min/max/mean + per-rank spread (the merged registry
  holds the mean; the raw view keeps the spread), histogram buckets
  add. The aggregator also emits the ``fleet/*`` straggler family
  (``fleet/step_skew`` = max−min completed step, ``fleet/slowest_rank``,
  ``fleet/step_wall_spread_ms`` off the merged per-rank
  ``perf/step_wall_ms`` gauges) so the restart policy and the operator
  see *which* rank is behind, not just that mtimes moved.
- :class:`PostmortemReport` — the multi-host analogue of PR 3's
  :class:`~apex_tpu.observability.health.CrashDump`: on gang teardown,
  harvest each rank's last snapshot, heartbeat age, and log tail into
  one strict-JSON + markdown artifact naming the likely culprit rank
  (dead heartbeat > stalled step > health non-finite, in that order).
- :class:`MetricsServer` — a stdlib ``ThreadingHTTPServer`` serving the
  merged registry via the existing
  :meth:`~apex_tpu.observability.registry.MetricsRegistry
  .render_prometheus` on ``/metrics`` and the raw merged JSON on
  ``/fleet``; no new dependency, no process-exit path (the handler
  raises, never exits — the ``ast-elastic-exits`` discipline extends to
  the supervisor's server thread).

Formats, routes, and the metric table: docs/OBSERVABILITY.md "Fleet
observability"; the teardown walkthrough: docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from apex_tpu.observability.registry import (MetricsRegistry, get_registry,
                                             json_float, json_safe_float)

__all__ = ["FLEET_DIR", "SNAPSHOT_SCHEMA", "FleetPublisher",
           "FleetAggregator", "MetricsServer", "PostmortemReport",
           "RankForensics", "merge_registry_dicts", "snapshot_path"]

FLEET_DIR = "fleet"
SNAPSHOT_SCHEMA = 1

_RANK_FILE = re.compile(r"rank_(\d+)\.json$")


def snapshot_path(run_dir: str, rank: int) -> str:
    return os.path.join(run_dir, FLEET_DIR, f"rank_{int(rank)}.json")


def _json_safe_tree(value: Any) -> Any:
    """Recursive strict-JSON conversion: non-finite floats become their
    string spellings at any nesting depth (health payloads legitimately
    carry inf/NaN — that IS the signal the postmortem keeps)."""
    if isinstance(value, float):
        return json_safe_float(value)
    if isinstance(value, dict):
        return {k: _json_safe_tree(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe_tree(v) for v in value]
    return value


# ---------------------------------------------------------------------------
# rank side: the publisher
# ---------------------------------------------------------------------------

class FleetPublisher:
    """Rank-side snapshot writer. Entirely host-side: it reads the host
    registry and writes a file — it never touches the device, so the
    step programs cannot change with it on.

    Call :meth:`publish` once per completed step (the
    :class:`~apex_tpu.elastic.runner.ElasticRunner` does this when one
    is attached); ``min_interval_s`` throttles the disk writes so a fast
    step loop is not one ``os.replace`` per step. The publisher is also
    a :class:`~apex_tpu.observability.report.StepReporter` hook
    (``hooks=[publisher]``): each payload's ``health/*`` entries are
    stashed and ride the next snapshot, so the supervisor sees the last
    numerics state of every rank without a second channel.

    Each write is atomic (temp file + ``os.replace`` in the same
    directory) — the aggregator can read concurrently and never sees a
    torn snapshot, the checkpoint-sidecar discipline.
    """

    def __init__(self, run_dir: str, rank: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None,
                 min_interval_s: float = 0.0):
        if rank is None:
            from apex_tpu.parallel import multiproc
            rank = multiproc.process_id()
        self.rank = int(rank)
        self.path = snapshot_path(run_dir, self.rank)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self.registry = registry if registry is not None else get_registry()
        self.min_interval_s = float(min_interval_s)
        self._health: Dict[str, float] = {}
        self._last_write: Optional[float] = None   # monotonic
        self._last_step: Optional[Tuple[int, float]] = None  # (step, perf)
        self.publishes = 0

    # -- StepReporter hook --------------------------------------------------
    def __call__(self, step: int, payload: Dict[str, float]) -> None:
        """Reporter-hook seat: keep the payload's numerics-health state
        and publish (throttled). ``amp/overflow_count`` rides along with
        the ``health/*`` keys — it is the overflow signal
        ``health.payload_nonfinite`` checks, and the postmortem's
        :func:`_health_nonfinite` mirrors that contract on the snapshot."""
        health = {k: v for k, v in payload.items()
                  if k.startswith("health/") or k == "amp/overflow_count"}
        if health:
            self._health = health
        self.publish(step)

    # -- the write ----------------------------------------------------------
    def _track_step_wall(self, step: int) -> None:
        """Per-rank wall ms per completed step, as a ``perf/`` gauge so
        the aggregator's gauge merge yields the cross-rank step-wall
        spread — the straggler signal ``fleet/step_wall_spread_ms``."""
        now = time.perf_counter()
        prev, self._last_step = self._last_step, (int(step), now)
        if prev is None:
            return
        d_steps, dt = int(step) - prev[0], now - prev[1]
        if d_steps > 0 and dt > 0.0:
            self.registry.gauge("perf/step_wall_ms").set(
                dt * 1e3 / d_steps)

    def publish(self, step: int, force: bool = False) -> Optional[str]:
        """Write the snapshot for completed step ``step``; returns the
        path, or None when throttled (``min_interval_s`` not elapsed and
        not ``force``)."""
        now = time.monotonic()
        if (not force and self._last_write is not None
                and now - self._last_write < self.min_interval_s):
            return None
        self._track_step_wall(step)
        doc = {
            "schema": SNAPSHOT_SCHEMA,
            "rank": self.rank,
            "step": int(step),
            "wall_time": time.time(),
            "registry": self.registry.to_dict(),
            "health": _json_safe_tree(self._health),
        }
        tmp = f"{self.path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, allow_nan=False)
        os.replace(tmp, self.path)
        self._last_write = now
        self.publishes += 1
        return self.path


# ---------------------------------------------------------------------------
# the merge
# ---------------------------------------------------------------------------

def merge_registry_dicts(docs: Iterable[dict],
                         stat_sources: Optional[List[bool]] = None
                         ) -> Tuple[MetricsRegistry, dict]:
    """Merge typed registry dicts (:meth:`MetricsRegistry.to_dict`) into
    ``(merged_registry, stats)``.

    Merge rules, per metric kind:

    - **counters** sum (each rank counted its own events);
    - **gauges** land in the merged registry as the cross-source MEAN,
      with ``stats["gauges"][name]`` carrying ``min``/``max``/``mean``/
      ``spread`` (max−min) + the per-source values — the spread is the
      straggler signal a mean would hide;
    - **histograms** add bucket-by-bucket when the bucket bounds match
      (observed min/max combine, sums/counts add), so a percentile of
      the merged histogram estimates the percentile of the POOLED
      samples (bucket-resolution bound unchanged). A source whose bounds
      disagree is skipped for that name and listed in
      ``stats["skipped_histograms"]`` — a half-merged histogram would
      lie, a loud skip does not.

    ``stat_sources`` (one bool per doc, default all-True) restricts
    which sources feed ``stats`` — the merged REGISTRY always folds in
    everything. The aggregator's scrape path uses it to merge the
    supervisor's own registry alongside the rank snapshots in ONE pass
    while keeping the per-rank spread stats rank-only.
    """
    merged = MetricsRegistry()
    gauge_all: Dict[str, List[float]] = {}
    gauge_vals: Dict[str, List[float]] = {}
    counter_vals: Dict[str, List[float]] = {}
    skipped: List[str] = []
    for i, doc in enumerate(docs):
        in_stats = stat_sources[i] if stat_sources is not None else True
        for name, value in doc.get("counters", {}).items():
            if in_stats:
                counter_vals.setdefault(name, []).append(
                    json_float(value))
            merged.counter(name).inc(json_float(value))
        for name, value in doc.get("gauges", {}).items():
            gauge_all.setdefault(name, []).append(json_float(value))
            if in_stats:
                gauge_vals.setdefault(name, []).append(json_float(value))
        for name, h in doc.get("histograms", {}).items():
            bounds = [float(b) for b in h["bounds"]]
            hist = merged.histogram(name, bounds)
            if list(hist.bounds) != sorted(bounds):
                skipped.append(f"{name}[source {i}]")
                continue
            if len(h["counts"]) != len(hist._counts):
                skipped.append(f"{name}[source {i}]")
                continue
            for j, c in enumerate(h["counts"]):
                hist._counts[j] += int(c)
            hist._sum += json_float(h["sum"])
            hist._count += int(h["count"])
            hist._min = min(hist._min, json_float(h["min"]))
            hist._max = max(hist._max, json_float(h["max"]))
    for name, vals in gauge_all.items():
        # the merged registry's gauge = mean over EVERY source
        merged.gauge(name).set(math.fsum(vals) / len(vals))
    gauge_stats: Dict[str, dict] = {}
    for name, vals in gauge_vals.items():
        # NaN-tolerant reductions: a NaN gauge (a health signal) must
        # surface as NaN in the mean, not crash min/max
        finite = [v for v in vals if not math.isnan(v)]
        lo = min(finite) if finite else math.nan
        hi = max(finite) if finite else math.nan
        mean = (math.fsum(vals) / len(vals)) if vals else math.nan
        gauge_stats[name] = {"min": lo, "max": hi, "mean": mean,
                             "spread": hi - lo, "values": list(vals)}
    stats = {"gauges": gauge_stats,
             "counters": {n: {"total": math.fsum(v), "values": list(v)}
                          for n, v in counter_vals.items()},
             "skipped_histograms": skipped}
    return merged, stats


# ---------------------------------------------------------------------------
# supervisor side: the aggregator
# ---------------------------------------------------------------------------

class FleetAggregator:
    """Supervisor-side merge of every rank's published snapshot.

    ``registry`` is the SUPERVISOR's own registry (the one carrying
    ``elastic/*``): :meth:`refresh` writes the ``fleet/*`` straggler
    gauges into it, and :meth:`merged_registry` folds its metrics into
    the combined view the ``/metrics`` endpoint renders — one scrape
    shows the supervisor's policy counters next to the gang's summed
    training counters.
    """

    def __init__(self, run_dir: str,
                 registry: Optional[MetricsRegistry] = None):
        self.run_dir = run_dir
        self.dir = os.path.join(run_dir, FLEET_DIR)
        self.registry = registry if registry is not None else get_registry()

    # -- snapshot IO --------------------------------------------------------
    def snapshots(self) -> Dict[int, dict]:
        """``{rank: snapshot}`` for every readable ``rank_<i>.json``.
        Writes are atomic so a partial file should never exist, but a
        snapshot that fails to parse is SKIPPED, not fatal — the
        supervisor must keep supervising on a half-corrupt fleet dir."""
        out: Dict[int, dict] = {}
        for path in sorted(glob.glob(os.path.join(self.dir,
                                                  "rank_*.json"))):
            m = _RANK_FILE.search(path)
            if not m:
                continue
            try:
                with open(path) as f:
                    out[int(m.group(1))] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    def clear(self) -> None:
        """Drop every rank snapshot (between supervisor rounds: a stale
        file from the previous gang must not vouch for — or skew — the
        new one; same rule as ``Heartbeat.clear``)."""
        for path in glob.glob(os.path.join(self.dir, "rank_*.json")):
            try:
                os.remove(path)
            except OSError:
                pass

    # -- the merged views ---------------------------------------------------
    # Every method takes an optional preloaded ``snapshots`` dict so one
    # disk read can feed several views: a /metrics scrape builds the
    # fleet gauges AND the merged registry from the SAME snapshot
    # generation (a rank publishing between two independent reads would
    # otherwise make one response describe two different fleets).

    def merged_registry(self, include_local: bool = True,
                        snapshots: Optional[Dict[int, dict]] = None
                        ) -> MetricsRegistry:
        """One registry over supervisor + all rank snapshots — what
        ``/metrics`` renders."""
        docs = []
        if include_local:
            docs.append(self.registry.to_dict())
        snaps = self.snapshots() if snapshots is None else snapshots
        docs.extend(s.get("registry", {})
                    for _, s in sorted(snaps.items()))
        merged, _ = merge_registry_dicts(docs)
        return merged

    def view(self, snapshots: Optional[Dict[int, dict]] = None) -> dict:
        """The raw merged JSON (the ``/fleet`` route): per-rank steps,
        the straggler signals, and the full gauge/counter merge stats."""
        snaps = self.snapshots() if snapshots is None else snapshots
        _, stats = merge_registry_dicts(
            [snaps[r].get("registry", {}) for r in sorted(snaps)])
        return self._view_doc(snaps, stats)

    def _view_doc(self, snaps: Dict[int, dict], stats: dict) -> dict:
        """Assemble the view from an already-computed merge: callers
        that merged for another purpose (the scrape path) reuse their
        stats instead of paying a second cross-rank merge."""
        ranks = sorted(snaps)
        steps = {r: int(snaps[r].get("step", 0)) for r in ranks}
        doc: Dict[str, Any] = {
            "wall_time": time.time(),
            "ranks": ranks,
            "steps": steps,
            "health": {r: snaps[r].get("health", {}) for r in ranks},
            "gauges": stats["gauges"],
            "counters": stats["counters"],
            "skipped_histograms": stats["skipped_histograms"],
        }
        # per-rank step wall, read straight off each snapshot (NOT the
        # merged stats: a rank missing the gauge would shift a zipped
        # mapping) — the spread is the straggler's wall-clock signature
        walls: Dict[int, float] = {}
        for r in ranks:
            v = snaps[r].get("registry", {}).get("gauges", {}) \
                        .get("perf/step_wall_ms")
            if v is not None:
                v = json_float(v)
                if math.isfinite(v):
                    walls[r] = v
        if steps:
            lo, hi = min(steps.values()), max(steps.values())
            doc["step_skew"] = hi - lo
            doc["slowest_rank"] = self._slowest(steps, walls)
        if walls:
            doc["step_wall_spread_ms"] = (max(walls.values())
                                          - min(walls.values()))
        return doc

    @staticmethod
    def _slowest(steps: Dict[int, int], walls: Dict[int, float]) -> int:
        """The straggler: the rank furthest behind in completed steps;
        ties break to the rank with the largest per-step wall
        (``perf/step_wall_ms``), then to the lowest rank id."""
        lo = min(steps.values())
        behind = sorted(r for r, s in steps.items() if s == lo)
        if len(behind) > 1:
            behind.sort(key=lambda r: (-walls.get(r, 0.0), r))
        return behind[0]

    def _publish_gauges(self, doc: dict,
                        reg: Optional[MetricsRegistry] = None) -> None:
        """Write the ``fleet/*`` straggler family off a view. A signal
        absent from the view RESETS its gauge (unset gauges are skipped
        by snapshot/Prometheus) — after :meth:`clear` between rounds, a
        dead gang's skew/straggler must not read as current."""
        reg = self.registry if reg is None else reg
        reg.gauge("fleet/ranks").set(len(doc["ranks"]))
        if "step_skew" in doc:
            reg.gauge("fleet/step_skew").set(doc["step_skew"])
            reg.gauge("fleet/slowest_rank").set(doc["slowest_rank"])
        else:
            reg.gauge("fleet/step_skew").reset()
            reg.gauge("fleet/slowest_rank").reset()
        if "step_wall_spread_ms" in doc:
            reg.gauge("fleet/step_wall_spread_ms").set(
                doc["step_wall_spread_ms"])
        else:
            reg.gauge("fleet/step_wall_spread_ms").reset()

    def refresh(self, snapshots: Optional[Dict[int, dict]] = None) -> dict:
        """Merge now and publish the ``fleet/*`` straggler family into
        the supervisor registry; returns the raw view."""
        doc = self.view(snapshots)
        self._publish_gauges(doc)
        return doc

    def scrape(self) -> Tuple[dict, MetricsRegistry]:
        """The ``/metrics`` fast path: ONE disk read and ONE cross-rank
        merge producing both views — the raw fleet doc (straggler
        gauges published to the supervisor registry) and the combined
        supervisor+ranks registry, with this scrape's own ``fleet/*``
        values folded in (the supervisor doc was serialized before they
        were computed). ``stat_sources`` keeps the per-rank spread
        stats rank-only while the merged registry carries everything."""
        snaps = self.snapshots()
        docs = [self.registry.to_dict()]
        docs.extend(snaps[r].get("registry", {}) for r in sorted(snaps))
        merged, stats = merge_registry_dicts(
            docs, stat_sources=[False] + [True] * len(snaps))
        doc = self._view_doc(snaps, stats)
        self._publish_gauges(doc)          # the supervisor's canonical copy
        self._publish_gauges(doc, merged)  # this scrape's rendered values
        return doc, merged


# ---------------------------------------------------------------------------
# the /metrics endpoint
# ---------------------------------------------------------------------------

class MetricsServer:
    """Stdlib HTTP server for the Prometheus + fleet views.

    ``render_metrics`` is a zero-arg callable returning Prometheus text
    (e.g. ``aggregator.merged_registry().render_prometheus`` composed,
    or a bare ``registry.render_prometheus`` for single-process runs);
    ``render_fleet`` optionally returns the raw merged dict for
    ``/fleet``. Both run per request, so every scrape is fresh. The
    server lives on a daemon thread; ``close()`` shuts it down
    deterministically. A handler exception returns 500 — nothing in
    this class exits the process (the supervisor's exit discipline,
    ``ast-elastic-exits``, must survive the server thread).
    """

    def __init__(self, render_metrics: Callable[[], str],
                 render_fleet: Optional[Callable[[], dict]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self._render_metrics = render_metrics
        self._render_fleet = render_fleet
        self._host = host
        self._port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port (None before :meth:`start`); ``port=0`` asks
        the OS for an ephemeral one."""
        return (self._httpd.server_address[1]
                if self._httpd is not None else None)

    @property
    def url(self) -> str:
        if self._httpd is None:
            raise RuntimeError("MetricsServer not started")
        return f"http://{self._host}:{self.port}"

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = outer._render_metrics().encode()
                        self._reply(200, body,
                                    "text/plain; version=0.0.4")
                    elif path == "/fleet" and \
                            outer._render_fleet is not None:
                        doc = _json_safe_tree(outer._render_fleet())
                        self._reply(200,
                                    json.dumps(doc,
                                               allow_nan=False).encode(),
                                    "application/json")
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except Exception as e:  # render failure -> 500, never exit
                    try:
                        self._reply(500, f"{type(e).__name__}: {e}\n"
                                    .encode(), "text/plain")
                    except OSError:
                        pass  # client went away mid-error

        self._httpd = ThreadingHTTPServer((self._host, self._port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True, name="apex-tpu-metrics")
        self._thread.start()
        return self.port

    def close(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# the postmortem
# ---------------------------------------------------------------------------

def _tail(path: str, max_bytes: int) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def _health_nonfinite(health: Dict[str, Any]) -> bool:
    """True when a rank's last health payload shows non-finite values —
    the host-side twin of ``health.payload_nonfinite``, tolerant of the
    strict-JSON string spellings the snapshot stores."""
    for key, value in health.items():
        try:
            v = json_float(value)
        except (TypeError, ValueError):
            continue
        if key.endswith("/nonfinite_count") and v > 0:
            return True
        if key == "amp/overflow_count" and v > 0:
            return True
    return False


@dataclasses.dataclass
class RankForensics:
    """Everything the postmortem knows about one rank at teardown."""

    rank: int
    returncode: Optional[int]          # PRE-teardown (None = still alive;
    #                                    the supervisor's own SIGKILL at
    #                                    teardown must not frame a victim)
    heartbeat_age_s: Optional[float]   # monotonic-derived; None = never beat
    last_step: Optional[int]
    stalled: bool                      # mtime moved, step did not (budget)
    nonfinite: bool                    # last snapshot's health flags
    snapshot_step: Optional[int]       # step of the last fleet snapshot
    log_tail: str

    def to_dict(self) -> dict:
        return _json_safe_tree(dataclasses.asdict(self))


# culprit precedence: a rank whose heart stopped (died, or silent past
# the budget) outranks one that is alive-but-stuck, which outranks one
# whose numbers went bad — because each earlier class CAUSES the later
# symptoms in its peers (a dead rank stalls every survivor inside gloo)
_REASONS = ("heartbeat_dead", "stalled_step", "health_nonfinite")


@dataclasses.dataclass
class PostmortemReport:
    """One gang teardown, reconstructed: per-rank forensics plus the
    likely culprit. ``cause`` is the supervisor's round outcome
    (``exit`` / ``heartbeat`` / ``stall`` / ``timeout``); the culprit is
    chosen rank-side: dead heartbeat > stalled step > health non-finite
    (``culprit_reason`` names which class fired; ``unknown`` when no
    signal distinguishes a rank)."""

    round_index: int
    world_size: int
    cause: str
    culprit_rank: Optional[int]
    culprit_reason: str
    ranks: List[RankForensics]
    wall_time: float

    @classmethod
    def collect(cls, run_dir: str, *, round_index: int, world_size: int,
                cause: str, returncodes: Dict[int, Optional[int]],
                heartbeat_ages: Optional[Dict[int, float]] = None,
                stalled_ranks: Iterable[int] = (),
                heartbeat_timeout_s: float = math.inf,
                log_tail_bytes: int = 4096) -> "PostmortemReport":
        """Harvest the on-disk state (fleet snapshots, heartbeat files,
        per-round worker logs) plus the supervisor's in-memory signals
        (pre-teardown exit codes, monotonic heartbeat ages, the stall
        set) into a report."""
        from apex_tpu.elastic.launch import Heartbeat

        heartbeat_ages = heartbeat_ages or {}
        stalled = set(stalled_ranks)
        snaps = FleetAggregator(run_dir).snapshots()
        ranks = []
        for rank in range(world_size):
            snap = snaps.get(rank, {})
            age = heartbeat_ages.get(rank)
            if age is None:
                age = Heartbeat.age_s(run_dir, rank)
            log = os.path.join(run_dir, "logs",
                               f"round{round_index}_rank{rank}.log")
            ranks.append(RankForensics(
                rank=rank,
                returncode=returncodes.get(rank),
                heartbeat_age_s=age,
                last_step=Heartbeat.last_step(run_dir, rank),
                stalled=rank in stalled,
                nonfinite=_health_nonfinite(snap.get("health", {})),
                snapshot_step=(int(snap["step"]) if "step" in snap
                               else None),
                log_tail=_tail(log, log_tail_bytes)))
        culprit, reason = cls._pick_culprit(ranks, heartbeat_timeout_s)
        return cls(round_index=int(round_index),
                   world_size=int(world_size), cause=str(cause),
                   culprit_rank=culprit, culprit_reason=reason,
                   ranks=ranks, wall_time=time.time())

    @staticmethod
    def _pick_culprit(ranks: List[RankForensics],
                      hb_timeout_s: float
                      ) -> Tuple[Optional[int], str]:
        def dead(r: RankForensics) -> bool:
            if r.returncode not in (None, 0):
                return True  # died on its own before teardown
            return (r.heartbeat_age_s is not None
                    and r.heartbeat_age_s > hb_timeout_s)

        candidates = [r for r in ranks if dead(r)]
        if candidates:
            # the rank that stopped beating FIRST is where the cascade
            # started; a missing age sorts last (it beat or never ran)
            candidates.sort(key=lambda r: (-(r.heartbeat_age_s
                                             if r.heartbeat_age_s
                                             is not None else -1.0),
                                           r.rank))
            return candidates[0].rank, "heartbeat_dead"
        stalled = sorted(r.rank for r in ranks if r.stalled)
        if stalled:
            return stalled[0], "stalled_step"
        bad = sorted(r.rank for r in ranks if r.nonfinite)
        if bad:
            return bad[0], "health_nonfinite"
        return None, "unknown"

    # -- artifacts ----------------------------------------------------------
    def to_dict(self) -> dict:
        doc = {"schema": SNAPSHOT_SCHEMA,
               "round_index": self.round_index,
               "world_size": self.world_size,
               "cause": self.cause,
               "culprit_rank": self.culprit_rank,
               "culprit_reason": self.culprit_reason,
               "wall_time": self.wall_time,
               "ranks": [r.to_dict() for r in self.ranks]}
        return _json_safe_tree(doc)

    def markdown(self) -> str:
        lines = [f"# Gang postmortem — round {self.round_index} "
                 f"(world {self.world_size})",
                 "",
                 f"- **cause**: `{self.cause}`",
                 f"- **likely culprit**: "
                 + (f"rank {self.culprit_rank} "
                    f"(`{self.culprit_reason}`)"
                    if self.culprit_rank is not None
                    else f"none identified (`{self.culprit_reason}`)"),
                 "",
                 "| rank | exit (pre-teardown) | hb age s | last step | "
                 "stalled | non-finite |",
                 "|---|---|---|---|---|---|"]
        fmt = lambda v: "-" if v is None else (f"{v:.1f}"
                                               if isinstance(v, float)
                                               else str(v))
        for r in self.ranks:
            lines.append(
                f"| {r.rank} | {fmt(r.returncode)} "
                f"| {fmt(r.heartbeat_age_s)} | {fmt(r.last_step)} "
                f"| {'yes' if r.stalled else 'no'} "
                f"| {'yes' if r.nonfinite else 'no'} |")
        for r in self.ranks:
            if r.log_tail:
                lines += ["", f"## rank {r.rank} log tail", "```",
                          r.log_tail.rstrip("\n"), "```"]
        return "\n".join(lines) + "\n"

    def write(self, out_dir: str) -> Tuple[str, str]:
        """Write ``round<k>.json`` (strict JSON — non-finite values as
        strings, ``allow_nan=False``) and ``round<k>.md`` into
        ``out_dir``; returns both paths."""
        os.makedirs(out_dir, exist_ok=True)
        base = os.path.join(out_dir, f"round{self.round_index}")
        json_path, md_path = base + ".json", base + ".md"
        with open(json_path + ".tmp", "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True,
                      allow_nan=False)
        os.replace(json_path + ".tmp", json_path)
        with open(md_path + ".tmp", "w") as f:
            f.write(self.markdown())
        os.replace(md_path + ".tmp", md_path)
        return json_path, md_path
