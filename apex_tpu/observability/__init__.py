"""apex_tpu.observability — structured training telemetry.

The reference exposes runtime behavior only through ad-hoc prints (amp's
``maybe_print``, ``reference:apex/amp/_amp_state.py:39-51``; Megatron
``_Timers.log``) and the deprecated pyprof pipeline. This package is the
structured replacement: one stream that answers "what did this step spend,
where, on which rank" without a trace capture.

Composable modules, each zero-cost when unused:

- :mod:`~apex_tpu.observability.registry` — host-side counters, gauges and
  fixed-bucket histograms (``Metric.observe()``), grouped in a
  :class:`MetricsRegistry`;
- :mod:`~apex_tpu.observability.ingraph` — the in-graph accumulator: traced
  code calls :func:`record`, a reaping wrapper returns the recorded scalars
  as a pytree of device values, and :func:`aggregate` psums them across the
  mesh at report time (no host round-trips inside the step);
- :mod:`~apex_tpu.observability.report` / ``sinks`` — a
  :class:`StepReporter` snapshotting registry + ``Timers`` + in-graph
  metrics each step into pluggable sinks (JSONL event log, TensorBoard
  ``add_scalar`` writers, Chrome-trace span export);
- :mod:`~apex_tpu.observability.runtime` — compile/recompile counters via
  ``jax.monitoring`` listeners and a ``memory_stats()`` gauge sampler, so
  recompilation storms and HBM growth land in the same stream;
- :mod:`~apex_tpu.observability.health` — the numerics watchdog: per-leaf
  NaN/overflow attribution (``health/*``), replica-agreement checks, and
  the :class:`HealthConfig` policy whose :class:`HealthMonitor` reporter
  hook raises or writes a structured :class:`CrashDump` on a non-finite
  step;
- :mod:`~apex_tpu.observability.costs` — the peak-flops table and MFU
  math shared by ``bench.py`` and the reporter's ``perf/mfu`` gauge;
- :mod:`~apex_tpu.observability.reqtrace` /
  :mod:`~apex_tpu.observability.slo` — the serving-side request
  lifecycle: per-request span records with TTFT/TPOT/queue-wait/e2e
  latencies, a bounded flight-recorder ring with a per-slot-swimlane
  Chrome-trace export, and :class:`SLOTracker` — declarative latency
  targets, rolling goodput/burn-rate gauges (``slo/*``), and a
  flight-recorder :class:`CrashDump` on violation;
- :mod:`~apex_tpu.observability.perfwatch` — the performance
  observatory: the append-only ``BENCH_HISTORY.jsonl`` bench history
  (:class:`BenchHistory`, full-precision ``raw_value`` + git/host
  provenance, ``BENCH_r*.json`` importer), the rolling-median+MAD
  :class:`RegressionDetector` with unit-inferred direction,
  :class:`AttributionDiff` region diffs naming the suspect region, and
  measured/modeled cost-model drift (``perf/model_drift`` gauges +
  shift alerts); CLI: ``python -m apex_tpu.perfwatch``;
- :mod:`~apex_tpu.observability.fleet` — the cross-rank merge layer:
  rank-side registry snapshots (:class:`FleetPublisher`, atomic JSON),
  the supervisor-side :class:`FleetAggregator` (counters sum, gauges
  min/max/mean + spread, histogram buckets add) with the ``fleet/*``
  straggler family, :class:`PostmortemReport` gang forensics, and a
  stdlib :class:`MetricsServer` serving ``/metrics`` (Prometheus text
  via ``render_prometheus``) + ``/fleet`` (merged JSON).

Hot paths in the library are pre-instrumented (``amp/*``, ``ddp/*``,
``pipeline/*``, ``optim/*``, ``health/*`` — see ``docs/OBSERVABILITY.md``);
with no collector active every instrumentation point is a module-level
no-op that adds nothing to the traced program.
"""

from apex_tpu.observability.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry, log_buckets)
from apex_tpu.observability.ingraph import (  # noqa: F401
    Metrics, aggregate, collecting, reap, record, recording)
from apex_tpu.observability.trace import (  # noqa: F401
    Span, chrome_trace_events, drain_spans, epoch_offset,
    merge_chrome_traces, span_recording, spans_enabled)
from apex_tpu.observability.sinks import (  # noqa: F401
    ChromeTraceSink, JSONLSink, TensorBoardSink)
from apex_tpu.observability.report import (  # noqa: F401
    NullReporter, StepReporter, attach_reporter, detach_reporter,
    get_reporter)
from apex_tpu.observability.runtime import (  # noqa: F401
    install_compile_listeners, reset_compile_listeners,
    sample_memory_stats, uninstall_compile_listeners)
from apex_tpu.observability.health import (  # noqa: F401
    CrashDump, HealthConfig, HealthMonitor, NonFiniteError, TreeStats,
    check_replica_agreement, decode_attribution, tensor_stats)
from apex_tpu.observability.costs import (  # noqa: F401
    flops_budget, memory_budget, mfu, peak_flops)
from apex_tpu.observability.reqtrace import (  # noqa: F401
    LATENCY_BUCKETS_MS, RequestRecord, RequestTrace, chrome_request_trace)
from apex_tpu.observability.slo import (  # noqa: F401
    SLOTarget, SLOTracker, SLOViolationError)
from apex_tpu.observability.fleet import (  # noqa: F401
    FleetAggregator, FleetPublisher, MetricsServer, PostmortemReport,
    merge_registry_dicts)
from apex_tpu.observability.perfwatch import (  # noqa: F401
    AttributionDiff, BenchHistory, DriftShift, Regression,
    RegressionDetector, detect_drift_shifts, drift_series, publish_drift,
    unit_direction)
