"""apex_tpu.observability — structured training telemetry.

The reference exposes runtime behavior only through ad-hoc prints (amp's
``maybe_print``, ``reference:apex/amp/_amp_state.py:39-51``; Megatron
``_Timers.log``) and the deprecated pyprof pipeline. This package is the
structured replacement: one stream that answers "what did this step spend,
where, on which rank" without a trace capture.

Four layers, composable and each zero-cost when unused:

- :mod:`~apex_tpu.observability.registry` — host-side counters, gauges and
  fixed-bucket histograms (``Metric.observe()``), grouped in a
  :class:`MetricsRegistry`;
- :mod:`~apex_tpu.observability.ingraph` — the in-graph accumulator: traced
  code calls :func:`record`, a reaping wrapper returns the recorded scalars
  as a pytree of device values, and :func:`aggregate` psums them across the
  mesh at report time (no host round-trips inside the step);
- :mod:`~apex_tpu.observability.report` / ``sinks`` — a
  :class:`StepReporter` snapshotting registry + ``Timers`` + in-graph
  metrics each step into pluggable sinks (JSONL event log, TensorBoard
  ``add_scalar`` writers, Chrome-trace span export);
- :mod:`~apex_tpu.observability.runtime` — compile/recompile counters via
  ``jax.monitoring`` listeners and a ``memory_stats()`` gauge sampler, so
  recompilation storms and HBM growth land in the same stream.

Hot paths in the library are pre-instrumented (``amp/*``, ``ddp/*``,
``pipeline/*``, ``optim/*`` — see ``docs/OBSERVABILITY.md``); with no
collector active every instrumentation point is a module-level no-op that
adds nothing to the traced program.
"""

from apex_tpu.observability.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry)
from apex_tpu.observability.ingraph import (  # noqa: F401
    Metrics, aggregate, collecting, reap, record, recording)
from apex_tpu.observability.trace import (  # noqa: F401
    Span, chrome_trace_events, drain_spans, span_recording, spans_enabled)
from apex_tpu.observability.sinks import (  # noqa: F401
    ChromeTraceSink, JSONLSink, TensorBoardSink)
from apex_tpu.observability.report import (  # noqa: F401
    NullReporter, StepReporter, attach_reporter, detach_reporter,
    get_reporter)
from apex_tpu.observability.runtime import (  # noqa: F401
    install_compile_listeners, sample_memory_stats)
