"""Peak-flops table, MFU math, and compiled-memory budgets — one source of
truth.

``bench.py`` grew a hand-rolled device-kind -> peak-bf16-flops table and a
``compiled.cost_analysis()`` extraction for its MFU columns; the
:class:`~apex_tpu.observability.report.StepReporter` wants the same number
as a live gauge. Both now read from here:

- :data:`PEAK_BF16_FLOPS` / :func:`peak_flops` — peak dense bf16 FLOP/s
  per chip by ``device_kind`` prefix (public spec-sheet numbers);
- :func:`flops_budget` — the per-step model FLOPs of a lowered+compiled
  executable via XLA's cost analysis (None when the backend reports
  nothing useful — notably, Mosaic custom calls report zero flops, so GPT
  steps with flash attention should prefer an analytic count);
- :func:`mfu` — model-flops-utilization: achieved FLOP/s over peak;
- :func:`memory_budget` — the executable's static memory plan from
  ``compiled.memory_analysis()`` (argument/output/temp/peak bytes) — the
  number that makes an activation-remat policy choice measurable instead
  of vibes (``StepReporter.attach_memory_budget`` turns it into the
  ``mem/*`` gauge family; ``bench.py`` records it next to step_ms).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Dict, Optional

__all__ = ["PEAK_BF16_FLOPS", "DEFAULT_PEAK_FLOPS", "peak_flops",
           "DeviceSpec", "DEVICE_SPECS", "DEFAULT_DEVICE_SPEC",
           "device_spec", "flops_budget", "memory_budget", "mfu"]

# peak dense bf16 TFLOP/s per chip by device kind (public spec sheets)
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}

# assume v5e-class when the device kind is unknown (CPU test hosts, new
# chips the table has not learned yet) — conservative for MFU claims
DEFAULT_PEAK_FLOPS = 197e12


def peak_flops(device=None) -> float:
    """Peak dense bf16 FLOP/s of ``device`` (default: the first visible
    device), matched by ``device_kind`` prefix against
    :data:`PEAK_BF16_FLOPS`; :data:`DEFAULT_PEAK_FLOPS` when unknown."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    for prefix, value in PEAK_BF16_FLOPS.items():
        if kind.startswith(prefix):
            return value
    return DEFAULT_PEAK_FLOPS


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Roofline corners of one chip: peak dense bf16 FLOP/s, HBM bandwidth
    and per-link ICI bandwidth. The numbers the pyprof roofline evaluator
    (:mod:`apex_tpu.pyprof.model`) divides modeled FLOPs/bytes by."""
    name: str
    peak_flops: float   # dense bf16 FLOP/s per chip
    hbm_gbps: float     # HBM bandwidth, GB/s per chip
    ici_gbps: float     # ICI bandwidth, GB/s per link per direction

    def compute_ms(self, flops: float) -> float:
        return flops / self.peak_flops * 1e3

    def hbm_ms(self, traffic_bytes: float) -> float:
        return traffic_bytes / (self.hbm_gbps * 1e9) * 1e3

    def comm_ms(self, wire_bytes: float) -> float:
        return wire_bytes / (self.ici_gbps * 1e9) * 1e3


# HBM/ICI companions to PEAK_BF16_FLOPS (public spec-sheet numbers; ICI
# is per link per direction — the ring models in pyprof serialize hops
# over one link, the worst-case topology). Env-overridable via
# APEX_TPU_PEAK_FLOPS / APEX_TPU_HBM_GBPS / APEX_TPU_ICI_GBPS, the escape
# hatch for chips the table has not learned yet (and for calibrating the
# roofline against a measured bandwidth instead of the datasheet).
DEVICE_SPECS = {
    "TPU v4": DeviceSpec("TPU v4", PEAK_BF16_FLOPS["TPU v4"], 1228.0, 50.0),
    "TPU v5 lite": DeviceSpec("TPU v5e", PEAK_BF16_FLOPS["TPU v5e"],
                              819.0, 50.0),
    "TPU v5e": DeviceSpec("TPU v5e", PEAK_BF16_FLOPS["TPU v5e"],
                          819.0, 50.0),
    "TPU v5": DeviceSpec("TPU v5p", PEAK_BF16_FLOPS["TPU v5p"],
                         2765.0, 100.0),
    "TPU v5p": DeviceSpec("TPU v5p", PEAK_BF16_FLOPS["TPU v5p"],
                          2765.0, 100.0),
    "TPU v6 lite": DeviceSpec("TPU v6e", PEAK_BF16_FLOPS["TPU v6e"],
                              1640.0, 100.0),
    "TPU v6e": DeviceSpec("TPU v6e", PEAK_BF16_FLOPS["TPU v6e"],
                          1640.0, 100.0),
}

# CPU test hosts and unknown chips: v5e-class corners, same rationale as
# DEFAULT_PEAK_FLOPS (conservative for utilization claims; on CPU the
# modeled milliseconds are structural, not predictive — the regions,
# ratios and byte counts are what the tests pin down)
DEFAULT_DEVICE_SPEC = DeviceSpec("unknown (v5e-class assumed)",
                                 DEFAULT_PEAK_FLOPS, 819.0, 50.0)


def device_spec(device=None) -> DeviceSpec:
    """The :class:`DeviceSpec` of ``device`` (default: first visible
    device), matched by ``device_kind`` prefix; falls back to
    :data:`DEFAULT_DEVICE_SPEC`. ``APEX_TPU_PEAK_FLOPS`` (FLOP/s),
    ``APEX_TPU_HBM_GBPS`` and ``APEX_TPU_ICI_GBPS`` (GB/s) override the
    matched table entry field-by-field."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "")
    spec = DEFAULT_DEVICE_SPEC
    for prefix, value in DEVICE_SPECS.items():
        if kind.startswith(prefix):
            spec = value
            break
    overrides = {}
    for env, field in (("APEX_TPU_PEAK_FLOPS", "peak_flops"),
                       ("APEX_TPU_HBM_GBPS", "hbm_gbps"),
                       ("APEX_TPU_ICI_GBPS", "ici_gbps")):
        raw = os.environ.get(env)
        if raw:
            value = float(raw)
            if value <= 0.0:
                raise ValueError(f"{env} must be positive, got {raw!r}")
            overrides[field] = value
    if overrides:
        spec = dataclasses.replace(spec, name=spec.name + " (env-tuned)",
                                   **overrides)
    return spec


def flops_budget(compiled) -> Optional[float]:
    """Per-execution model FLOPs of a compiled executable
    (``jit(f).lower(...).compile()``), from XLA's cost analysis.

    Returns None when the backend exposes no cost analysis or reports a
    non-positive/non-finite count (custom calls — e.g. Mosaic flash
    attention — report zero flops and would deflate MFU; callers should
    fall back to an analytic count, as ``bench.py`` does).
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost["flops"])
    except Exception:
        return None
    if not (0.0 < flops < float("inf")):  # rejects NaN, ±inf, <= 0
        return None
    return flops


def memory_budget(compiled) -> Optional[Dict[str, int]]:
    """Static memory plan of a compiled executable
    (``jit(f).lower(...).compile()``), from ``compiled.memory_analysis()``.

    Returns None when the backend exposes no analysis; otherwise a dict of

    - ``argument_bytes`` / ``output_bytes`` — buffers entering/leaving the
      program (donated/aliased bytes already netted out via
      ``alias_bytes``);
    - ``temp_bytes`` — XLA's scratch high-water for the program body: the
      activation/residual working set. THIS is the number an activation-
      remat policy moves (``none > selective > full`` on a train step);
    - ``alias_bytes`` — input/output-aliased (donated) bytes;
    - ``generated_code_bytes`` — the program text itself;
    - ``host_temp_bytes`` — host-memory scratch: nonzero exactly when an
      ``offload`` remat policy (or any host-memory placement) is in play;
    - ``peak_hbm_bytes`` — the device high-water estimate
      ``argument + output + temp + generated_code - alias`` (the standard
      XLA accounting: arguments and outputs are resident for the whole
      program, donation collapses the aliased pairs).
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None

    def _get(attr: str) -> int:
        return int(getattr(ma, attr, 0) or 0)

    out = {
        "argument_bytes": _get("argument_size_in_bytes"),
        "output_bytes": _get("output_size_in_bytes"),
        "temp_bytes": _get("temp_size_in_bytes"),
        "alias_bytes": _get("alias_size_in_bytes"),
        "generated_code_bytes": _get("generated_code_size_in_bytes"),
        "host_temp_bytes": _get("host_temp_size_in_bytes"),
    }
    out["peak_hbm_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"]
                             + out["generated_code_bytes"]
                             - out["alias_bytes"])
    return out


def mfu(flops_per_step: float, step_time_s: float,
        peak: Optional[float] = None) -> float:
    """Model-flops-utilization: ``flops_per_step / step_time_s / peak``
    (``peak`` defaults to :func:`peak_flops` of the first device).

    A non-positive ``step_time_s`` or ``peak`` returns ``NaN`` instead of
    raising: the first-report wall-time delta in a tight loop can
    legitimately be ~0 on a fast host (two ``perf_counter`` reads between
    cached dispatches), and an exception or ``inf``/``ZeroDivisionError``
    mid-``report()`` would kill the training loop over a telemetry
    artifact. Consumers that want a hard failure should validate inputs
    at configuration time (``StepReporter.attach_flops_budget`` does).
    """
    if peak is None:
        peak = peak_flops()
    if step_time_s <= 0.0 or peak <= 0.0:
        return math.nan
    return flops_per_step / step_time_s / peak
