"""Pluggable metric sinks for the :class:`StepReporter`.

A sink receives one flattened ``{name: float}`` payload (plus any timer
spans) per reported step. Three are provided:

- :class:`JSONLSink` — one JSON object per step, the grep-able event log;
- :class:`TensorBoardSink` — adapter onto any object with
  ``add_scalar(tag, value, step)``, the writer protocol ``Timers.write``
  already targets (``reference:apex/transformer/pipeline_parallel/
  _timers.py:66-75``), so a real SummaryWriter drops in unchanged;
- :class:`ChromeTraceSink` — accumulates timer spans (and per-step metric
  counter tracks) into a ``chrome://tracing`` / Perfetto-loadable JSON.
"""

from __future__ import annotations

import io
import json
import math
import os
import time
from typing import Any, Dict, Iterable, Optional, Sequence, Union

from apex_tpu.observability.registry import json_safe_float
from apex_tpu.observability.trace import (Span, chrome_trace_events,
                                          trace_metadata)

__all__ = ["Sink", "JSONLSink", "TensorBoardSink", "ChromeTraceSink",
           "json_safe_value", "json_safe_metrics"]


def json_safe_value(value: Any) -> Any:
    """Non-finite floats as the strings ``"NaN"``/``"Infinity"``/
    ``"-Infinity"`` — health metrics legitimately carry them (a NaN
    abs-max IS the signal), and Python's default ``json`` emits bare
    non-standard literals that jq/``JSON.parse``/Go reject wholesale.
    The one spelling contract lives in
    :func:`~apex_tpu.observability.registry.json_safe_float` (shared
    with the fleet snapshot serialization); this wrapper just passes
    non-float values through untouched."""
    if isinstance(value, float) and not math.isfinite(value):
        return json_safe_float(value)
    return value


def json_safe_metrics(metrics: Dict[str, Any]) -> Dict[str, Any]:
    return {k: json_safe_value(v) for k, v in metrics.items()}


class Sink:
    """Interface: ``emit`` once per reported step, ``close`` at shutdown."""

    def emit(self, step: int, metrics: Dict[str, float],
             spans: Sequence[Span] = ()) -> None:  # pragma: no cover
        raise NotImplementedError

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JSONLSink(Sink):
    """One ``{"step", "time", "metrics"}`` JSON line per report.

    Accepts a path (opened append, crash-durable via line-buffered flush)
    or any text file-like (e.g. ``io.StringIO`` in tests, ``sys.stdout``
    for the reference's print-style visibility done structurally).
    """

    def __init__(self, path_or_file: Union[str, os.PathLike, io.TextIOBase]):
        if isinstance(path_or_file, (str, os.PathLike)):
            self._file = open(path_or_file, "a")
            self._owns = True
        else:
            self._file = path_or_file
            self._owns = False

    def emit(self, step, metrics, spans=()):
        self._file.write(json.dumps(
            {"step": int(step), "time": time.time(),
             "metrics": {k: json_safe_value(metrics[k])
                         for k in sorted(metrics)}},
            allow_nan=False) + "\n")
        self._file.flush()

    def close(self):
        if self._owns:
            self._file.close()


class TensorBoardSink(Sink):
    """Fan a payload out as ``writer.add_scalar(name, value, step)``."""

    def __init__(self, writer):
        if not hasattr(writer, "add_scalar"):
            raise TypeError("TensorBoardSink needs an object with "
                            "add_scalar(tag, value, step)")
        self.writer = writer

    def emit(self, step, metrics, spans=()):
        for name in sorted(metrics):
            self.writer.add_scalar(name, metrics[name], step)

    def close(self):
        flush = getattr(self.writer, "flush", None)
        if flush is not None:
            flush()


class ChromeTraceSink(Sink):
    """Accumulate spans into Chrome-trace JSON, written on ``close``.

    Metric payloads are also emitted as counter events (``ph="C"``) so
    scalar series render as tracks under the spans in Perfetto. ``pid`` is
    the JAX process index by default, separating hosts in a multi-process
    capture.
    """

    def __init__(self, path: Union[str, os.PathLike],
                 pid: Optional[int] = None,
                 counters: Union[bool, Iterable[str]] = True):
        self.path = os.fspath(path)
        if pid is None:
            try:
                import jax
                pid = jax.process_index()
            except Exception:
                pid = 0
        self.pid = pid
        self._counters = counters
        self._events = []
        # the cross-process timebase anchor: ts fields are perf_counter
        # microseconds (process-local zero), and this offset is what lets
        # trace.merge_chrome_traces align several ranks' files into one
        # Perfetto view (sampled once — the clocks only NTP-slew apart)
        self._metadata = trace_metadata()

    def emit(self, step, metrics, spans=()):
        self._events.extend(
            chrome_trace_events(spans, pid=self.pid, step=step))
        if self._counters and metrics:
            names = (sorted(metrics) if self._counters is True
                     else [n for n in self._counters if n in metrics])
            ts = time.perf_counter() * 1e6
            for name in names:
                self._events.append(
                    {"name": name, "ph": "C", "cat": "apex_tpu",
                     "ts": ts, "pid": self.pid,
                     "args": {name: json_safe_value(metrics[name])}})

    def close(self):
        with open(self.path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": self._metadata}, f, allow_nan=False)
