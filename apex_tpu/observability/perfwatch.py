"""Performance observatory: bench history, regression watch, model drift.

The repo can observe a step (pyprof attribution), a request (reqtrace)
and a fleet (fleet.py), but nothing observes performance *across runs*:
``BENCH_r*.json`` files accumulate unanalyzed and BASELINE.md's anchor
raise is a manual protocol. This module is the longitudinal layer:

- :class:`BenchHistory` — an append-only JSONL store of bench lines
  (full-precision ``raw_value`` next to the 2-decimal display value,
  config block, pyprof extras, git-sha + host-fingerprint provenance)
  with a one-shot importer for the historical ``BENCH_r*.json`` files;
- :class:`RegressionDetector` — per-metric rolling-median + MAD
  thresholds with the good direction inferred from the unit
  (``tokens/sec`` up-is-good, ``ms``/``bytes`` down-is-good), noise
  floors learned from the trailing window's variance, typed
  :class:`Regression` findings;
- :class:`AttributionDiff` — region-by-region diff of two pyprof
  attribution reports, so a flagged regression *names the region* whose
  measured ms moved;
- :func:`drift_series` / :func:`detect_drift_shifts` /
  :func:`publish_drift` — the measured/modeled ratio per attributed
  line as a time series, surfaced as ``perf/model_drift`` gauges with a
  two-sided shift alert — the continuous cost-model validation the
  roofline autotuner (ROADMAP item 4) needs before trusting the model.

CLI: ``python -m apex_tpu.perfwatch [--check|--report|--import-bench|
--selfcheck]`` — exit 0 clean, 1 regressions/drift shifts/dead
selfcheck, 2 usage.

Everything here is host-side Python over JSON lines — no jax import
anywhere in the module, and the dryrun gate asserts the serving device
programs are byte-identical with the observatory on and off (the same
zero-cost contract as the registry/fleet layers). The JSONL schema is
pinned by the literal ``HISTORY_FIELDS`` table, which the
``ast-bench-history`` lint validates against this module's own writer
(docs/OBSERVABILITY.md "Performance observatory").
"""

from __future__ import annotations

import argparse
import dataclasses
import glob
import json
import math
import os
import platform
import subprocess
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["HISTORY_FIELDS", "REQUIRED_FIELDS", "FIELD_NAMES",
           "UNIT_DIRECTION", "DEFAULT_HISTORY", "unit_direction",
           "make_record", "validate_record", "detect_git_sha",
           "host_fingerprint", "BenchHistory", "Regression",
           "RegressionDetector", "RegionDelta", "AttributionDiff",
           "DriftShift", "drift_series", "detect_drift_shifts",
           "publish_drift", "selfcheck", "synthetic_history",
           "render_report", "main"]

# ---------------------------------------------------------------------------
# the JSONL schema: one literal table, one writer, one lint
# ---------------------------------------------------------------------------

DEFAULT_HISTORY = "BENCH_HISTORY.jsonl"

# The history record schema. ``required`` keys appear in EVERY record
# (the base dict literal in :func:`make_record`); ``optional`` keys are
# promoted from the extras when present. This table is the single
# source of truth: the ``ast-bench-history`` lint statically checks the
# writer's literal keys against it, and any on-disk history file against
# both — so a drive-by key rename cannot silently fork the schema.
HISTORY_FIELDS = (
    ("metric", "required"),       # bench line name
    ("value", "required"),        # 2-decimal display value (bench parity)
    ("raw_value", "required"),    # full-precision value (detector input)
    ("unit", "required"),         # bench unit string (direction source)
    ("vs_baseline", "required"),  # the line's own baseline ratio or null
    ("run", "required"),          # round id ("r05", gate leg, ...) or null
    ("source", "required"),       # "bench" | importer filename | caller tag
    ("git_sha", "required"),      # code provenance
    ("host", "required"),         # host fingerprint (cross-host noise)
    ("config", "optional"),         # the line's TrainConfig-shaped block
    ("modeled_step_ms", "optional"),    # pyprof roofline lower bound
    ("comm_exposed_ms", "optional"),    # modeled unhidden communication
    ("overlap_efficiency", "optional"),  # hidden-fraction of ICI bytes
    ("step_time_ms", "optional"),   # measured step (drift numerator)
    ("attribution", "optional"),    # per-region [{region, modeled_ms,
                                    #   measured_ms}] (diff input)
    ("extra", "optional"),          # everything else the line carried
)

REQUIRED_FIELDS = frozenset(
    k for k, kind in HISTORY_FIELDS if kind == "required")
FIELD_NAMES = frozenset(k for k, _kind in HISTORY_FIELDS)

# optional keys lifted from a bench line's extras to top-level record
# keys (everything else rides under "extra") — derived from the table so
# the writer cannot drift from the schema
_PROMOTED = tuple(k for k, kind in HISTORY_FIELDS
                  if kind == "optional" and k not in ("config", "extra"))

# unit -> good direction: +1 up-is-good, -1 down-is-good, 0 not a
# performance series (skip lines / error lines / unknown units). The
# exact spellings are the ones bench.py emits; unlisted units fall back
# to suffix inference in :func:`unit_direction`.
UNIT_DIRECTION = {
    "imgs/sec": 1,
    "tokens/sec": 1,
    "percent": 1,     # goodput: fraction of requests meeting the SLO
    "ms": -1,
    "bytes": -1,
    "skipped": 0,
    "error": 0,
}


def unit_direction(unit: str) -> int:
    """+1 when larger is better, -1 when smaller is better, 0 when the
    unit carries no performance direction (the detector skips it)."""
    u = str(unit)
    if u in UNIT_DIRECTION:
        return UNIT_DIRECTION[u]
    if u.endswith("/sec") or u.endswith("/s"):
        return 1
    if u.endswith("ms") or u.endswith("bytes") or u in ("s", "sec"):
        return -1
    return 0


# ---------------------------------------------------------------------------
# provenance
# ---------------------------------------------------------------------------

_GIT_SHA_CACHE: Dict[str, str] = {}


def _package_root() -> str:
    """The repo root this package is installed from
    (``<repo>/apex_tpu/observability/perfwatch.py``)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def detect_git_sha(repo: Optional[str] = None) -> str:
    """Short HEAD sha of ``repo`` (default: the package's own tree), or
    ``"unknown"`` outside a checkout — provenance must never fail a
    bench run."""
    root = os.path.abspath(repo or _package_root())
    if root not in _GIT_SHA_CACHE:
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short=12", "HEAD"], cwd=root,
                capture_output=True, text=True, timeout=10)
            sha = out.stdout.strip() if out.returncode == 0 else ""
        except (OSError, subprocess.SubprocessError):
            sha = ""
        _GIT_SHA_CACHE[root] = sha or "unknown"
    return _GIT_SHA_CACHE[root]


def host_fingerprint() -> str:
    """``node/arch/pyX.Y`` — enough to separate series recorded on
    different hosts (a CPU sandbox and a TPU host must never share a
    noise floor)."""
    return "%s/%s/py%d.%d" % (
        platform.node() or "unknown", platform.machine() or "unknown",
        sys.version_info[0], sys.version_info[1])


# ---------------------------------------------------------------------------
# records + the append-only store
# ---------------------------------------------------------------------------

def make_record(metric: str, value: float, unit: str,
                vs_baseline: Optional[float] = None, *,
                raw_value: Optional[float] = None,
                run: Optional[str] = None, source: str = "bench",
                extras: Optional[dict] = None,
                git_sha: Optional[str] = None,
                host: Optional[str] = None) -> dict:
    """One schema-complete history record.

    ``value`` mirrors bench.py's printed 2-decimal display value;
    ``raw_value`` carries the FULL-PRECISION number (defaults to
    ``value`` before rounding) — the detector always reads
    ``raw_value``, so sub-0.5% deltas survive the display quantization
    that forced ``gpt_decode_goodput`` into percent. Extras named in
    ``HISTORY_FIELDS`` are promoted to top-level keys; the remainder
    rides under ``extra``.
    """
    raw = float(value if raw_value is None else raw_value)
    rec = {
        "metric": str(metric),
        "value": round(float(value), 2),
        "raw_value": raw,
        "unit": str(unit),
        "vs_baseline": None if vs_baseline is None else float(vs_baseline),
        "run": run,
        "source": str(source),
        "git_sha": git_sha if git_sha is not None else detect_git_sha(),
        "host": host if host is not None else host_fingerprint(),
    }
    leftover = dict(extras or {})
    config = leftover.pop("config", None)
    if config is not None:
        rec["config"] = config
    for key in _PROMOTED:
        if key in leftover:
            rec[key] = leftover.pop(key)
    if leftover:
        rec["extra"] = leftover
    return rec


def validate_record(rec: Any) -> None:
    """Raise ``ValueError`` unless ``rec`` matches ``HISTORY_FIELDS``
    (every required key present, no key outside the table)."""
    if not isinstance(rec, dict):
        raise ValueError(f"history record must be a dict, "
                         f"got {type(rec).__name__}")
    missing = sorted(REQUIRED_FIELDS - set(rec))
    unknown = sorted(set(rec) - FIELD_NAMES)
    if missing or unknown:
        raise ValueError(
            f"history record for {rec.get('metric', '?')!r} violates "
            f"HISTORY_FIELDS: missing {missing}, unknown {unknown}")


class BenchHistory:
    """Append-only JSONL store of bench records, in metric/time order.

    ``path=None`` keeps the history in memory (selfchecks, gate legs);
    with a path, every :meth:`append` writes one JSON line — append-only
    by construction, so concurrent readers never see a torn file and
    provenance is never rewritten.
    """

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.records: List[dict] = []
        if path is not None and os.path.exists(path):
            with open(path) as f:
                for lineno, line in enumerate(f, 1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError as e:
                        raise ValueError(
                            f"{path}:{lineno}: not a JSON record: {e}")
                    validate_record(rec)
                    self.records.append(rec)

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def append(self, rec: dict) -> dict:
        validate_record(rec)
        self.records.append(rec)
        if self.path is not None:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec

    def record(self, metric: str, value: float, unit: str,
               vs_baseline: Optional[float] = None, **kwargs) -> dict:
        """Build (via :func:`make_record`) and append one record."""
        return self.append(make_record(metric, value, unit, vs_baseline,
                                       **kwargs))

    def metrics(self) -> List[str]:
        """Metric names in first-seen order."""
        seen: Dict[str, None] = {}
        for rec in self.records:
            seen.setdefault(rec["metric"], None)
        return list(seen)

    def series(self, metric: str) -> List[dict]:
        """Every record for ``metric``, in append order."""
        return [r for r in self.records if r["metric"] == metric]

    # -- the one-shot importer ---------------------------------------

    def import_bench_files(self, paths: Optional[Sequence[str]] = None,
                           root: Optional[str] = None) -> int:
        """Ingest historical ``BENCH_r*.json`` driver dumps
        (``{n, cmd, rc, tail, parsed}`` — the metric lines are the
        ``tail`` lines opening with ``{``). Idempotent per file: a
        source filename already present in the history is skipped, so
        re-running the importer never duplicates a round. Returns the
        number of records added. Historical lines predate ``raw_value``,
        so it equals the 2-decimal value there — the detector's noise
        floor covers the quantization."""
        if paths is None:
            root = root or _package_root()
            paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
        seen_sources = {r.get("source") for r in self.records}
        added = 0
        for path in paths:
            base = os.path.basename(path)
            if base in seen_sources:
                continue
            with open(path) as f:
                dump = json.load(f)
            if not isinstance(dump, dict):
                continue
            run = (f"r{int(dump['n']):02d}" if isinstance(
                dump.get("n"), int) else os.path.splitext(base)[0])
            for line in str(dump.get("tail", "")).splitlines():
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(obj, dict) or "metric" not in obj \
                        or "value" not in obj:
                    continue
                extras = {k: v for k, v in obj.items()
                          if k not in ("metric", "value", "unit",
                                       "vs_baseline")}
                self.record(str(obj["metric"]), float(obj["value"]),
                            str(obj.get("unit", "")), obj.get("vs_baseline"),
                            run=run, source=base, extras=extras,
                            git_sha="import", host="import")
                added += 1
        return added


# ---------------------------------------------------------------------------
# the regression detector
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Regression:
    """One direction-adverse shift of a metric beyond its noise."""
    metric: str
    index: int              # position within the metric's series
    run: Optional[str]
    value: float
    baseline: float         # rolling median the point was scored against
    delta_frac: float       # signed (value - baseline) / |baseline|
    threshold_frac: float   # the learned noise floor it exceeded
    unit: str
    direction: int          # +1 up-is-good, -1 down-is-good
    suspect_region: Optional[str] = None
    suspect_delta_ms: Optional[float] = None

    def message(self) -> str:
        good = "up-is-good" if self.direction > 0 else "down-is-good"
        msg = (f"{self.metric}[{self.run or self.index}] = "
               f"{self.value:g} {self.unit}: {self.delta_frac:+.1%} vs "
               f"rolling median {self.baseline:g} "
               f"(threshold ±{self.threshold_frac:.1%}, {good})")
        if self.suspect_region is not None:
            msg += (f"; suspect region {self.suspect_region} "
                    f"({self.suspect_delta_ms:+.3f} ms)")
        return msg


class RegressionDetector:
    """Rolling-median + MAD change detection over a :class:`BenchHistory`.

    Each point is scored against the median of the up-to-``window``
    points since the last accepted level; the threshold is the larger of
    ``mad_scale`` scaled-MADs (``1.4826 * MAD`` estimates sigma for
    normal noise — the floor *learned from the history's own variance*)
    and the ``noise_floor`` relative minimum (timer jitter on a quiet
    series, and the 2-decimal quantization of pre-``raw_value``
    imports). A firing resets the baseline to the new level, so a step
    change fires exactly ONCE instead of once per post-step point, and
    the metric keeps being watched at its new level.
    """

    def __init__(self, window: int = 6, mad_scale: float = 4.0,
                 min_points: int = 3, noise_floor: float = 0.02):
        if window < min_points:
            raise ValueError(f"window {window} < min_points {min_points}")
        self.window = int(window)
        self.mad_scale = float(mad_scale)
        self.min_points = int(min_points)
        self.noise_floor = float(noise_floor)

    def check_series(self, values: Sequence[float], direction: int = -1,
                     two_sided: bool = False
                     ) -> List[Tuple[int, float, float, float]]:
        """``(index, baseline, delta_frac, threshold_frac)`` for every
        firing point. ``two_sided=True`` flags ANY shift beyond the
        threshold regardless of direction (the drift-shift mode)."""
        out = []
        start = 0
        for i in range(len(values)):
            ref = list(values[max(start, i - self.window):i])
            if len(ref) < self.min_points:
                continue
            med = _median(ref)
            if med == 0.0:
                continue
            mad = _median([abs(v - med) for v in ref])
            learned = self.mad_scale * 1.4826 * mad / abs(med)
            thresh = max(learned, self.noise_floor)
            delta = (values[i] - med) / abs(med)
            bad = abs(delta) > thresh if two_sided \
                else direction * delta < -thresh
            if bad:
                out.append((i, med, delta, thresh))
                start = i  # accept the new level; fire once per step
        return out

    def check(self, history: BenchHistory) -> List[Regression]:
        """Typed :class:`Regression` findings over every directional
        metric in the history, with the suspect region attached from an
        :class:`AttributionDiff` when the flagged and a prior record
        both carry per-region attribution."""
        findings = []
        for metric in history.metrics():
            recs = history.series(metric)
            direction = _series_direction(recs)
            if direction == 0:
                continue
            values = [float(r.get("raw_value", r["value"])) for r in recs]
            for i, med, delta, thresh in self.check_series(
                    values, direction=direction):
                suspect = region_delta = None
                after = recs[i].get("attribution")
                before = next((recs[j].get("attribution")
                               for j in range(i - 1, -1, -1)
                               if recs[j].get("attribution")), None)
                if after and before:
                    worst = AttributionDiff(before, after).suspect()
                    if worst is not None:
                        suspect = worst.region
                        region_delta = worst.delta_ms
                findings.append(Regression(
                    metric=metric, index=i, run=recs[i].get("run"),
                    value=values[i], baseline=med, delta_frac=delta,
                    threshold_frac=thresh, unit=str(recs[i]["unit"]),
                    direction=direction, suspect_region=suspect,
                    suspect_delta_ms=region_delta))
        return findings


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        raise ValueError("median of empty sequence")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _series_direction(recs: Sequence[dict]) -> int:
    """A series' good direction from its units (the latest record wins —
    a renamed-unit metric is a renamed metric, see BASELINE.md)."""
    for rec in reversed(recs):
        d = unit_direction(rec.get("unit", ""))
        if d != 0:
            return d
    return 0


# ---------------------------------------------------------------------------
# attribution diffs: name the region that moved
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RegionDelta:
    """One region's ms movement between two attribution reports."""
    region: str
    before_ms: float
    after_ms: float
    delta_ms: float
    delta_frac: Optional[float]  # None when before_ms == 0
    basis: str                   # "measured" | "modeled"


class AttributionDiff:
    """Region-by-region diff of two pyprof attribution reports.

    Accepts :class:`~apex_tpu.pyprof.AttributionReport` objects, their
    ``as_dict()`` forms, or the compact ``[{region, modeled_ms,
    measured_ms}]`` lists a history record carries — duck-typed, so this
    module never imports the jax-backed pyprof package. Per region the
    diff prefers measured ms (present on both sides) and falls back to
    modeled ms; :meth:`suspect` is the region whose time grew the most.
    """

    def __init__(self, before: Any, after: Any):
        b, a = _region_table(before), _region_table(after)
        self.regions: List[RegionDelta] = []
        for name in list(b) + [n for n in a if n not in b]:
            bm, bmod = b.get(name, (None, None))
            am, amod = a.get(name, (None, None))
            if bm is not None and am is not None:
                basis, x, y = "measured", bm, am
            elif bmod is not None and amod is not None:
                basis, x, y = "modeled", bmod, amod
            else:
                continue
            self.regions.append(RegionDelta(
                region=name, before_ms=x, after_ms=y, delta_ms=y - x,
                delta_frac=(y - x) / x if x else None, basis=basis))
        self.regions.sort(key=lambda d: -abs(d.delta_ms))

    def suspect(self) -> Optional[RegionDelta]:
        """The region that got SLOWER the most, or None when nothing
        grew (the regression is outside the attributed step)."""
        grew = [d for d in self.regions if d.delta_ms > 0]
        return max(grew, key=lambda d: d.delta_ms) if grew else None

    def markdown(self) -> str:
        lines = ["| region | before ms | after ms | delta ms | basis |",
                 "|---|---|---|---|---|"]
        for d in self.regions:
            lines.append(f"| {d.region} | {d.before_ms:.4f} | "
                         f"{d.after_ms:.4f} | {d.delta_ms:+.4f} | "
                         f"{d.basis} |")
        return "\n".join(lines)


def _region_table(report: Any) -> Dict[str, Tuple[Optional[float],
                                                  Optional[float]]]:
    """``{region: (measured_ms, modeled_ms)}`` from any report shape."""
    regions = getattr(report, "regions", None)
    if regions is None:
        regions = report.get("regions", []) if isinstance(report, dict) \
            else report
    out: Dict[str, Tuple[Optional[float], Optional[float]]] = {}
    for r in regions or ():
        if isinstance(r, dict):
            name = r.get("region", r.get("name"))
            measured, modeled = r.get("measured_ms"), r.get("modeled_ms")
        else:
            name = getattr(r, "name", None)
            measured = getattr(r, "measured_ms", None)
            modeled = getattr(r, "modeled_ms", None)
        if name is not None:
            out[str(name)] = (
                None if measured is None else float(measured),
                None if modeled is None else float(modeled))
    return out


# ---------------------------------------------------------------------------
# cost-model drift: measured/modeled as a time series
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class DriftShift:
    """A two-sided shift of a metric's measured/modeled ratio — either
    the code got slower against a stable model, or the model stopped
    pricing the program (both block the autotuner trusting it)."""
    metric: str
    index: int
    run: Optional[str]
    ratio: float
    baseline_ratio: float
    delta_frac: float
    threshold_frac: float

    def message(self) -> str:
        return (f"{self.metric}[{self.run or self.index}] model-drift "
                f"ratio {self.ratio:.3f} shifted {self.delta_frac:+.1%} "
                f"vs rolling median {self.baseline_ratio:.3f} "
                f"(threshold ±{self.threshold_frac:.1%})")


def drift_series(history: BenchHistory
                 ) -> Dict[str, List[Tuple[int, Optional[str], float]]]:
    """``{metric: [(index, run, measured/modeled)]}`` for every record
    carrying both a measured step time (``step_time_ms``, the
    ``step_ms`` extra, or the raw value of an ``ms``-unit line) and the
    pyprof ``modeled_step_ms`` roofline. Ratio 1.0 means the model
    prices the program exactly; the ratio's *level* is the systematic
    model gap, its *shifts* are what :func:`detect_drift_shifts`
    alerts on."""
    out: Dict[str, List[Tuple[int, Optional[str], float]]] = {}
    for metric in history.metrics():
        pts = []
        for i, rec in enumerate(history.series(metric)):
            modeled = rec.get("modeled_step_ms")
            measured = rec.get("step_time_ms")
            if measured is None:
                measured = (rec.get("extra") or {}).get("step_ms")
            if measured is None and rec.get("unit") == "ms":
                measured = rec.get("raw_value", rec.get("value"))
            if not modeled or not measured:
                continue
            pts.append((i, rec.get("run"),
                        float(measured) / float(modeled)))
        if pts:
            out[metric] = pts
    return out


def detect_drift_shifts(history: BenchHistory,
                        detector: Optional[RegressionDetector] = None
                        ) -> List[DriftShift]:
    """Two-sided rolling-median + MAD alerts over every drift series.
    Improvements alert too: a ratio suddenly *dropping* means the model
    or the measurement changed, and the autotuner must not silently
    retune against it."""
    det = detector or RegressionDetector()
    findings = []
    for metric, pts in drift_series(history).items():
        ratios = [p[2] for p in pts]
        for i, med, delta, thresh in det.check_series(
                ratios, two_sided=True):
            findings.append(DriftShift(
                metric=metric, index=pts[i][0], run=pts[i][1],
                ratio=ratios[i], baseline_ratio=med, delta_frac=delta,
                threshold_frac=thresh))
    return findings


def publish_drift(history: BenchHistory, registry: Any
                  ) -> Dict[str, float]:
    """Set the latest measured/modeled ratio of every drifting metric as
    a ``perf/model_drift/<metric>`` gauge, plus the single worst ratio
    (largest ``|log ratio|``) as ``perf/model_drift`` — the scalar the
    fleet merge and the autotuner gate watch. Returns the per-metric
    latest ratios."""
    latest: Dict[str, float] = {}
    worst: Optional[float] = None
    for metric, pts in drift_series(history).items():
        ratio = pts[-1][2]
        latest[metric] = ratio
        registry.gauge(f"perf/model_drift/{metric}").set(ratio)
        if ratio > 0 and (worst is None
                          or abs(math.log(ratio)) > abs(math.log(worst))):
            worst = ratio
    if worst is not None:
        registry.gauge("perf/model_drift").set(worst)
    return latest


# ---------------------------------------------------------------------------
# selfcheck: clean history silent, planted regression fires
# ---------------------------------------------------------------------------

# deterministic per-mille wiggle cycle for the synthetic series — well
# inside the detector's noise floor (no Date/random: selfchecks must be
# byte-reproducible)
_WIGGLE = (0.0, 0.002, -0.002, 0.001, -0.001, 0.003, -0.003, 0.002)

_SELFCHECK_REGIONS = (("gpt_embed", 0.4), ("gpt_attention", 3.0),
                      ("gpt_mlp", 2.2), ("gpt_head_loss", 0.9))


def synthetic_history(planted: bool = False,
                      metric: str = "gpt_fast_tokens_per_sec",
                      n: int = 10, drop_frac: float = 0.20
                      ) -> BenchHistory:
    """An in-memory history of ``n`` runs of ``metric`` around a stable
    level (sub-noise-floor wiggle). With ``planted=True`` the LAST run
    drops by ``drop_frac`` and its attribution block shows
    ``gpt_attention`` absorbing the lost time — the detector must name
    both."""
    hist = BenchHistory()
    base_tps, base_step_ms = 100_000.0, 6.5
    for i in range(n):
        wiggle = _WIGGLE[i % len(_WIGGLE)]
        scale = 1.0 + wiggle
        is_drop = planted and i == n - 1
        if is_drop:
            scale = 1.0 - drop_frac
        tps = base_tps * scale
        step_ms = base_step_ms / scale
        lost_ms = step_ms - base_step_ms
        attribution = [
            {"region": name,
             "modeled_ms": ms,
             "measured_ms": round(
                 ms + (lost_ms if name == "gpt_attention" else 0.0), 4)}
            for name, ms in _SELFCHECK_REGIONS]
        hist.record(metric, tps, "tokens/sec", None, run=f"s{i:02d}",
                    source="selfcheck",
                    extras={"modeled_step_ms": base_step_ms,
                            "step_time_ms": round(step_ms, 4),
                            "attribution": attribution},
                    git_sha="selfcheck", host="selfcheck")
    return hist


def selfcheck() -> Tuple[List[Regression], List[Regression]]:
    """``(clean_findings, planted_findings)`` — the PR 11 selfcheck
    convention: the clean synthetic history must stay silent AND the
    planted 20% drop must fire *with its suspect region attributed*; a
    detector that fires without naming the region is reported dead (the
    attribution-diff wiring rotted)."""
    det = RegressionDetector()
    clean_hist = synthetic_history(planted=False)
    clean: List[Regression] = det.check(clean_hist)
    clean_drift = detect_drift_shifts(clean_hist)
    planted = [r for r in det.check(synthetic_history(planted=True))
               if r.suspect_region is not None]
    return clean + list(clean_drift), planted  # type: ignore[operator]


# ---------------------------------------------------------------------------
# markdown report
# ---------------------------------------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values: Sequence[float]) -> str:
    lo, hi = min(values), max(values)
    if hi == lo:
        return _SPARK[3] * len(values)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in values)


def render_report(history: BenchHistory,
                  detector: Optional[RegressionDetector] = None) -> str:
    """The trajectory + drift tables as markdown (``--report``)."""
    det = detector or RegressionDetector()
    regressions = det.check(history)
    shifts = detect_drift_shifts(history, det)
    lines = ["# Performance observatory", "",
             f"{len(history)} record(s), {len(history.metrics())} "
             f"metric(s).", "", "## Trajectory", "",
             "| metric | unit | n | first | last | delta | trend |",
             "|---|---|---|---|---|---|---|"]
    for metric in history.metrics():
        recs = history.series(metric)
        if _series_direction(recs) == 0:
            continue
        vals = [float(r.get("raw_value", r["value"])) for r in recs]
        delta = ((vals[-1] - vals[0]) / abs(vals[0])
                 if vals[0] else float("nan"))
        lines.append(f"| {metric} | {recs[-1]['unit']} | {len(vals)} | "
                     f"{vals[0]:g} | {vals[-1]:g} | {delta:+.1%} | "
                     f"{_sparkline(vals)} |")
    drift = drift_series(history)
    if drift:
        lines += ["", "## Model drift (measured / modeled)", "",
                  "| metric | n | latest ratio | trend |",
                  "|---|---|---|---|"]
        for metric, pts in drift.items():
            ratios = [p[2] for p in pts]
            lines.append(f"| {metric} | {len(ratios)} | "
                         f"{ratios[-1]:.3f} | {_sparkline(ratios)} |")
    lines += ["", "## Findings", ""]
    if not regressions and not shifts:
        lines.append("No regressions, no drift shifts.")
    for r in regressions:
        lines.append(f"- **REGRESSION** {r.message()}")
    for s in shifts:
        lines.append(f"- **DRIFT** {s.message()}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _load_history(args) -> BenchHistory:
    """The history named by ``--history`` when it exists; otherwise an
    in-memory one bootstrapped from the root's ``BENCH_r*.json`` (the
    no-setup path: ``python -m apex_tpu.perfwatch --check`` works on a
    fresh checkout)."""
    path = args.history or os.path.join(args.root, DEFAULT_HISTORY)
    if os.path.exists(path):
        hist = BenchHistory(path)
    else:
        hist = BenchHistory(path if args.import_bench else None)
    if args.import_bench or not hist.records:
        added = hist.import_bench_files(root=args.root)
        if added:
            print(f"perfwatch: imported {added} record(s) from "
                  f"{args.root}/BENCH_r*.json", file=sys.stderr)
    return hist


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apex_tpu.perfwatch",
        description="performance observatory: bench history, regression "
                    "detection, cost-model drift (docs/OBSERVABILITY.md "
                    "'Performance observatory')")
    parser.add_argument("--history", default=None,
                        help=f"JSONL history path (default: "
                             f"<root>/{DEFAULT_HISTORY})")
    parser.add_argument("--root", default=_package_root(),
                        help="repo root holding BENCH_r*.json")
    parser.add_argument("--import-bench", action="store_true",
                        help="one-shot import of BENCH_r*.json into the "
                             "history file (idempotent)")
    parser.add_argument("--check", action="store_true",
                        help="detect regressions + drift shifts "
                             "(default action; exit 1 on findings)")
    parser.add_argument("--report", nargs="?", const="-", default=None,
                        metavar="PATH",
                        help="render the markdown trajectory/drift "
                             "report to PATH (default stdout)")
    parser.add_argument("--selfcheck", action="store_true",
                        help="clean synthetic history must stay silent, "
                             "planted 20%% drop must fire with its "
                             "suspect region")
    parser.add_argument("--window", type=int, default=6)
    parser.add_argument("--noise-floor", type=float, default=0.02)
    args = parser.parse_args(argv)

    if args.selfcheck:
        clean, planted = selfcheck()
        for f in clean:
            print(f"FALSE-POSITIVE {f.message()}")
        if not planted:
            print("perfwatch: planted regression did NOT fire — the "
                  "detector is dead")
        ok = not clean and bool(planted)
        if ok:
            print(f"perfwatch selfcheck ok (clean silent, planted fires "
                  f"{len(planted)} finding(s): "
                  f"{planted[0].message()})")
        return 0 if ok else 1

    try:
        hist = _load_history(args)
    except (OSError, ValueError) as e:
        print(f"perfwatch: {e}", file=sys.stderr)
        return 2

    det = RegressionDetector(window=args.window,
                             noise_floor=args.noise_floor)
    if args.report is not None:
        text = render_report(hist, det)
        if args.report == "-":
            sys.stdout.write(text)
        else:
            with open(args.report, "w") as f:
                f.write(text)
            print(f"perfwatch: report written to {args.report}")
        if not args.check:
            return 0

    regressions = det.check(hist)
    shifts = detect_drift_shifts(hist, det)
    for r in regressions:
        print(f"REGRESSION {r.message()}")
    for s in shifts:
        print(f"DRIFT {s.message()}")
    verdict = "clean" if not (regressions or shifts) else \
        f"{len(regressions)} regression(s), {len(shifts)} drift shift(s)"
    print(f"perfwatch: {len(hist)} record(s), "
          f"{len(hist.metrics())} metric(s) -> {verdict}")
    return 0 if not (regressions or shifts) else 1


if __name__ == "__main__":
    raise SystemExit(main())
