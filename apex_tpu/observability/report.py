"""StepReporter: one snapshot per training step into pluggable sinks.

The reporter is the host-side half of the telemetry loop. Each
:meth:`~StepReporter.report` merges, in one payload:

- the step's in-graph :class:`~apex_tpu.observability.ingraph.Metrics`
  (already mesh-aggregated device scalars — fetched with ONE transfer);
- the host :class:`~apex_tpu.observability.registry.MetricsRegistry`
  snapshot (compile counters, sampled memory gauges, ...);
- per-timer elapsed milliseconds from a ``Timers`` group
  (``time/<name>_ms``), the ``_Timers.write`` role
  (``reference:apex/transformer/pipeline_parallel/_timers.py:66-75``);

and emits it to every sink, together with any captured timer spans.

The module-level default is a :class:`NullReporter`, so library code and
training loops can call ``get_reporter().report(...)`` unconditionally at
zero cost; :func:`attach_reporter` swaps the real one in.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence

from apex_tpu.observability import trace
from apex_tpu.observability.ingraph import Metrics
from apex_tpu.observability.registry import MetricsRegistry, get_registry
from apex_tpu.observability.sinks import Sink

__all__ = ["StepReporter", "NullReporter", "attach_reporter",
           "detach_reporter", "get_reporter"]


class StepReporter:
    """Snapshot registry + timers + in-graph metrics into sinks.

    ``interval`` reports every Nth step (others are dropped without
    fetching, so a tight loop can call ``report`` every step and pay the
    device transfer only when something is emitted). ``capture_spans``
    turns on ``Timer`` span capture for the reporter's lifetime so a
    :class:`~apex_tpu.observability.sinks.ChromeTraceSink` sees them.
    """

    def __init__(self, sinks: Sequence[Sink],
                 registry: Optional[MetricsRegistry] = None,
                 timers=None, interval: int = 1,
                 capture_spans: bool = False):
        if interval < 1:
            raise ValueError("interval must be >= 1")
        self.sinks = list(sinks)
        self.registry = registry if registry is not None else get_registry()
        self.timers = timers
        self.interval = interval
        self._capture_spans = capture_spans
        if capture_spans:
            trace.enable_spans()

    def _timer_payload(self, reset: bool) -> Dict[str, float]:
        if self.timers is None:
            return {}
        out = {}
        for name, t in self.timers.timers.items():
            if t.started_:  # snapshot mid-flight without perturbing it
                continue
            out[f"time/{name}_ms"] = t.elapsed(reset=reset) * 1e3
        return out

    def report(self, step: int, metrics: Optional[Metrics] = None,
               extra: Optional[Dict[str, float]] = None,
               reset_timers: bool = True) -> Optional[Dict[str, float]]:
        """Emit one payload; returns it (None on off-interval steps).

        ``metrics`` is the step's in-graph pytree (or a plain dict of
        device/host scalars); ``extra`` merges host-side one-offs (e.g.
        the loss you already fetched for logging).
        """
        if step % self.interval:
            return None
        payload: Dict[str, float] = {}
        if metrics is not None:
            if isinstance(metrics, Metrics):
                payload.update(metrics.as_floats())
            else:
                payload.update({k: float(v) for k, v in metrics.items()})
        payload.update(self.registry.snapshot())
        payload.update(self._timer_payload(reset=reset_timers))
        if extra:
            payload.update({k: float(v) for k, v in extra.items()})
        spans = trace.drain_spans() if trace.spans_enabled() else []
        for sink in self.sinks:
            sink.emit(step, payload, spans)
        return payload

    def close(self) -> None:
        if self._capture_spans:
            trace.disable_spans()
        for sink in self.sinks:
            sink.close()
        # a closed reporter must not stay the process default: later
        # get_reporter().report(...) calls would write to closed sinks
        if _ACTIVE is self:
            detach_reporter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NullReporter:
    """The module-level no-op default: accepts the full reporter surface,
    does nothing, costs a method call."""

    sinks: tuple = ()
    interval = 1

    def report(self, step, metrics=None, extra=None, reset_timers=True):
        return None

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def __bool__(self):
        return False  # `if get_reporter():` reads naturally


_NULL = NullReporter()
_ACTIVE = _NULL


def get_reporter():
    """The attached reporter, or the no-op default."""
    return _ACTIVE


def attach_reporter(reporter: StepReporter):
    """Install ``reporter`` as the process-wide default; returns it so
    ``with attach_reporter(StepReporter(...)):`` works."""
    global _ACTIVE
    _ACTIVE = reporter
    return reporter


def detach_reporter() -> None:
    global _ACTIVE
    _ACTIVE = _NULL
