"""StepReporter: one snapshot per training step into pluggable sinks.

The reporter is the host-side half of the telemetry loop. Each
:meth:`~StepReporter.report` merges, in one payload:

- the step's in-graph :class:`~apex_tpu.observability.ingraph.Metrics`
  (already mesh-aggregated device scalars — fetched with ONE transfer);
- the host :class:`~apex_tpu.observability.registry.MetricsRegistry`
  snapshot (compile counters, sampled memory gauges, ...);
- per-timer elapsed milliseconds from a ``Timers`` group
  (``time/<name>_ms``), the ``_Timers.write`` role
  (``reference:apex/transformer/pipeline_parallel/_timers.py:66-75``);

and emits it to every sink, together with any captured timer spans.

The module-level default is a :class:`NullReporter`, so library code and
training loops can call ``get_reporter().report(...)`` unconditionally at
zero cost; :func:`attach_reporter` swaps the real one in.
"""

from __future__ import annotations

import math
import time
from typing import Callable, Dict, Iterable, Optional, Sequence

from apex_tpu.observability import trace
from apex_tpu.observability.ingraph import Metrics
from apex_tpu.observability.registry import MetricsRegistry, get_registry
from apex_tpu.observability.sinks import Sink

__all__ = ["StepReporter", "NullReporter", "attach_reporter",
           "detach_reporter", "get_reporter"]


class StepReporter:
    """Snapshot registry + timers + in-graph metrics into sinks.

    ``interval`` reports every Nth step (others are dropped without
    fetching, so a tight loop can call ``report`` every step and pay the
    device transfer only when something is emitted). ``capture_spans``
    turns on ``Timer`` span capture for the reporter's lifetime so a
    :class:`~apex_tpu.observability.sinks.ChromeTraceSink` sees them.

    ``hooks`` are host callbacks ``hook(step, payload)`` run after the
    sinks emit — the attachment point for reactive policies like the
    numerics watchdog (:meth:`HealthConfig.reporter_hook
    <apex_tpu.observability.health.HealthConfig.reporter_hook>`); a hook
    that raises (``on_nonfinite="raise"``) does so *after* the failing
    step reached every sink. Hooks also run on OFF-interval steps
    whenever ``metrics`` were passed (with just the in-graph payload —
    no registry/timer merge, no sink emission): a watchdog that only saw
    every Nth step would miss the transient non-finite excursion it
    exists to catch. The per-step metrics fetch this implies is the
    price of a watchdog; without hooks, off-interval steps stay
    fetch-free as before.

    :meth:`attach_flops_budget` (or the ``flops_per_step`` ctor arg) turns
    on a ``perf/mfu`` gauge: model-flops-utilization computed from the
    wall time between consecutive reports, against
    :func:`~apex_tpu.observability.costs.peak_flops` by default.

    :meth:`attach_memory_budget` sets the ``mem/*`` gauge family
    (``mem/peak_hbm_bytes``, ``mem/temp_bytes``, ...) from the compiled
    step's :func:`~apex_tpu.observability.costs.memory_budget` — static
    per executable, so attach once after AOT compile and every snapshot
    carries the step's HBM plan next to its live metrics (the accounting
    that makes an activation-remat policy choice measurable).
    """

    def __init__(self, sinks: Sequence[Sink],
                 registry: Optional[MetricsRegistry] = None,
                 timers=None, interval: int = 1,
                 capture_spans: bool = False,
                 hooks: Sequence[Callable[[int, Dict[str, float]], None]]
                 = (),
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        if interval < 1:
            raise ValueError("interval must be >= 1")
        self.sinks = list(sinks)
        self.registry = registry if registry is not None else get_registry()
        self.timers = timers
        self.interval = interval
        self.hooks = list(hooks)
        self._capture_spans = capture_spans
        self._flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        self._last_report: Optional[tuple] = None  # (step, perf_counter)
        if flops_per_step is not None:
            self.attach_flops_budget(flops_per_step, peak_flops)
        if capture_spans:
            trace.enable_spans()

    def attach_flops_budget(self, flops_per_step: float,
                            peak: Optional[float] = None) -> "StepReporter":
        """Enable the ``perf/mfu`` gauge: ``flops_per_step`` is the
        per-step model FLOPs (e.g. :func:`~apex_tpu.observability.costs.
        flops_budget` of the compiled step, or an analytic count);
        ``peak`` defaults to the first device's
        :func:`~apex_tpu.observability.costs.peak_flops`. Returns self
        for chaining."""
        from apex_tpu.observability.costs import peak_flops as _peak
        flops = float(flops_per_step)
        peak = float(peak) if peak is not None else _peak()
        # fail at configuration time, not as a ZeroDivisionError inside
        # report() mid-training
        if flops <= 0.0 or peak <= 0.0:
            raise ValueError("flops_per_step and peak must be positive, "
                             f"got {flops} and {peak}")
        self._flops_per_step = flops
        self._peak_flops = peak
        return self

    def attach_memory_budget(self, budget) -> "StepReporter":
        """Set the ``mem/*`` gauges from ``budget`` — either the dict
        returned by :func:`~apex_tpu.observability.costs.memory_budget`
        or a compiled executable to extract it from. A backend without
        memory analysis (``memory_budget(...) is None``) leaves the
        gauges unset rather than reporting zeros. Returns self for
        chaining."""
        if budget is not None and not isinstance(budget, dict):
            from apex_tpu.observability.costs import memory_budget
            budget = memory_budget(budget)
        if budget is None:
            return self
        reg = self.registry
        reg.gauge("mem/peak_hbm_bytes").set(budget["peak_hbm_bytes"])
        reg.gauge("mem/temp_bytes").set(budget["temp_bytes"])
        reg.gauge("mem/argument_bytes").set(budget["argument_bytes"])
        reg.gauge("mem/output_bytes").set(budget["output_bytes"])
        reg.gauge("mem/host_temp_bytes").set(budget["host_temp_bytes"])
        return self

    def attach_attribution(self, report) -> "StepReporter":
        """Set the ``perf/*`` attribution gauges from an
        :class:`~apex_tpu.pyprof._attribute.AttributionReport` —
        ``perf/modeled_step_ms`` (the roofline lower bound of the step),
        ``perf/comm_exposed_ms`` (modeled communication the measured step
        failed to hide under compute) and ``perf/overlap_efficiency``
        (share of modeled comm successfully hidden, unset on comm-free
        programs). Like the memory budget these are per-compile
        constants: attach once after AOT compile + a measured step and
        every snapshot carries the step's attribution next to its live
        metrics. Returns self for chaining."""
        reg = self.registry
        reg.gauge("perf/modeled_step_ms").set(report.modeled_step_ms)
        if report.comm_exposed_ms is not None:
            reg.gauge("perf/comm_exposed_ms").set(report.comm_exposed_ms)
        if report.overlap_efficiency is not None:
            reg.gauge("perf/overlap_efficiency").set(
                report.overlap_efficiency)
        return self

    def _update_mfu(self, step: int) -> None:
        """Set the perf/mfu gauge from the wall time since the previous
        report; it reaches the payload through the registry snapshot."""
        if self._flops_per_step is None:
            return
        now = time.perf_counter()
        prev, self._last_report = self._last_report, (step, now)
        if prev is None:
            return
        d_steps, dt = step - prev[0], now - prev[1]
        if d_steps <= 0 or dt <= 0.0:
            return
        from apex_tpu.observability.costs import mfu
        value = mfu(self._flops_per_step * d_steps, dt, self._peak_flops)
        # a ~0 wall delta (fast host, two reports inside one perf_counter
        # tick) yields NaN/inf — leave the gauge unset for this report
        # rather than emitting a fabricated utilization
        if math.isfinite(value):
            self.registry.gauge("perf/mfu").set(value)

    @staticmethod
    def _metrics_payload(metrics) -> Dict[str, float]:
        """One device transfer for the step's in-graph metrics."""
        if isinstance(metrics, Metrics):
            return metrics.as_floats()
        return {k: float(v) for k, v in metrics.items()}

    def _timer_payload(self, reset: bool) -> Dict[str, float]:
        if self.timers is None:
            return {}
        out = {}
        for name, t in self.timers.timers.items():
            if t.started_:  # snapshot mid-flight without perturbing it
                continue
            out[f"time/{name}_ms"] = t.elapsed(reset=reset) * 1e3
        return out

    def report(self, step: int, metrics: Optional[Metrics] = None,
               extra: Optional[Dict[str, float]] = None,
               reset_timers: bool = True) -> Optional[Dict[str, float]]:
        """Emit one payload; returns it (None on off-interval steps).

        ``metrics`` is the step's in-graph pytree (or a plain dict of
        device/host scalars); ``extra`` merges host-side one-offs (e.g.
        the loss you already fetched for logging).
        """
        if step % self.interval:
            # hooks still inspect every step that carries metrics: a
            # reactive policy (health watchdog) must not miss a
            # transient non-finite step just because the sinks sample
            if self.hooks and metrics is not None:
                payload = self._metrics_payload(metrics)
                for hook in self.hooks:
                    hook(step, payload)
            return None
        payload: Dict[str, float] = {}
        if metrics is not None:
            payload.update(self._metrics_payload(metrics))
        self._update_mfu(step)
        payload.update(self.registry.snapshot())
        payload.update(self._timer_payload(reset=reset_timers))
        if extra:
            payload.update({k: float(v) for k, v in extra.items()})
        spans = trace.drain_spans() if trace.spans_enabled() else []
        for sink in self.sinks:
            sink.emit(step, payload, spans)
        # hooks run AFTER the sinks so a raising policy (e.g. the health
        # monitor's on_nonfinite="raise") never loses the failing step
        for hook in self.hooks:
            hook(step, payload)
        return payload

    def close(self) -> None:
        if self._capture_spans:
            trace.disable_spans()
        for sink in self.sinks:
            sink.close()
        # a closed reporter must not stay the process default: later
        # get_reporter().report(...) calls would write to closed sinks
        if _ACTIVE is self:
            detach_reporter()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class NullReporter:
    """The module-level no-op default: accepts the full reporter surface,
    does nothing, costs a method call."""

    sinks: tuple = ()
    interval = 1

    def report(self, step, metrics=None, extra=None, reset_timers=True):
        return None

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    def __bool__(self):
        return False  # `if get_reporter():` reads naturally


_NULL = NullReporter()
_ACTIVE = _NULL


def get_reporter():
    """The attached reporter, or the no-op default."""
    return _ACTIVE


def attach_reporter(reporter: StepReporter):
    """Install ``reporter`` as the process-wide default; returns it so
    ``with attach_reporter(StepReporter(...)):`` works."""
    global _ACTIVE
    _ACTIVE = reporter
    return reporter


def detach_reporter() -> None:
    global _ACTIVE
    _ACTIVE = _NULL
