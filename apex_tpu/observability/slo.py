"""Serving SLO tracking: declarative latency targets, rolling goodput,
burn rate, and a flight-recorder dump on violation.

An aggregate throughput number cannot answer the production question
"what fraction of traffic met its latency target this window"; goodput
can, and it is the quantity the ROADMAP's serving items are actually
optimizing. Three pieces, all riding the existing telemetry spine:

- :class:`SLOTarget` — one declarative target, e.g. *TTFT p95 <= 200 ms*
  (``metric`` is one of the request-record latency fields, ``quantile``
  defines both the percentile readout to police and the implied error
  budget ``1 - q/100``);
- :class:`SLOTracker` — the rolling evaluator:
  :meth:`~SLOTracker.observe` ingests each retired
  :class:`~apex_tpu.observability.reqtrace.RequestRecord` (the
  :class:`~apex_tpu.serving.scheduler.SlotScheduler` calls it when wired
  via ``slo=``), keeps per-target value windows, and maintains the
  ``slo/*`` host-registry gauges — goodput (fraction of windowed
  requests meeting ALL targets), burn rate (violation fraction over the
  error budget: 1.0 = burning exactly the budget, >1 = on track to miss
  the SLO), and a 0/1 ``violating`` flag (any target's window percentile
  over its threshold);
- the **reporter hook** — the tracker is itself a
  ``StepReporter(hooks=[...])`` callable, the same attachment point as
  PR 3's :class:`~apex_tpu.observability.health.HealthMonitor`: on a
  violating report (after ``consecutive`` violating reports in a row) it
  writes a flight-recorder
  :class:`~apex_tpu.observability.health.CrashDump` whose ``requests``
  field carries the last-N request records from the attached
  :class:`~apex_tpu.observability.reqtrace.RequestTrace` — the
  post-mortem shows WHICH requests blew the target and where their time
  went, not just that a percentile moved. ``on_violation="raise"``
  additionally raises :class:`SLOViolationError`.

Everything here is host-side arithmetic over already-collected
timestamps: attaching a tracker adds zero device work to the serving
loop (the zero-cost contract ``tests/test_reqtrace.py`` asserts).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from apex_tpu.observability.health import CrashDump
from apex_tpu.observability.registry import get_registry
from apex_tpu.observability.reqtrace import RequestRecord, RequestTrace

__all__ = ["SLOTarget", "SLOTracker", "SLOViolationError",
           "LATENCY_METRICS", "ON_VIOLATION", "FAILED_REASONS"]

LATENCY_METRICS = ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms")
ON_VIOLATION = ("skip", "dump", "raise")

# finish reasons that are SERVER-side failures: such a retirement counts
# against goodput unconditionally, whatever its (often absent) latency
# fields say. Without this, a request expired while QUEUED — ttft/tpot
# never measured, e2e tiny — would sail past every latency target and
# read as served-well at exactly the moment the server is shedding its
# queue; "cancelled" stays metrics-based (a user disconnect is not the
# server failing).
FAILED_REASONS = ("expired", "poisoned", "error")


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One latency objective: ``metric``'s p-``quantile`` must stay at or
    under ``threshold_ms``. The quantile also defines the error budget —
    *p95 <= X* tolerates 5% of requests over X; the per-target burn rate
    is the observed over-threshold fraction divided by that budget."""

    metric: str
    quantile: float
    threshold_ms: float

    def __post_init__(self):
        if self.metric not in LATENCY_METRICS:
            raise ValueError(f"metric must be one of {LATENCY_METRICS}, "
                             f"got {self.metric!r}")
        if not 0.0 < self.quantile < 100.0:
            raise ValueError("quantile must be in (0, 100), "
                             f"got {self.quantile!r}")
        if self.threshold_ms <= 0.0:
            raise ValueError("threshold_ms must be positive, "
                             f"got {self.threshold_ms!r}")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.quantile / 100.0

    def describe(self) -> str:
        return f"{self.metric} p{self.quantile:g} <= {self.threshold_ms:g}ms"


class SLOViolationError(RuntimeError):
    """An SLO target's window percentile exceeded its threshold and the
    tracker's policy said ``on_violation="raise"``. Carries the
    flight-recorder :class:`CrashDump` and the path it was written to."""

    def __init__(self, message: str, dump: CrashDump,
                 dump_path: Optional[str] = None):
        super().__init__(message)
        self.dump = dump
        self.dump_path = dump_path


class SLOTracker:
    """See module docstring.

    Args:
      targets: the declarative :class:`SLOTarget` list (at least one).
      window: rolling window size in *requests* — goodput, burn rate and
        the percentile checks all read the last ``window`` retirements.
      registry: host :class:`MetricsRegistry` for the ``slo/*`` family
        (the process default when None).
      trace: the :class:`RequestTrace` flight-recorder source; when
        attached, violation dumps carry its last ``flight_n`` records.
      on_violation: the reporter-hook reaction — ``"skip"`` keeps the
        gauges only, ``"dump"`` writes the flight-recorder dump,
        ``"raise"`` dumps then raises :class:`SLOViolationError`.
      dump_dir: where ``slo_dump_step<N>.json`` files land.
      flight_n: how many trailing request records a dump carries.
      consecutive: violating *reports* in a row before the hook fires
        (a clean report resets the streak) — one hot request in a small
        window should not page anyone; same knob as the health monitor.
    """

    def __init__(self, targets: Sequence[SLOTarget], *, window: int = 512,
                 registry=None, trace: Optional[RequestTrace] = None,
                 on_violation: str = "dump", dump_dir: str = ".",
                 flight_n: int = 64, consecutive: int = 1):
        targets = tuple(targets)
        if not targets:
            raise ValueError("need at least one SLOTarget")
        if on_violation not in ON_VIOLATION:
            raise ValueError(f"on_violation must be one of {ON_VIOLATION}, "
                             f"got {on_violation!r}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if consecutive < 1:
            raise ValueError(f"consecutive must be >= 1, got {consecutive}")
        self.targets = targets
        self.window = int(window)
        self.trace = trace
        self.on_violation = on_violation
        self.dump_dir = dump_dir
        self.flight_n = int(flight_n)
        self.consecutive = int(consecutive)
        self._reg = registry if registry is not None else get_registry()
        # rolling windows with INCREMENTAL counters: observe() sits on
        # the scheduler's retirement path, so every readout it refreshes
        # must be O(targets), not an O(window) rescan (eviction is
        # handled explicitly — a maxlen deque would drop samples without
        # letting the counters follow)
        self._vals = [collections.deque() for _ in targets]
        self._over = [0 for _ in targets]
        self._good: collections.deque = collections.deque()
        self._good_count = 0
        self.dumps: List[str] = []
        self.streak = 0
        self._last_dump: Optional[CrashDump] = None

    # -- ingestion ----------------------------------------------------------

    def observe(self, record: RequestRecord) -> None:
        """Ingest one retired request: window updates + ``slo/*`` gauges,
        O(targets) per call (counters maintained incrementally). A
        latency a request does not define (``tpot_ms`` on a one-token
        request) neither counts for nor against its targets — but a
        server-side failure retirement (:data:`FAILED_REASONS`) is
        counted against goodput unconditionally, defined latencies or
        not."""
        good = record.finish_reason not in FAILED_REASONS
        for i, target in enumerate(self.targets):
            v = getattr(record, target.metric)
            if v is None:
                continue
            vals = self._vals[i]
            if len(vals) >= self.window:
                if vals.popleft() > target.threshold_ms:
                    self._over[i] -= 1
            vals.append(float(v))
            if v > target.threshold_ms:
                self._over[i] += 1
                good = False
        if len(self._good) >= self.window:
            self._good_count -= self._good.popleft()
        self._good.append(good)
        self._good_count += good
        self._update_gauges()

    # -- rolling readouts ---------------------------------------------------

    def goodput(self) -> float:
        """Fraction of windowed requests that met EVERY target's
        threshold AND did not retire by a server-side failure
        (:data:`FAILED_REASONS` — expired/poisoned/error). NaN before
        the first retirement."""
        if not self._good:
            return float("nan")
        return self._good_count / len(self._good)

    def burn_rate(self, target: SLOTarget) -> float:
        """Observed over-threshold fraction over the target's error
        budget: 1.0 burns exactly the budget the quantile allows, >1 is
        on track to violate (the SRE burn-rate convention). NaN with no
        samples."""
        i = self.targets.index(target)
        if not self._vals[i]:
            return float("nan")
        return (self._over[i] / len(self._vals[i])) / target.error_budget

    def max_burn_rate(self) -> float:
        """Worst burn rate across targets (NaN with no samples anywhere)
        — the single number the serving brownout policy
        (:class:`~apex_tpu.serving.resilience.BrownoutPolicy`) and the
        ``slo/burn_rate`` gauge summarize the tracker to."""
        burns = [b for t in self.targets
                 if (b := self.burn_rate(t)) == b]
        return max(burns) if burns else float("nan")

    def window_percentile(self, target: SLOTarget) -> float:
        """The target metric's p-``quantile`` over the rolling window —
        exact ``np.percentile`` over the retained samples, computed on
        demand (violation messages, debugging), NOT on the per-
        retirement path."""
        i = self.targets.index(target)
        vals = self._vals[i]
        if not vals:
            return float("nan")
        return float(np.percentile(np.asarray(vals), target.quantile))

    def violating_targets(self) -> List[SLOTarget]:
        """Targets currently violating: the windowed over-threshold
        fraction exceeds the error budget — the exceedance-rate
        statement of "the window's p-quantile sits above the threshold"
        (identical up to interpolation convention), evaluated from the
        incremental counters in O(targets)."""
        return [t for t in self.targets
                if self.burn_rate(t) > 1.0]  # NaN-safe: NaN > 1 is False

    def _update_gauges(self) -> None:
        reg = self._reg
        reg.gauge("slo/goodput").set(self.goodput())
        burn = self.max_burn_rate()
        if burn == burn:  # skip the NaN empty-window readout
            reg.gauge("slo/burn_rate").set(burn)
        reg.gauge("slo/violating").set(
            1.0 if self.violating_targets() else 0.0)
        reg.gauge("slo/window_requests").set(float(len(self._good)))

    # -- the flight recorder ------------------------------------------------

    def flight_dump(self, step: int = 0,
                    payload: Optional[Dict[str, float]] = None) -> str:
        """Write the flight-recorder dump NOW (also callable from an
        except block around the serving loop — the "or crash" half of the
        contract): a strict-JSON :class:`CrashDump` whose ``requests``
        field holds the last ``flight_n`` request records. Returns the
        written path."""
        records = self.trace.last(self.flight_n) if self.trace else []
        dump = CrashDump.from_payload(
            step, payload if payload is not None else {},
            requests=[r.to_dict() for r in records])
        dump.config = {
            "targets": [t.describe() for t in self.targets],
            "window": self.window, "on_violation": self.on_violation,
            "flight_n": self.flight_n, "consecutive": self.consecutive,
        }
        path = dump.write(self.dump_dir, prefix="slo_dump")
        self.dumps.append(path)
        self._last_dump = dump
        return path

    # -- the StepReporter hook ----------------------------------------------

    def __call__(self, step: int, payload: Dict[str, float]) -> None:
        """``StepReporter(hooks=[tracker])`` — evaluated once per
        reported payload, after the sinks emitted (the stream always
        carries the violating window's gauges)."""
        if self.on_violation == "skip":
            return
        violating = self.violating_targets()
        if not violating:
            self.streak = 0
            return
        self.streak += 1
        if self.streak < self.consecutive:
            return
        self._reg.counter("slo/violations").inc()
        path = self.flight_dump(step, payload)
        if self.on_violation == "raise":
            desc = "; ".join(
                f"{t.describe()} (p{t.quantile:g}="
                f"{self.window_percentile(t):.1f}ms)" for t in violating)
            raise SLOViolationError(
                f"SLO violated at step {step}: {desc}; flight recorder: "
                f"{path}", self._last_dump, dump_path=path)

    def reporter_hook(self) -> "SLOTracker":
        """Symmetry with ``HealthConfig.reporter_hook()`` — the tracker
        IS the hook."""
        return self
