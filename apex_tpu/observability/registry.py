"""Host-side metrics registry: counters, gauges, fixed-bucket histograms.

These are plain Python accumulators for *host-observed* quantities —
compile counts, queue depths, sampled device memory — the complement of the
in-graph accumulators (:mod:`~apex_tpu.observability.ingraph`) that live
inside the traced step. A :class:`MetricsRegistry` is a named collection
whose :meth:`~MetricsRegistry.snapshot` flattens everything to
``{name: float}`` for the sinks; the module-level default registry
(:func:`get_registry`) is what the pre-wired runtime listeners write to.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "DEFAULT_BUCKETS", "log_buckets",
           "json_safe_float", "json_float"]

# power-of-4 spread from sub-millisecond to minutes — wide enough for both
# durations (seconds) and sizes (use explicit buckets for bytes)
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(4.0 ** e for e in range(-6, 6))


def log_buckets(lo: float, hi: float, n: int) -> Tuple[float, ...]:
    """``n`` log-spaced bucket bounds from ``lo`` to ``hi`` inclusive —
    the right grid for latency histograms, whose interesting quantiles
    (p50 vs p99) live decades apart.

    Adjacent bounds keep a constant ratio ``r = (hi/lo)**(1/(n-1))``,
    which is also the percentile resolution: :meth:`Histogram.percentile`
    interpolates inside one bucket, so its error is bounded by that
    bucket's width — relative error at most ``r - 1`` (documented with
    worked numbers in docs/OBSERVABILITY.md "Serving latency & SLO").
    """
    if not (0.0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi, got lo={lo}, hi={hi}")
    if n < 2:
        raise ValueError(f"need at least 2 bounds, got n={n}")
    ratio = (hi / lo) ** (1.0 / (n - 1))
    return tuple(lo * ratio ** i for i in range(n))


class Metric:
    """Base: a named observable. ``observe`` is the uniform write API so
    call sites can hold any metric kind."""

    def __init__(self, name: str):
        self.name = name

    def observe(self, value: float) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def snapshot(self) -> Dict[str, float]:  # pragma: no cover - abstract
        raise NotImplementedError

    def reset(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(Metric):
    """Monotonic accumulator. ``observe(v)`` adds ``v`` (default usage is
    :meth:`inc`)."""

    def __init__(self, name: str):
        super().__init__(name)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    observe = inc

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict[str, float]:
        return {self.name: self._value}

    def reset(self) -> None:
        self._value = 0.0


class Gauge(Metric):
    """Last-value metric. ``observe``/``set`` overwrite.

    "Never set" is tracked with an explicit flag, NOT a NaN sentinel: the
    health watchdog legitimately reports NaN-valued gauges (a NaN abs-max
    IS the signal), and a sentinel would silently swallow them. ``value``
    still reads NaN when unset, so numeric consumers need no branch; the
    registry snapshot skips unset gauges via :attr:`is_set`.
    """

    def __init__(self, name: str):
        super().__init__(name)
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    observe = set

    @property
    def is_set(self) -> bool:
        return self._value is not None

    @property
    def value(self) -> float:
        return math.nan if self._value is None else self._value

    def snapshot(self) -> Dict[str, float]:
        return {self.name: self.value}

    def reset(self) -> None:
        self._value = None


class Histogram(Metric):
    """Fixed-bucket histogram (Prometheus-style cumulative ``le`` buckets).

    ``observe(v)`` increments the first bucket whose upper bound admits
    ``v``; the snapshot carries per-bucket counts plus ``_count``/``_sum``
    so sinks can derive means without keeping samples.
    """

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # +1 = overflow (+inf)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, value: float) -> None:
        # write order matters for lock-free readers: total count (and
        # sum/extrema) move BEFORE the bucket count, so a concurrent
        # bucket walk that then reads ``count`` (render_prometheus's
        # order) always sees count >= running bucket sum — the scraped
        # histogram stays monotone with le="+Inf" as the ceiling. The
        # residual tear is benign: percentile() may transiently see a
        # count one past the bucket sum and falls through to the
        # observed max.
        value = float(value)
        self._sum += value
        self._count += 1
        if value < self._min:
            self._min = value
        if value > self._max:
            self._max = value
        self._counts[bisect.bisect_left(self.bounds, value)] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def _order_stat(self, k: int) -> float:
        """Bucket estimate of the k-th order statistic (1-indexed).
        Exact at the ends (the observed min/max are tracked); interior
        ranks spread uniformly inside their bucket, clamped to the
        observed range — so the estimate never leaves the true value's
        bucket."""
        if k <= 1:
            return self._min
        if k >= self._count:
            return self._max
        running = 0
        lo = -math.inf
        bounds = (*self.bounds, math.inf)  # +inf = the overflow bucket
        for bound, c in zip(bounds, self._counts):
            if c and running + c >= k:
                b_lo = max(lo, self._min)
                b_hi = min(bound, self._max)
                est = b_lo + (b_hi - b_lo) * ((k - running) / c)
                return min(max(est, self._min), self._max)
            running += c
            lo = bound
        # reachable only on a torn lock-free read (count incremented
        # before its bucket): the observed max is the honest answer
        return self._max

    def percentile(self, q: float) -> float:
        """The ``q``-th percentile (``q`` in [0, 100]) in numpy's
        linear-interpolation convention — the fractional rank
        ``1 + q/100·(count−1)`` interpolated between the two adjacent
        order statistics, each estimated from its bucket
        (:meth:`_order_stat`) — so small windows (a handful of requests)
        agree with ``np.percentile`` up to bucket resolution instead of
        drifting to a different rank convention.

        Each order-statistic estimate stays inside the true value's
        bucket and is clamped to the tracked observed ``[min, max]``
        (q=0/q=100 are exact; the overflow bucket reads the observed
        maximum instead of fabricating +inf), so the error is bounded by
        one bucket's width at each endpoint: with :func:`log_buckets`'
        constant-ratio grid the *relative* error vs ``np.percentile`` is
        at most ``r - 1`` where ``r = (hi/lo)**(1/(n-1))`` for in-grid
        samples — the resolution/emission-size trade-off, documented in
        docs/OBSERVABILITY.md. Returns NaN on an empty histogram.
        """
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"q must be in [0, 100], got {q}")
        if self._count == 0:
            return math.nan
        pos = 1.0 + (q / 100.0) * (self._count - 1)
        k = int(math.floor(pos))
        frac = pos - k
        x_k = self._order_stat(k)
        if frac <= 0.0 or k >= self._count:
            return x_k
        return x_k + frac * (self._order_stat(k + 1) - x_k)

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative counts, honoring the Prometheus ``le`` contract:
        ``..._bucket_le_B`` is the number of samples ``<= B``, and
        ``le_inf`` equals ``count``."""
        out = {}
        running = 0
        for bound, c in zip(self.bounds, self._counts):
            running += c
            out[f"{self.name}_bucket_le_{bound:g}"] = running
        out[f"{self.name}_bucket_le_inf"] = running + self._counts[-1]
        return out

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {f"{self.name}_count": float(self._count),
                                 f"{self.name}_sum": self._sum}
        out.update({k: float(v) for k, v in self.bucket_counts().items()})
        return out

    def reset(self) -> None:
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf


class MetricsRegistry:
    """Named collection with get-or-create accessors.

    Re-requesting a name returns the existing metric; requesting it as a
    different kind raises — a name means one thing for the whole run.
    """

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, kind, factory) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name))

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram,
                                   lambda: Histogram(name, buckets))

    def names(self) -> Iterable[str]:
        return tuple(self._metrics)

    def snapshot(self) -> Dict[str, float]:
        """Flat ``{name: value}`` over every registered metric; gauges
        that were never set are skipped so sinks don't emit noise (a gauge
        explicitly set to NaN IS emitted — see :class:`Gauge`)."""
        out: Dict[str, float] = {}
        for m in self._metrics.values():
            if isinstance(m, Gauge) and not m.is_set:
                continue
            out.update(m.snapshot())
        return out

    def reset(self) -> None:
        for m in self._metrics.values():
            m.reset()

    # -- typed serialization (the fleet snapshot format) -------------------
    def to_dict(self) -> dict:
        """A TYPED, strict-JSON-safe dict of the whole registry — unlike
        :meth:`snapshot` (flat floats, which cannot be merged: a
        histogram's bucket counts and a gauge's last value need different
        merge rules), this carries each metric's kind and full state, so
        another process can rebuild (:meth:`from_dict`) or merge
        (:func:`~apex_tpu.observability.fleet.merge_registry_dicts`) it.
        Non-finite values use the string spellings ``"NaN"`` /
        ``"Infinity"`` / ``"-Infinity"`` (strict-JSON contract, same as
        the crash dumps); never-set gauges are skipped (same contract as
        :meth:`snapshot`)."""
        with self._lock:
            metrics = list(self._metrics.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in metrics:
            if isinstance(m, Counter):
                out["counters"][name] = json_safe_float(m.value)
            elif isinstance(m, Gauge):
                if m.is_set:
                    out["gauges"][name] = json_safe_float(m.value)
            elif isinstance(m, Histogram):
                out["histograms"][name] = {
                    "bounds": list(m.bounds),
                    "counts": list(m._counts),
                    "sum": json_safe_float(m._sum),
                    "count": int(m._count),
                    "min": json_safe_float(m._min),
                    "max": json_safe_float(m._max),
                }
        return out

    @classmethod
    def from_dict(cls, doc: dict) -> "MetricsRegistry":
        """Rebuild a registry from :meth:`to_dict` output. Histograms
        restore their per-bucket counts AND the observed min/max/sum, so
        :meth:`Histogram.percentile` answers the same after a round-trip
        (asserted in ``tests/test_fleet.py``)."""
        reg = cls()
        for name, value in doc.get("counters", {}).items():
            reg.counter(name).inc(json_float(value))
        for name, value in doc.get("gauges", {}).items():
            reg.gauge(name).set(json_float(value))
        for name, h in doc.get("histograms", {}).items():
            hist = reg.histogram(name, h["bounds"])
            _restore_histogram(hist, h)
        return reg

    def render_prometheus(self) -> str:
        """The registry in Prometheus text exposition format, so a host
        process can serve the snapshot on a ``/metrics`` endpoint and be
        scraped without any new dependency.

        Slashes (and anything else outside ``[a-zA-Z0-9_:]``) in metric
        names become underscores (``serve/ttft_ms`` →
        ``serve_ttft_ms``); histograms emit the standard cumulative
        ``_bucket{le="..."}`` series ending in ``le="+Inf"`` plus
        ``_sum``/``_count``; never-set gauges are skipped (same contract
        as :meth:`snapshot`), and non-finite values use the spellings
        Prometheus' parser accepts (``NaN``/``+Inf``/``-Inf``).
        """
        with self._lock:
            metrics = list(self._metrics.items())
        lines: List[str] = []
        for name, m in metrics:
            pn = _prometheus_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {pn} counter")
                lines.append(f"{pn} {_prometheus_value(m.value)}")
            elif isinstance(m, Gauge):
                if not m.is_set:
                    continue
                lines.append(f"# TYPE {pn} gauge")
                lines.append(f"{pn} {_prometheus_value(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {pn} histogram")
                running = 0
                for bound, c in zip(m.bounds, m._counts):
                    running += c
                    lines.append(f'{pn}_bucket{{le="{bound:g}"}} {running}')
                lines.append(f'{pn}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{pn}_sum {_prometheus_value(m.sum)}")
                lines.append(f"{pn}_count {m.count}")
        return "\n".join(lines) + ("\n" if lines else "")


def json_safe_float(value: float) -> Any:
    """Strict-JSON spelling of one float: non-finite values become the
    strings ``"NaN"``/``"Infinity"``/``"-Infinity"`` (the crash-dump
    contract — ``json.dump(..., allow_nan=False)`` then round-trips)."""
    value = float(value)
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "Infinity" if value > 0 else "-Infinity"
    return value


def json_float(value: Any) -> float:
    """Inverse of :func:`json_safe_float`: accepts the string spellings
    back (``float("NaN")``/``float("Infinity")`` parse them natively)."""
    return float(value)


def _restore_histogram(hist: Histogram, doc: dict) -> None:
    """Overwrite ``hist``'s internal state from a serialized dict whose
    ``bounds`` already match (``from_dict`` creates it that way)."""
    counts = [int(c) for c in doc["counts"]]
    if len(counts) != len(hist.bounds) + 1:
        raise ValueError(
            f"histogram {hist.name!r}: {len(counts)} counts for "
            f"{len(hist.bounds)} bounds (+1 overflow expected)")
    hist._counts = counts
    hist._sum = json_float(doc["sum"])
    hist._count = int(doc["count"])
    hist._min = json_float(doc["min"])
    hist._max = json_float(doc["max"])


_PROM_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _prometheus_name(name: str) -> str:
    pn = _PROM_BAD_CHARS.sub("_", name)
    return "_" + pn if pn[:1].isdigit() else pn


def _prometheus_value(value: float) -> str:
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return f"{value:g}"


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT
