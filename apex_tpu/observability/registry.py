"""Host-side metrics registry: counters, gauges, fixed-bucket histograms.

These are plain Python accumulators for *host-observed* quantities —
compile counts, queue depths, sampled device memory — the complement of the
in-graph accumulators (:mod:`~apex_tpu.observability.ingraph`) that live
inside the traced step. A :class:`MetricsRegistry` is a named collection
whose :meth:`~MetricsRegistry.snapshot` flattens everything to
``{name: float}`` for the sinks; the module-level default registry
(:func:`get_registry`) is what the pre-wired runtime listeners write to.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "DEFAULT_BUCKETS"]

# power-of-4 spread from sub-millisecond to minutes — wide enough for both
# durations (seconds) and sizes (use explicit buckets for bytes)
DEFAULT_BUCKETS: Tuple[float, ...] = tuple(4.0 ** e for e in range(-6, 6))


class Metric:
    """Base: a named observable. ``observe`` is the uniform write API so
    call sites can hold any metric kind."""

    def __init__(self, name: str):
        self.name = name

    def observe(self, value: float) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def snapshot(self) -> Dict[str, float]:  # pragma: no cover - abstract
        raise NotImplementedError

    def reset(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(Metric):
    """Monotonic accumulator. ``observe(v)`` adds ``v`` (default usage is
    :meth:`inc`)."""

    def __init__(self, name: str):
        super().__init__(name)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self._value += amount

    observe = inc

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict[str, float]:
        return {self.name: self._value}

    def reset(self) -> None:
        self._value = 0.0


class Gauge(Metric):
    """Last-value metric. ``observe``/``set`` overwrite.

    "Never set" is tracked with an explicit flag, NOT a NaN sentinel: the
    health watchdog legitimately reports NaN-valued gauges (a NaN abs-max
    IS the signal), and a sentinel would silently swallow them. ``value``
    still reads NaN when unset, so numeric consumers need no branch; the
    registry snapshot skips unset gauges via :attr:`is_set`.
    """

    def __init__(self, name: str):
        super().__init__(name)
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    observe = set

    @property
    def is_set(self) -> bool:
        return self._value is not None

    @property
    def value(self) -> float:
        return math.nan if self._value is None else self._value

    def snapshot(self) -> Dict[str, float]:
        return {self.name: self.value}

    def reset(self) -> None:
        self._value = None


class Histogram(Metric):
    """Fixed-bucket histogram (Prometheus-style cumulative ``le`` buckets).

    ``observe(v)`` increments the first bucket whose upper bound admits
    ``v``; the snapshot carries per-bucket counts plus ``_count``/``_sum``
    so sinks can derive means without keeping samples.
    """

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)  # +1 = overflow (+inf)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        self._counts[bisect.bisect_left(self.bounds, value)] += 1
        self._sum += value
        self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self) -> Dict[str, int]:
        """Cumulative counts, honoring the Prometheus ``le`` contract:
        ``..._bucket_le_B`` is the number of samples ``<= B``, and
        ``le_inf`` equals ``count``."""
        out = {}
        running = 0
        for bound, c in zip(self.bounds, self._counts):
            running += c
            out[f"{self.name}_bucket_le_{bound:g}"] = running
        out[f"{self.name}_bucket_le_inf"] = running + self._counts[-1]
        return out

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {f"{self.name}_count": float(self._count),
                                 f"{self.name}_sum": self._sum}
        out.update({k: float(v) for k, v in self.bucket_counts().items()})
        return out

    def reset(self) -> None:
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0


class MetricsRegistry:
    """Named collection with get-or-create accessors.

    Re-requesting a name returns the existing metric; requesting it as a
    different kind raises — a name means one thing for the whole run.
    """

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, kind, factory) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter, lambda: Counter(name))

    def gauge(self, name: str) -> Gauge:
        return self._get_or_create(name, Gauge, lambda: Gauge(name))

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram,
                                   lambda: Histogram(name, buckets))

    def names(self) -> Iterable[str]:
        return tuple(self._metrics)

    def snapshot(self) -> Dict[str, float]:
        """Flat ``{name: value}`` over every registered metric; gauges
        that were never set are skipped so sinks don't emit noise (a gauge
        explicitly set to NaN IS emitted — see :class:`Gauge`)."""
        out: Dict[str, float] = {}
        for m in self._metrics.values():
            if isinstance(m, Gauge) and not m.is_set:
                continue
            out.update(m.snapshot())
        return out

    def reset(self) -> None:
        for m in self._metrics.values():
            m.reset()


_DEFAULT: Optional[MetricsRegistry] = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created on first use)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT
