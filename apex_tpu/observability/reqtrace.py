"""Request-lifecycle tracing for the serving stack.

The training side measures *steps*; the unit a serving user experiences
is a *request*, and its cost decomposes into phases no aggregate counter
can recover after the fact: how long it queued, how long until its first
token (TTFT), how fast the tokens streamed after that (TPOT), and when
it retired. This module is the per-request counterpart of the PR 6 step
attribution discipline:

- :class:`RequestRecord` — one request's span record: submit / admit /
  prefill-done / first-token / decode-tick / retire timestamps
  (``time.perf_counter`` seconds, the same clock as
  :mod:`~apex_tpu.observability.trace` spans so the two compose in one
  Chrome trace), slot id, prompt/generated lengths, finish reason, and
  the derived ``queue_wait_ms`` / ``ttft_ms`` / ``tpot_ms`` / ``e2e_ms``
  latencies;
- :class:`RequestTrace` — a bounded, thread-safe ring buffer of retired
  records (overflow evicts oldest), the flight recorder the
  :class:`~apex_tpu.observability.slo.SLOTracker` dumps from;
- :func:`chrome_request_trace` — strict-JSON Chrome-trace export: one
  swimlane (``tid``) per slot plus a queue lane, per-request flow events
  linking a request's queue wait to its slot residency, and optional
  per-decode-tick instants.

The capture itself lives in
:class:`~apex_tpu.serving.scheduler.SlotScheduler`: timestamps are
stamped unconditionally (one ``perf_counter`` per scheduler transition —
the whole hot-loop overhead), while the ring buffer, per-tick lists, and
the Chrome export only exist when a ``RequestTrace`` is attached.
Tracing never touches the device: the three AOT serving programs are
byte-identical with tracing on or off (asserted in
``tests/test_reqtrace.py``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import threading
from typing import Any, Dict, Iterable, List, Optional

from apex_tpu.observability.registry import log_buckets
from apex_tpu.observability.trace import trace_metadata

__all__ = ["RequestRecord", "RequestTrace", "chrome_request_trace",
           "LATENCY_BUCKETS_MS"]

# the serving latency grid: 10 µs .. 60 s in milliseconds, constant-ratio
# r = (6e4/1e-2)**(1/67) ~= 1.26 — percentile readouts carry at most ~26%
# relative error (one bucket; see Histogram.percentile), which separates
# a 20 ms TTFT from a 200 ms one while keeping snapshots bounded
LATENCY_BUCKETS_MS = log_buckets(1e-2, 6e4, 68)


def _ms(t0: Optional[float], t1: Optional[float]) -> Optional[float]:
    if t0 is None or t1 is None:
        return None
    return (t1 - t0) * 1e3


@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle. Timestamps are ``perf_counter`` seconds;
    every field after ``submit_t`` fills in as the request advances
    (``None`` = the transition has not happened). ``decode_ts`` is only
    populated when a :class:`RequestTrace` is attached to the scheduler —
    it is the per-token truth the Chrome export renders, not something
    the untraced hot loop should pay a list append for."""

    request_id: int
    prompt_len: int
    submit_t: float
    admit_t: Optional[float] = None
    prefill_done_t: Optional[float] = None
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    retire_t: Optional[float] = None
    slot: Optional[int] = None
    generated: int = 0
    finish_reason: Optional[str] = None
    decode_ts: List[float] = dataclasses.field(default_factory=list)

    # -- derived latencies (the serving SLO vocabulary) ---------------------

    @property
    def queue_wait_ms(self) -> Optional[float]:
        """Submit → admit: time spent waiting for a free slot."""
        return _ms(self.submit_t, self.admit_t)

    @property
    def ttft_ms(self) -> Optional[float]:
        """Submit → first sampled token (the prefill samples it), queue
        wait included — the latency a user perceives before output."""
        return _ms(self.submit_t, self.first_token_t)

    @property
    def tpot_ms(self) -> Optional[float]:
        """Mean time per output token *after* the first (None for
        single-token requests): steady-state streaming cadence."""
        if self.generated < 2:
            return None
        span = _ms(self.first_token_t, self.last_token_t)
        if span is None:
            return None
        return span / (self.generated - 1)

    @property
    def e2e_ms(self) -> Optional[float]:
        """Submit → retire: the whole request."""
        return _ms(self.submit_t, self.retire_t)

    def to_dict(self) -> Dict[str, Any]:
        """A JSON-safe dict (strict JSON: no NaN/inf values) carrying the
        raw stamps, the derived latencies, and the tick list — the shape
        the flight-recorder dump stores."""
        out: Dict[str, Any] = {
            "request_id": self.request_id,
            "prompt_len": self.prompt_len,
            "generated": self.generated,
            "slot": self.slot,
            "finish_reason": self.finish_reason,
            "submit_t": self.submit_t,
            "admit_t": self.admit_t,
            "prefill_done_t": self.prefill_done_t,
            "first_token_t": self.first_token_t,
            "last_token_t": self.last_token_t,
            "retire_t": self.retire_t,
            "queue_wait_ms": self.queue_wait_ms,
            "ttft_ms": self.ttft_ms,
            "tpot_ms": self.tpot_ms,
            "e2e_ms": self.e2e_ms,
            "decode_ts": list(self.decode_ts),
        }
        return {k: (None if isinstance(v, float) and not math.isfinite(v)
                    else v) for k, v in out.items()}


class RequestTrace:
    """Bounded thread-safe ring buffer of retired :class:`RequestRecord`
    objects. Appends past ``capacity`` evict the oldest record — a
    serving process traces forever in O(capacity) memory; drain (or dump)
    before eviction if you need everything."""

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)

    def append(self, record: RequestRecord) -> None:
        with self._lock:
            self._buf.append(record)

    def records(self) -> List[RequestRecord]:
        """Snapshot of the buffer, oldest first (non-destructive)."""
        with self._lock:
            return list(self._buf)

    def last(self, n: int) -> List[RequestRecord]:
        """The newest ``n`` records (all of them when fewer) — the
        flight-recorder window."""
        with self._lock:
            if n <= 0:
                return []
            return list(self._buf)[-n:]

    def drain(self) -> List[RequestRecord]:
        """Pop and return everything, oldest first. Safe to race with
        producers: each record comes out exactly once."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    def chrome_trace(self, pid: int = 0, ticks: bool = True) -> dict:
        return chrome_request_trace(self.records(), pid=pid, ticks=ticks)

    def write_chrome_trace(self, path, pid: int = 0,
                           ticks: bool = True) -> None:
        """Write the Chrome-trace JSON for the buffered records.
        ``allow_nan=False``: the file is strict JSON by construction, the
        PR 6 interop contract."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(pid=pid, ticks=ticks), f,
                      allow_nan=False)


def _span_args(r: RequestRecord) -> Dict[str, Any]:
    args: Dict[str, Any] = {"request_id": r.request_id,
                            "prompt_len": r.prompt_len,
                            "generated": r.generated}
    if r.finish_reason is not None:
        args["finish_reason"] = r.finish_reason
    for key in ("queue_wait_ms", "ttft_ms", "tpot_ms", "e2e_ms"):
        v = getattr(r, key)
        if v is not None and math.isfinite(v):
            args[key] = round(v, 3)
    return args


def chrome_request_trace(records: Iterable[RequestRecord], pid: int = 0,
                         ticks: bool = True) -> dict:
    """Chrome-trace (Perfetto-loadable) document for request records.

    Track layout: ``tid 0`` is the queue lane (one span per request,
    submit → admit), ``tid slot+1`` is that slot's swimlane (one span per
    request residency, admit → retire, latencies in ``args``), with a
    flow event (``ph="s"``/``"f"``) tying each request's queue span to
    its slot span so the viewer draws the handoff arrow. ``ticks=True``
    adds one instant per decode tick on the slot lane (only records
    captured with a :class:`RequestTrace` attached carry ticks).

    Timestamps are ``perf_counter``-derived microseconds — the same
    timebase as :func:`~apex_tpu.observability.trace.chrome_trace_events`
    spans and the ``ChromeTraceSink`` counters, so a host-step trace and
    a request trace line up when loaded together. Cross-PROCESS
    alignment rides the ``metadata.epoch_offset_s`` stamp (see
    :func:`~apex_tpu.observability.trace.merge_chrome_traces`): two
    ranks' perf_counter zero points are unrelated, and the offset is
    what recovers a shared timeline. The returned document is strict
    JSON (round-trips ``json.loads``; asserted in tests).
    """
    records = list(records)
    events: List[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": "apex_tpu serving"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 0,
         "args": {"name": "queue"}},
    ]
    for slot in sorted({r.slot for r in records if r.slot is not None}):
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": slot + 1, "args": {"name": f"slot {slot}"}})
    for r in records:
        rid = r.request_id
        if r.admit_t is not None:
            events.append({"name": f"req {rid} queued", "ph": "X",
                           "cat": "serve", "ts": r.submit_t * 1e6,
                           "dur": (r.admit_t - r.submit_t) * 1e6,
                           "pid": pid, "tid": 0,
                           "args": {"request_id": rid}})
        end = r.retire_t if r.retire_t is not None else r.last_token_t
        if r.admit_t is None or end is None or r.slot is None:
            continue  # still queued / mid-flight: no slot span yet
        tid = r.slot + 1
        events.append({"name": f"req {rid}", "ph": "s", "cat": "serve",
                       "id": rid, "ts": r.submit_t * 1e6, "pid": pid,
                       "tid": 0})
        events.append({"name": f"req {rid}", "ph": "f", "bp": "e",
                       "cat": "serve", "id": rid, "ts": r.admit_t * 1e6,
                       "pid": pid, "tid": tid})
        events.append({"name": f"req {rid}", "ph": "X", "cat": "serve",
                       "ts": r.admit_t * 1e6,
                       "dur": (end - r.admit_t) * 1e6, "pid": pid,
                       "tid": tid, "args": _span_args(r)})
        if r.first_token_t is not None:
            events.append({"name": "first_token", "ph": "i", "s": "t",
                           "cat": "serve", "ts": r.first_token_t * 1e6,
                           "pid": pid, "tid": tid,
                           "args": {"request_id": rid}})
        if ticks:
            for t in r.decode_ts:
                events.append({"name": "tick", "ph": "i", "s": "t",
                               "cat": "serve", "ts": t * 1e6, "pid": pid,
                               "tid": tid, "args": {"request_id": rid}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": trace_metadata()}
