"""Runtime introspection: compile/recompile counters and memory gauges.

Two classes of regressions are invisible in a loss curve until they ruin a
run: a *recompilation storm* (a shape or static-arg leak retracing the
step every iteration) and *HBM growth* (fragmentation or a leaked
reference creeping toward OOM). Both have first-class signals in JAX:

- ``jax.monitoring`` events — every trace/lower/backend-compile records a
  duration event; :func:`install_compile_listeners` turns them into
  registry counters (``jax/compiles``, ``jax/traces``) and a compile-time
  histogram, so ``jax/compiles`` climbing after warmup IS the storm;
- ``Device.memory_stats()`` — :func:`sample_memory_stats` snapshots
  ``bytes_in_use``/``peak_bytes_in_use`` per local device into gauges
  (skipping backends that expose no stats, e.g. CPU).

Both write to the default registry, so an attached
:class:`~apex_tpu.observability.report.StepReporter` folds them into the
same per-step stream as the in-graph metrics.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from apex_tpu.observability.registry import MetricsRegistry, get_registry

__all__ = ["install_compile_listeners", "uninstall_compile_listeners",
           "reset_compile_listeners", "sample_memory_stats"]

# jax.monitoring event suffixes -> counter names. Matched by suffix so the
# '/jax/core/compile/...' prefix may move between jax versions without
# silently zeroing the counters.
_DURATION_COUNTERS = {
    "backend_compile_duration": "jax/compiles",
    "jaxpr_trace_duration": "jax/traces",
}

# ``jax.monitoring`` offers no per-listener deregistration, so exactly ONE
# process-wide dispatcher is ever registered with jax; it fans out to the
# currently-installed registries. Installing registers a target (idempotent
# per registry object), uninstalling removes it — repeated
# install/uninstall lifecycles (e.g. one per StepReporter session, or per
# test) can no longer accumulate orphaned listeners that double-count
# ``jax/compiles`` into a registry forever.
_TARGETS = []           # [(registry, {suffix: counter}, compile_histogram)]
_DISPATCHER_ON = False


def _dispatch(event: str, duration: float, **kw) -> None:
    for _reg, counters, compile_s in list(_TARGETS):
        for suffix, counter in counters.items():
            if event.endswith(suffix):
                counter.inc()
                if suffix == "backend_compile_duration":
                    compile_s.observe(duration)


def install_compile_listeners(
        registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Feed ``registry`` from ``jax.monitoring`` duration events.

    Idempotent per registry object — double-installing never
    double-counts. :func:`uninstall_compile_listeners` undoes it. Returns
    the registry for chaining.
    """
    global _DISPATCHER_ON
    reg = registry if registry is not None else get_registry()
    if any(r is reg for r, _, _ in _TARGETS):
        return reg
    compile_s = reg.histogram("jax/compile_seconds")
    counters = {suffix: reg.counter(name)
                for suffix, name in _DURATION_COUNTERS.items()}
    _TARGETS.append((reg, counters, compile_s))
    if not _DISPATCHER_ON:
        jax.monitoring.register_event_duration_secs_listener(_dispatch)
        _DISPATCHER_ON = True
    return reg


def uninstall_compile_listeners(
        registry: Optional[MetricsRegistry] = None) -> bool:
    """Stop feeding ``registry`` (default: the process default registry).
    Returns True when it was installed. The jax-level dispatcher stays
    registered (jax offers no deregistration) but dispatches to nothing
    for this registry — its counters keep their values and stop moving."""
    reg = registry if registry is not None else get_registry()
    for i, (r, _, _) in enumerate(_TARGETS):
        if r is reg:
            del _TARGETS[i]
            return True
    return False


def reset_compile_listeners() -> None:
    """Detach every installed registry (for tests)."""
    del _TARGETS[:]


_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def sample_memory_stats(
        registry: Optional[MetricsRegistry] = None) -> Dict[str, float]:
    """Gauge-sample allocator stats from every local device.

    Returns (and stores in ``registry``) ``memory/<key>/device<i>`` for
    each stat the backend exposes; backends without ``memory_stats()``
    (CPU) contribute nothing. Call once per report interval — it is a
    host-side query, not a device sync.
    """
    reg = registry if registry is not None else get_registry()
    out: Dict[str, float] = {}
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in _MEM_KEYS:
            if key in stats:
                name = f"memory/{key}/device{i}"
                reg.gauge(name).set(stats[key])
                out[name] = float(stats[key])
    return out
