"""Runtime introspection: compile/recompile counters and memory gauges.

Two classes of regressions are invisible in a loss curve until they ruin a
run: a *recompilation storm* (a shape or static-arg leak retracing the
step every iteration) and *HBM growth* (fragmentation or a leaked
reference creeping toward OOM). Both have first-class signals in JAX:

- ``jax.monitoring`` events — every trace/lower/backend-compile records a
  duration event; :func:`install_compile_listeners` turns them into
  registry counters (``jax/compiles``, ``jax/traces``) and a compile-time
  histogram, so ``jax/compiles`` climbing after warmup IS the storm;
- ``Device.memory_stats()`` — :func:`sample_memory_stats` snapshots
  ``bytes_in_use``/``peak_bytes_in_use`` per local device into gauges
  (skipping backends that expose no stats, e.g. CPU).

Both write to the default registry, so an attached
:class:`~apex_tpu.observability.report.StepReporter` folds them into the
same per-step stream as the in-graph metrics.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax

from apex_tpu.observability.registry import MetricsRegistry, get_registry

__all__ = ["install_compile_listeners", "sample_memory_stats"]

# jax.monitoring event suffixes -> counter names. Matched by suffix so the
# '/jax/core/compile/...' prefix may move between jax versions without
# silently zeroing the counters.
_DURATION_COUNTERS = {
    "backend_compile_duration": "jax/compiles",
    "jaxpr_trace_duration": "jax/traces",
}

_installed_registries = []


def install_compile_listeners(
        registry: Optional[MetricsRegistry] = None) -> MetricsRegistry:
    """Register ``jax.monitoring`` listeners feeding ``registry``.

    Idempotent per registry (``jax.monitoring`` offers no per-listener
    deregistration, so double-installing would double-count). Returns the
    registry for chaining.
    """
    reg = registry if registry is not None else get_registry()
    if any(r is reg for r in _installed_registries):
        return reg
    _installed_registries.append(reg)

    compile_s = reg.histogram("jax/compile_seconds")
    counters = {suffix: reg.counter(name)
                for suffix, name in _DURATION_COUNTERS.items()}
    compiles = counters["backend_compile_duration"]

    def on_duration(event: str, duration: float, **kw) -> None:
        for suffix, counter in counters.items():
            if event.endswith(suffix):
                counter.inc()
                if counter is compiles:
                    compile_s.observe(duration)

    jax.monitoring.register_event_duration_secs_listener(on_duration)
    return reg


_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def sample_memory_stats(
        registry: Optional[MetricsRegistry] = None) -> Dict[str, float]:
    """Gauge-sample allocator stats from every local device.

    Returns (and stores in ``registry``) ``memory/<key>/device<i>`` for
    each stat the backend exposes; backends without ``memory_stats()``
    (CPU) contribute nothing. Call once per report interval — it is a
    host-side query, not a device sync.
    """
    reg = registry if registry is not None else get_registry()
    out: Dict[str, float] = {}
    for i, dev in enumerate(jax.local_devices()):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for key in _MEM_KEYS:
            if key in stats:
                name = f"memory/{key}/device{i}"
                reg.gauge(name).set(stats[key])
                out[name] = float(stats[key])
    return out
