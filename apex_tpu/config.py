"""Unified training config tree.

The reference carries three config systems (SURVEY §5): amp's ``Properties``
policy object (``reference:apex/amp/frontend.py:7-97``), the 808-line
Megatron argparse namespace (``reference:apex/transformer/testing/
arguments.py`` + process-global ``get_args()``), and setup.py build flags.
Here they collapse into one typed dataclass tree with plain constructors —
no globals, no argparse, no feature-detect imports (every op has an XLA
path; Pallas selection is a runtime capability check).

``TrainConfig`` is the single object a trainer needs: it *builds* the
pieces (model, optimizer, policy, scaler, microbatch calculator, samplers)
rather than being threaded into them, so each subsystem keeps its explicit
functional API. ``to_dict``/``from_dict`` give a JSON-serializable form for
the checkpoint ``host_state`` sidecar.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

__all__ = ["ModelConfig", "ParallelConfig", "BatchConfig", "OptimizerConfig",
           "TrainConfig"]


def _asdict(obj) -> dict:
    return dataclasses.asdict(obj)


# optimizers with a ZeRO (DistributedFused*) variant — the single source
# for build_optimizer's zero dispatch and fastpath()'s capability check,
# so adding a variant cannot silently leave one of them stale
ZERO_CAPABLE_OPTIMIZERS = ("adam", "adamw", "lamb")


def _zero_enabled(v) -> bool:
    """Normalize ``OptimizerConfig.zero``: accepts the legacy bool plus the
    stage spelling (``"off" | 1 | "1"``) — ZeRO stage 1 (sharded optimizer
    state) is the only stage this library implements, so anything truthy
    beyond stage 1 is rejected loudly."""
    if v in (False, 0, None) or v == "off":
        return False
    if v in (True, 1) or v == "1":
        return True
    raise ValueError(
        f"unsupported zero={v!r}; expected off|1 (bools accepted)")


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Network-size args (``arguments.py`` ``_add_network_size_args``)."""
    name: str = "gpt"                 # "gpt" | "bert" | "resnet50"
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    ffn_hidden_size: Optional[int] = None
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    num_classes: int = 1000           # resnet head
    # Activation rematerialization (gpt/bert). ``remat_policy``:
    # None | "none" | "full" | "selective" | "offload" — the named-policy
    # knob (apex_tpu/remat.py; "selective" keeps GEMM/flash outputs
    # resident, recomputing only the cheap LN/gelu tier). ``remat: bool``
    # is the deprecated all-or-nothing spelling, honored (True -> "full",
    # with a DeprecationWarning) only while remat_policy is None.
    # ``remat_names``: custom save/offload list for the name-based modes
    # (members of remat.CHECKPOINT_NAMES).
    remat: bool = False
    remat_policy: Optional[str] = None
    remat_names: Optional[Tuple[str, ...]] = None
    # Megatron-LM sequence parallelism (gpt only; needs tp > 1, pp == 1;
    # through GPTHybridTrainer additionally needs VMA jax — the trainer
    # refuses on the pre-VMA 0.4.x line, see training.py)
    sequence_parallel: bool = False
    # ring-decomposed SP collectives overlapping their GEMMs (gpt only;
    # needs sequence_parallel — see tensor_parallel.collective_matmul)
    tp_comm_overlap: bool = False


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Mesh axes (``arguments.py`` ``_add_distributed_args`` /
    ``parallel_state.initialize_model_parallel``)."""
    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    context_parallel_size: int = 1
    # multi-host layout rule (parallel_state._dcn_device_grid): lay the
    # data axis outermost over the process (DCN) dimension, tp/pp/cp
    # strictly intra-process. None = auto (on exactly when the device
    # set spans >1 process); explicit True/False overrides.
    dcn_data_parallel: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """Batch sizing (``arguments.py`` ``_add_training_args`` +
    ``microbatches.py``)."""
    global_batch_size: int = 64
    micro_batch_size: int = 8
    rampup_batch_size: Optional[Tuple[int, int, int]] = None


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Optimizer selection (``arguments.py`` ``_add_learning_rate_args``)."""
    name: str = "adam"                # adam|adamw|sgd|lamb|novograd|adagrad
    lr: float = 1e-4
    weight_decay: float = 0.01
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    momentum: float = 0.9             # sgd
    flat: bool = False                # wrap in FlatOptimizer
    # ZeRO stage over the data axis: off | 1 (bools accepted) selects
    # DistributedFusedAdam/LAMB — optimizer state sharded 1/dp, grads
    # reduce-scattered, updated params all-gathered (per-bucket when
    # TrainConfig.ddp_bucket_bytes is set)
    zero: Any = False


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    model: ModelConfig = ModelConfig()
    parallel: ParallelConfig = ParallelConfig()
    batch: BatchConfig = BatchConfig()
    optimizer: OptimizerConfig = OptimizerConfig()
    opt_level: str = "O2"             # amp policy preset
    half_dtype: str = "bfloat16"
    seed: int = 1234
    # numerics watchdog (observability.health): in-graph instrumentation
    # tier ("off" is provably zero-cost) + host reaction to a non-finite
    # step ("skip" keeps amp's silent select-skip; "dump"/"raise" write a
    # structured CrashDump via the StepReporter health hook).
    # health_consecutive: fire raise/dump only after N non-finite reports
    # in a row — fp16 + dynamic loss scaling should set >= 2, because the
    # scaler's growth calibration overflows by design (see HealthConfig)
    health_level: str = "off"         # off | cheap | full
    health_on_nonfinite: str = "skip"  # raise | dump | skip
    health_consecutive: int = 1
    health_dump_dir: str = "."
    # DP gradient-sync bucketing (parallel/distributed.py bucketing
    # engine): bytes per flat fp32 bucket for the DDP allreduce and the
    # ZeRO reduce-scatter/all-gather. None = disabled — the trainer step
    # is provably identical to the pre-bucketing program (asserted on the
    # jaxpr, the same contract as health level="off"). "auto" = resolve
    # via the pyprof roofline (pyprof.tune_bucket_bytes: smallest bucket
    # whose RS+AG wire time hides under the modeled backward compute);
    # GPTHybridTrainer resolves it at construction and stores the
    # resolved int back into its config, so checkpoints/sidecars always
    # carry the concrete grid (the ZeRO bucket_stamp layout contract).
    ddp_bucket_bytes: Any = None

    # -- serialization ----------------------------------------------------
    def to_dict(self) -> dict:
        return _asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TrainConfig":
        d = dict(d)
        for field, sub in (("model", ModelConfig),
                           ("parallel", ParallelConfig),
                           ("batch", BatchConfig),
                           ("optimizer", OptimizerConfig)):
            if field in d and isinstance(d[field], dict):
                sub_d = dict(d[field])
                if field == "optimizer" and "betas" in sub_d:
                    sub_d["betas"] = tuple(sub_d["betas"])
                if field == "batch" and sub_d.get("rampup_batch_size"):
                    sub_d["rampup_batch_size"] = tuple(
                        sub_d["rampup_batch_size"])
                if field == "model" and sub_d.get("remat_names"):
                    sub_d["remat_names"] = tuple(sub_d["remat_names"])
                d[field] = sub(**sub_d)
        return cls(**d)

    # -- presets ----------------------------------------------------------
    _KEEP = object()   # fastpath() sentinel: no explicit bucket override

    def fastpath(self, *, bucket_bytes: Any = _KEEP) -> "TrainConfig":
        """The flagship compound-overlap preset, one declarative config:
        everything the overlap machinery can hide, turned on together —

        - ``zero=1`` — ZeRO-1 sharded optimizer with per-bucket
          backward-interleaved RS→math→AG chains
          (:mod:`apex_tpu.optimizers.distributed_fused`);
        - ``ddp_bucket_bytes`` — the bucket grid those chains pipeline
          over; a grid already set on the receiver is KEPT (it is a
          checkpoint-layout property), an unset one defaults to
          ``"auto"`` (roofline-tuned,
          :func:`apex_tpu.pyprof.tune_bucket_bytes`); pass
          ``bucket_bytes=`` to pin it explicitly;
        - ``remat_policy="selective"`` — GEMM/flash outputs resident,
          only the cheap LN/gelu tier recomputed (apex_tpu/remat.py);
        - ``sequence_parallel`` + ``tp_comm_overlap`` — ring-decomposed
          TP collectives riding under their GEMMs — when the mesh can
          carry them: ``tp > 1``, ``pp == 1`` (the SP head/stage
          contract) and VMA jax (``GPTHybridTrainer`` refuses SP on the
          pre-VMA 0.4.x line; the preset degrades to plain TP there
          rather than constructing a trainer that would refuse).

        Donation is the trainer-call half of the preset —
        ``jit_train_step(donate=True)`` is already the default. Returns
        a new config; the receiver is unchanged. Explicit model-level
        SP/overlap or remat settings on the receiver are kept as-is.
        Raises for optimizers with no ZeRO variant (sgd/novograd/...).
        """
        from apex_tpu.utils.compat import HAS_VMA
        if not _zero_enabled(self.optimizer.zero) \
                and self.optimizer.name not in ZERO_CAPABLE_OPTIMIZERS:
            raise ValueError(
                f"fastpath needs a ZeRO-capable optimizer "
                f"({'|'.join(ZERO_CAPABLE_OPTIMIZERS)}), got "
                f"{self.optimizer.name!r}")
        tp = self.parallel.tensor_model_parallel_size
        pp = self.parallel.pipeline_model_parallel_size
        sp_ok = tp > 1 and pp == 1 and HAS_VMA
        # the deprecated remat=True spelling means "full" (ModelConfig
        # docs) — a receiver that asked for full remat keeps it; only a
        # genuinely-unset policy defaults to selective
        policy = self.model.remat_policy or (
            "full" if self.model.remat else "selective")
        model = dataclasses.replace(
            self.model,
            remat_policy=policy,
            sequence_parallel=self.model.sequence_parallel or sp_ok,
            tp_comm_overlap=self.model.tp_comm_overlap or sp_ok)
        optimizer = (self.optimizer if _zero_enabled(self.optimizer.zero)
                     else dataclasses.replace(self.optimizer, zero=1))
        if bucket_bytes is TrainConfig._KEEP:
            bucket_bytes = (self.ddp_bucket_bytes
                            if self.ddp_bucket_bytes is not None
                            else "auto")
        return dataclasses.replace(self, model=model, optimizer=optimizer,
                                   ddp_bucket_bytes=bucket_bytes)

    # -- builders ---------------------------------------------------------
    def build_policy(self):
        import jax.numpy as jnp

        from apex_tpu.amp import get_policy
        half = jnp.bfloat16 if self.half_dtype == "bfloat16" else jnp.float16
        return get_policy(self.opt_level, half_dtype=half)

    def build_scaler(self):
        """Loss-scale object implied by the policy (may be a no-op)."""
        from apex_tpu.amp import make_loss_scale
        return make_loss_scale(self.build_policy().loss_scale)

    def build_health(self):
        """The numerics-watchdog policy (level "off" by default — the
        provably-free tier)."""
        from apex_tpu.observability.health import HealthConfig
        return HealthConfig(level=self.health_level,
                            on_nonfinite=self.health_on_nonfinite,
                            consecutive=self.health_consecutive,
                            dump_dir=self.health_dump_dir)

    def build_model(self):
        import jax.numpy as jnp

        pol = self.build_policy()
        m = self.model
        if m.name == "gpt":
            from apex_tpu.models import GPTConfig, GPTModel
            return GPTModel(GPTConfig(
                vocab_size=m.vocab_size, hidden_size=m.hidden_size,
                num_layers=m.num_layers,
                num_attention_heads=m.num_attention_heads,
                max_position_embeddings=m.max_position_embeddings,
                ffn_hidden_size=m.ffn_hidden_size,
                tensor_model_parallel_size=
                self.parallel.tensor_model_parallel_size,
                params_dtype=pol.param_dtype,
                compute_dtype=pol.compute_dtype,
                hidden_dropout=m.hidden_dropout,
                attention_dropout=m.attention_dropout, remat=m.remat,
                remat_policy=m.remat_policy, remat_names=m.remat_names,
                sequence_parallel=m.sequence_parallel,
                tp_comm_overlap=m.tp_comm_overlap))
        if m.name == "bert":
            from apex_tpu.models import BertConfig, BertModel
            return BertModel(BertConfig(
                vocab_size=m.vocab_size, hidden_size=m.hidden_size,
                num_layers=m.num_layers,
                num_attention_heads=m.num_attention_heads,
                max_position_embeddings=m.max_position_embeddings,
                remat=m.remat, remat_policy=m.remat_policy,
                remat_names=m.remat_names,
                compute_dtype=pol.compute_dtype))
        if m.name == "resnet50":
            from apex_tpu.models import ResNet50, ResNetConfig
            return ResNet50(ResNetConfig(
                num_classes=m.num_classes, compute_dtype=pol.compute_dtype,
                params_dtype=pol.param_dtype))
        raise ValueError(f"unknown model {m.name!r}")

    def build_optimizer(self):
        from apex_tpu import optimizers as opt

        o = self.optimizer
        if _zero_enabled(o.zero):
            if self.ddp_bucket_bytes == "auto":
                # the roofline resolution needs a model + mesh to price;
                # GPTHybridTrainer owns it (and stores the resolved int
                # back into its config). A raw build cannot guess a grid
                # silently — bucket_bytes is a checkpoint-layout property.
                raise ValueError(
                    'ddp_bucket_bytes="auto" must be resolved before '
                    "build_optimizer: construct the trainer "
                    "(GPTHybridTrainer resolves it via "
                    "apex_tpu.pyprof.tune_bucket_bytes) or call "
                    "tune_bucket_bytes yourself and pass the int")
            if o.name in ("adam", "adamw"):
                return opt.DistributedFusedAdam(
                    lr=o.lr, betas=o.betas, eps=o.eps,
                    adam_w_mode=o.name == "adamw",
                    weight_decay=o.weight_decay,
                    bucket_bytes=self.ddp_bucket_bytes)
            if o.name == "lamb":
                return opt.DistributedFusedLAMB(
                    lr=o.lr, betas=o.betas, eps=o.eps,
                    weight_decay=o.weight_decay,
                    bucket_bytes=self.ddp_bucket_bytes)
            # dispatch above covers exactly ZERO_CAPABLE_OPTIMIZERS —
            # extend both together (fastpath() gates on the same tuple)
            raise ValueError(
                f"no ZeRO variant of {o.name!r} (capable: "
                f"{'|'.join(ZERO_CAPABLE_OPTIMIZERS)})")
        if o.name in ("adam", "adamw"):
            inner = opt.FusedAdam(lr=o.lr, betas=o.betas, eps=o.eps,
                                  adam_w_mode=o.name == "adamw",
                                  weight_decay=o.weight_decay)
        elif o.name == "sgd":
            inner = opt.FusedSGD(lr=o.lr, momentum=o.momentum,
                                 weight_decay=o.weight_decay)
        elif o.name == "lamb":
            inner = opt.FusedLAMB(lr=o.lr, betas=o.betas, eps=o.eps,
                                  weight_decay=o.weight_decay)
        elif o.name == "novograd":
            inner = opt.FusedNovoGrad(lr=o.lr, betas=o.betas, eps=o.eps,
                                      weight_decay=o.weight_decay)
        elif o.name == "adagrad":
            inner = opt.FusedAdagrad(lr=o.lr,
                                     weight_decay=o.weight_decay)
        else:
            raise ValueError(f"unknown optimizer {o.name!r}")
        return opt.FlatOptimizer(inner) if o.flat else inner

    def build_microbatch_calculator(self, data_parallel_size: int):
        from apex_tpu.transformer.pipeline_parallel.microbatches import (
            build_num_microbatches_calculator)
        ram = (list(self.batch.rampup_batch_size)
               if self.batch.rampup_batch_size else None)
        return build_num_microbatches_calculator(
            rank=0, rampup_batch_size=ram,
            global_batch_size=self.batch.global_batch_size,
            micro_batch_size=self.batch.micro_batch_size,
            data_parallel_size=data_parallel_size)

    def build_sampler(self, total_samples: int, consumed_samples: int,
                      data_parallel_rank: int, data_parallel_size: int,
                      shuffle: bool = False):
        from apex_tpu.transformer._data import (
            MegatronPretrainingRandomSampler, MegatronPretrainingSampler)
        local = self.batch.global_batch_size // data_parallel_size
        cls = (MegatronPretrainingRandomSampler if shuffle
               else MegatronPretrainingSampler)
        return cls(total_samples=total_samples,
                   consumed_samples=consumed_samples,
                   local_minibatch_size=local,
                   data_parallel_rank=data_parallel_rank,
                   data_parallel_size=data_parallel_size)

    def initialize_mesh(self, devices=None):
        from apex_tpu.transformer import parallel_state
        return parallel_state.initialize_model_parallel(
            tensor_model_parallel_size=
            self.parallel.tensor_model_parallel_size,
            pipeline_model_parallel_size=
            self.parallel.pipeline_model_parallel_size,
            virtual_pipeline_model_parallel_size=
            self.parallel.virtual_pipeline_model_parallel_size,
            context_parallel_size=self.parallel.context_parallel_size,
            devices=devices,
            dcn_data_parallel=self.parallel.dcn_data_parallel)
