"""Mixed-precision policies: the TPU-native equivalent of apex.amp opt levels.

The reference implements mixed precision by monkey-patching the torch namespace
(O1) or casting the model in place and maintaining fp32 master weights behind a
patched ``optimizer.step`` (O2/O3) — see ``reference:apex/amp/frontend.py:102-191``
for the O0–O3 policy objects and ``reference:apex/amp/_initialize.py:145-263`` for
how they are applied.

On TPU none of that machinery is needed: a functional train step lets the policy
be three dtypes (param / compute / output) plus two flags, applied by tree-mapping
casts at well-defined boundaries. "Master weights" (O2) are simply fp32 params
cast to the compute dtype at use; XLA fuses the casts into the consuming ops, so
there is no separate fp16 weight copy to keep in sync and no state_dict hook is
needed to save fp32 (params *are* fp32 — cf. ``reference:apex/amp/_initialize.py:133-142``).

The default half dtype on TPU is bfloat16: same exponent range as fp32, so the
O1/O2 distinction (and most of the loss-scaling machinery) matters mainly for
float16, which we still support for parity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

__all__ = [
    "Policy",
    "O0",
    "O1",
    "O2",
    "O3",
    "get_policy",
    "cast_to_compute",
    "cast_to_param",
    "cast_to_output",
    "cast_floating",
    "with_policy",
]

def _is_float_array(x: Any) -> bool:
    # Non-floating leaves (ints, bools, PRNG keys) are left untouched by casts.
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


@dataclasses.dataclass(frozen=True)
class Policy:
    """A mixed-precision policy.

    Mirrors the knobs of ``reference:apex/amp/frontend.py:7-97`` (``Properties``:
    cast_model_type / patch_torch_functions / keep_batchnorm_fp32 /
    master_weights / loss_scale), reshaped for a functional framework:

    Attributes:
      name: display name ("O0".."O3" or custom).
      param_dtype: dtype in which parameters (and optimizer state) are stored.
      compute_dtype: dtype in which matmuls/convs run. Casting params to this
        at use-site is the whole of "O1 patching" on TPU.
      output_dtype: dtype of model outputs (losses are always accumulated fp32).
      keep_norms_fp32: run Layer/Batch/RMS norms' reductions and params in fp32
        (equivalent of ``keep_batchnorm_fp32``).
      loss_scale: None (no scaling), a float (static scale), or "dynamic".
    """

    name: str = "O0"
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32
    keep_norms_fp32: bool = True
    loss_scale: Union[None, float, str] = None

    @property
    def uses_master_weights(self) -> bool:
        """True when params are stored wider than compute (the O2 pattern)."""
        return jnp.dtype(self.param_dtype) != jnp.dtype(self.compute_dtype)

    @property
    def uses_dynamic_scaling(self) -> bool:
        return self.loss_scale == "dynamic"

    def replace(self, **kw) -> "Policy":
        return dataclasses.replace(self, **kw)


def O0() -> Policy:
    """Pure fp32 (reference: ``frontend.py:102-118``)."""
    return Policy(name="O0", param_dtype=jnp.float32, compute_dtype=jnp.float32,
                  output_dtype=jnp.float32, keep_norms_fp32=True, loss_scale=None)


def O1(half_dtype: Any = jnp.bfloat16) -> Policy:
    """Op-level mixed precision (reference: ``frontend.py:121-143``).

    fp32 params; matmul-class ops in half. On TPU this is the recommended
    default with bfloat16 (loss scaling unnecessary); with float16 pair it
    with dynamic loss scaling as the reference does.
    """
    scale = "dynamic" if jnp.dtype(half_dtype) == jnp.dtype(jnp.float16) else None
    return Policy(name="O1", param_dtype=jnp.float32, compute_dtype=half_dtype,
                  output_dtype=jnp.float32, keep_norms_fp32=True, loss_scale=scale)


def O2(half_dtype: Any = jnp.bfloat16) -> Policy:
    """"Almost half": half model + fp32 master weights (``frontend.py:146-168``).

    Functionally: params stored fp32 (the master copy *is* the param), compute
    and outputs in half, norms fp32.
    """
    scale = "dynamic" if jnp.dtype(half_dtype) == jnp.dtype(jnp.float16) else None
    return Policy(name="O2", param_dtype=jnp.float32, compute_dtype=half_dtype,
                  output_dtype=half_dtype, keep_norms_fp32=True, loss_scale=scale)


def O3(half_dtype: Any = jnp.bfloat16) -> Policy:
    """Pure half, speed baseline (``frontend.py:171-191``)."""
    return Policy(name="O3", param_dtype=half_dtype, compute_dtype=half_dtype,
                  output_dtype=half_dtype, keep_norms_fp32=False, loss_scale=None)


_OPT_LEVELS: dict = {"O0": O0, "O1": O1, "O2": O2, "O3": O3}


def get_policy(opt_level: Union[str, Policy], half_dtype: Any = jnp.bfloat16,
               **overrides) -> Policy:
    """Resolve an opt-level string to a Policy, applying kwarg overrides.

    Mirrors ``amp.initialize(opt_level=..., **overrides)``
    (``reference:apex/amp/frontend.py:195-358``): the preset is constructed
    first, then explicit overrides win.
    """
    if isinstance(opt_level, Policy):
        pol = opt_level
    else:
        try:
            factory = _OPT_LEVELS[opt_level.upper()]
        except KeyError:
            raise ValueError(
                f"Unexpected optimization level {opt_level!r}; options are "
                "'O0', 'O1', 'O2', 'O3'.") from None
        pol = factory() if opt_level.upper() == "O0" else factory(half_dtype)
    if overrides:
        pol = pol.replace(**overrides)
    return pol


def _cast_tree(tree: Any, dtype: Any) -> Any:
    dtype = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda x: x.astype(dtype) if _is_float_array(x) else x, tree)


def cast_to_compute(tree: Any, policy: Policy) -> Any:
    """Cast float leaves to the compute dtype (use-site cast of params/inputs)."""
    return _cast_tree(tree, policy.compute_dtype)


def cast_to_param(tree: Any, policy: Policy) -> Any:
    """Cast float leaves to the param/storage dtype (e.g. grads before update)."""
    return _cast_tree(tree, policy.param_dtype)


def cast_to_output(tree: Any, policy: Policy) -> Any:
    """Cast float leaves to the output dtype (patched-forward output cast,
    ``reference:apex/amp/_initialize.py:190-201``)."""
    return _cast_tree(tree, policy.output_dtype)


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Generic float-leaf cast (equivalent of ``network_to_half`` /
    ``convert_network``, ``reference:apex/fp16_utils/fp16util.py:35-80``)."""
    return _cast_tree(tree, dtype)


def with_policy(fn: Callable, policy: Policy,
                cast_inputs: bool = True) -> Callable:
    """Wrap a functional model apply: params+inputs→compute dtype, outputs→output dtype.

    The functional analog of the patched ``model.forward``
    (``reference:apex/amp/_initialize.py:190-201``).
    """

    def wrapped(params, *args, **kwargs):
        params = cast_to_compute(params, policy)
        if cast_inputs:
            args = cast_to_compute(args, policy)
            kwargs = cast_to_compute(kwargs, policy)
        out = fn(params, *args, **kwargs)
        return cast_to_output(out, policy)

    return wrapped
