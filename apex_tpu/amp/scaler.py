"""Loss scaling, fully on-device.

Reference semantics: ``reference:apex/amp/scaler.py:33-217`` — dynamic scale
starts at 2**16, halves on overflow, doubles after 2000 consecutive clean steps;
static scaling is a constant multiplier. The reference detects overflow with a
GPU->CPU ``.item()`` sync every iteration (``scaler.py:199-200``) and skips
``optimizer.step`` by monkey-patching it (``reference:apex/amp/handle.py:128-154``).

On TPU a host sync per step would stall the XLA pipeline, so the whole protocol
is expressed as a carried pytree + ``jnp.where``/``lax.cond``: the finite-check
is a fused reduction over the grad tree, the skip is a select between old and
new optimizer state. Bitwise-resumable: the state is two scalars, checkpointed
like any other pytree (cf. ``amp.state_dict``, ``reference:apex/amp/frontend.py:361-400``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from apex_tpu.observability import health as _health
from apex_tpu.observability import ingraph as _metrics

__all__ = [
    "LossScaleState",
    "DynamicLossScale",
    "StaticLossScale",
    "NoOpLossScale",
    "make_loss_scale",
    "all_finite",
    "select_tree",
    "scaled_value_and_grad",
]


class LossScaleState(NamedTuple):
    """Carried scaler state: ``(loss_scale, unskipped_steps)``.

    ``unskipped`` mirrors ``LossScaler._unskipped``
    (``reference:apex/amp/scaler.py:46,203-217``).
    """

    loss_scale: jnp.ndarray  # f32 scalar
    unskipped: jnp.ndarray   # i32 scalar


def all_finite(tree: Any, axis_names: Union[None, str, Sequence[str]] = None,
               observe: Optional[str] = "grads") -> jnp.ndarray:
    """Single fused bool: every float leaf in ``tree`` is finite.

    The equivalent of the ``noop_flag`` overflow buffer threaded through every
    ``multi_tensor_apply`` launch (``reference:csrc/multi_tensor_apply.cuh:19-26``,
    ``reference:apex/amp/scaler.py:94-124``) — except XLA fuses the isfinite
    reductions into the producing ops, so it costs no extra memory pass.

    When called inside ``shard_map`` with explicit model-parallel axes, pass
    ``axis_names`` to reduce the flag across the model-parallel group, matching
    ``transformer.amp.GradScaler`` (``reference:apex/transformer/amp/grad_scaler.py:38-49``).

    ``observe`` names the tree for the health watchdog (the amp grad-check
    default "grads" gives overflow steps per-leaf attribution); callers
    finite-checking a NON-gradient tree (``multi_tensor_apply`` outputs)
    must pass a distinct name or None, or their records would sum into —
    and mis-attribute — ``health/grads/*``.
    """
    # the health watchdog hangs off the same tree this check consumes, so
    # amp's overflow signal carries per-leaf attribution
    # (health/grads/first_nonfinite_leaf names the offending leaf) when a
    # policy is active — a trace-time-gated no-op otherwise
    if observe is not None:
        _health.observe_tree(tree, observe)
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)]
    if not leaves:
        finite = jnp.array(True)
    else:
        finite = jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()
    if axis_names:
        if isinstance(axis_names, str):
            axis_names = (axis_names,)
        for ax in axis_names:
            finite = jax.lax.pmin(finite.astype(jnp.int32), ax).astype(jnp.bool_)
    return finite


def select_tree(pred: jnp.ndarray, on_true: Any, on_false: Any) -> Any:
    """``jnp.where`` over matching pytrees — the on-device "skip step".

    Non-array leaves (Python scalars) are promoted with ``jnp.asarray`` so the
    select stays traceable under jit.
    """
    return jax.tree_util.tree_map(
        lambda t, f: jax.lax.select(pred, jnp.asarray(t), jnp.asarray(f)),
        on_true, on_false)


def _record_scale_metrics(scale: jnp.ndarray, grads_finite: jnp.ndarray) -> None:
    """Telemetry for every scale update — the structured replacement for
    the reference's ``maybe_print`` on overflow
    (``reference:apex/amp/scaler.py:204-217``). Thunked values: with no
    collector active this adds nothing to the traced program."""
    _metrics.record("amp/loss_scale",
                    lambda: scale.astype(jnp.float32), reduce="mean")
    overflowed = lambda: 1.0 - grads_finite.astype(jnp.float32)
    _metrics.record("amp/overflow_count", overflowed, reduce="sum")
    # the on-device select skips the whole optimizer step on overflow, so
    # per step these coincide; kept as separate series because static
    # scaling (no backoff) still skips, and sinks sum them independently
    _metrics.record("amp/skipped_steps", overflowed, reduce="max")


@dataclasses.dataclass(frozen=True)
class DynamicLossScale:
    """Dynamic loss scaling config (``reference:apex/amp/scaler.py:33-56``).

    init_scale 2**16, doubling every ``growth_interval`` clean steps, halving on
    overflow; optional min/max clamps mirror ``amp.initialize``'s
    min_loss_scale/max_loss_scale kwargs (``reference:apex/amp/frontend.py:195-254``).
    """

    init_scale: float = 2.0 ** 16
    growth_factor: float = 2.0
    backoff_factor: float = 0.5
    growth_interval: int = 2000
    min_scale: float = 1.0
    max_scale: float = 2.0 ** 24

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32))

    def scale(self, state: LossScaleState, tree: Any) -> Any:
        s = state.loss_scale

        def _scale(x):
            x = jnp.asarray(x)
            if jnp.issubdtype(x.dtype, jnp.floating):
                # widen sub-f32 dtypes for the multiply: the default 2**16
                # scale overflows fp16's max (65504) if cast to fp16 first;
                # f64 leaves keep their precision via promote_types
                wide = jnp.promote_types(x.dtype, jnp.float32)
                return (x.astype(wide) * s.astype(wide)).astype(x.dtype)
            return x

        return jax.tree_util.tree_map(_scale, tree)

    def unscale(self, state: LossScaleState, grads: Any, cast_to: Any = jnp.float32) -> Any:
        """fp32 unscale of a (possibly half) grad tree — the functional
        ``LossScaler.unscale`` (``reference:apex/amp/scaler.py:94-124``):
        grads are widened to ``cast_to`` *before* multiplying by 1/scale, the
        master-grad copy semantics of amp O2."""
        inv = (1.0 / state.loss_scale)

        def _unscale(g):
            g = jnp.asarray(g)
            if jnp.issubdtype(g.dtype, jnp.floating):
                return g.astype(cast_to) * inv
            return g

        return jax.tree_util.tree_map(_unscale, grads)

    def update(self, state: LossScaleState, grads_finite: jnp.ndarray) -> LossScaleState:
        """Scale update rule of ``reference:apex/amp/scaler.py:197-217``,
        branch-free on device."""
        grew = state.unskipped + 1 >= self.growth_interval
        scale_if_finite = jnp.where(
            grew,
            jnp.minimum(state.loss_scale * self.growth_factor, self.max_scale),
            state.loss_scale)
        unskipped_if_finite = jnp.where(grew, 0, state.unskipped + 1)
        new_scale = jnp.where(
            grads_finite, scale_if_finite,
            jnp.maximum(state.loss_scale * self.backoff_factor, self.min_scale))
        new_unskipped = jnp.where(grads_finite, unskipped_if_finite, 0)
        _record_scale_metrics(new_scale, grads_finite)
        return LossScaleState(loss_scale=new_scale,
                              unskipped=new_unskipped.astype(jnp.int32))


class StaticLossScale:
    """Constant loss scale (``reference:apex/fp16_utils/loss_scaler.py:10-44``).

    Not a dataclass: the scale *value* rides in ``init_scale`` so the
    ``scale(state, tree)`` method keeps the same protocol as
    :class:`DynamicLossScale` (a ``scale`` field would shadow it).
    """

    def __init__(self, scale: float = 1.0):
        self.init_scale = float(scale)

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.init_scale == other.init_scale)

    def __hash__(self):
        # keep hashability (the pre-refactor frozen dataclass had it): these
        # objects are legitimate jit static args / cache keys
        return hash((type(self), self.init_scale))

    def init(self) -> LossScaleState:
        return LossScaleState(
            loss_scale=jnp.asarray(self.init_scale, jnp.float32),
            unskipped=jnp.asarray(0, jnp.int32))

    def scale(self, state, tree):
        return DynamicLossScale.scale(self, state, tree)  # type: ignore[arg-type]

    def unscale(self, state, grads, cast_to=jnp.float32):
        return DynamicLossScale.unscale(self, state, grads, cast_to)  # type: ignore[arg-type]

    def update(self, state: LossScaleState, grads_finite: jnp.ndarray) -> LossScaleState:
        _record_scale_metrics(state.loss_scale, grads_finite)
        return state


class NoOpLossScale(StaticLossScale):
    """Scale of 1 and no overflow checking cost beyond the finite flag."""

    def __init__(self):
        super().__init__(scale=1.0)


def make_loss_scale(spec: Union[None, float, str],
                    **kwargs) -> Union[DynamicLossScale, StaticLossScale]:
    """Resolve a ``Policy.loss_scale`` spec ("dynamic" | float | None)."""
    if spec is None:
        return NoOpLossScale()
    if spec == "dynamic":
        return DynamicLossScale(**kwargs)
    scale = float(spec)
    if scale <= 0.0:
        raise ValueError(f"loss scale must be positive, got {scale}")
    return StaticLossScale(scale=scale)


def scaled_value_and_grad(
    fun: Callable,
    loss_scale: Union[DynamicLossScale, StaticLossScale],
    has_aux: bool = False,
    axis_names: Union[None, str, Sequence[str]] = None,
    grad_dtype: Any = jnp.float32,
):
    """The functional ``with amp.scale_loss(...) as scaled: scaled.backward()``
    (``reference:apex/amp/handle.py:16-158``).

    Returns ``step(state, params, *args) -> (value, aux, grads, grads_finite, new_state)``
    where ``grads`` are unscaled fp32 ("master") grads and ``new_state`` has the
    scale already adjusted. Callers gate their optimizer update on
    ``grads_finite`` via :func:`select_tree` — the traced equivalent of the
    patched skip-step.
    """

    def step(state: LossScaleState, params: Any, *args, **kwargs):
        def scaled_fun(p, *a, **k):
            out = fun(p, *a, **k)
            if has_aux:
                value, aux = out
            else:
                value, aux = out, None
            scaled = value.astype(jnp.float32) * state.loss_scale
            return scaled, (value, aux)

        (_, (value, aux)), grads = jax.value_and_grad(
            scaled_fun, has_aux=True)(params, *args, **kwargs)
        grads = loss_scale.unscale(state, grads, cast_to=grad_dtype)
        finite = all_finite(grads, axis_names=axis_names)
        new_state = loss_scale.update(state, finite)
        return value, aux, grads, finite, new_state

    return step
